"""Signature-keyed compiled-op cache for eager dispatch (ISSUE 2).

Covers the cache contract end to end: hit/miss per signature component,
LRU eviction, unhashable-static and closure-array bypass, AMP interaction,
grad-vs-no_grad keying, the env kill-switch, capture-seam bypass guards
(to_static / lazy segments / static-graph hook), the fused nan check,
observability counters, and byte-identical numerics cache-on vs cache-off.
Plus the satellites: thread-safe RemovableHandle ids, ``shape_tuple()``,
and the ``to_tensor`` committed-array dtype cast.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.core import dispatch_cache as dcache
from paddle_tpu.core import lazy as lazy_mod
from paddle_tpu.core import tensor as tensor_mod
from paddle_tpu.core.tensor import apply, to_tensor


@pytest.fixture(autouse=True)
def _cache_on():
    prev = (dcache._ENABLED, dcache._MAXSIZE, dcache._WARMUP)
    dcache.configure(enabled=True, maxsize=256, warmup=2)
    dcache.cache_clear()
    yield
    dcache.configure(enabled=prev[0], maxsize=prev[1], warmup=prev[2])
    dcache.cache_clear()


def _t(shape=(4, 4), dtype="float32", grad=False, seed=0):
    rng = np.random.RandomState(seed)
    return to_tensor(rng.randn(*shape).astype(dtype), stop_gradient=not grad)


# ---------------------------------------------------------------------------
# hit/miss semantics per signature component
# ---------------------------------------------------------------------------

def test_repeat_signature_hits_after_warmup():
    x = _t()
    y1 = x * 2.0                      # cold miss
    y2 = x * 2.0                      # warm miss -> compiled + served
    y3 = x * 2.0                      # hit
    info = dcache.cache_info()
    assert info["misses"] == 2 and info["compiles"] == 1
    assert info["hits"] == 1
    for y in (y2, y3):
        np.testing.assert_array_equal(np.asarray(y1._data),
                                      np.asarray(y._data))


def test_closure_scalar_is_part_of_the_key():
    x = _t()
    for _ in range(3):
        x * 2.0
    hits = dcache.cache_info()["hits"]
    y = x * 3.0                       # same op/avals, different closure const
    assert dcache.cache_info()["hits"] == hits  # no false hit
    np.testing.assert_array_equal(np.asarray(y._data),
                                  np.asarray(x._data) * 3.0)


def test_shape_dtype_and_static_kwargs_key_components():
    a = _t((4, 4))
    for _ in range(3):
        a + a
    hits = dcache.cache_info()["hits"]
    b = _t((2, 8))
    b + b                             # different shape: no hit
    c = to_tensor(np.ones((4, 4), np.int64))
    c + c                             # different dtype: no hit
    assert dcache.cache_info()["hits"] == hits

    def f(x, scale=1.0):
        return x * scale

    for _ in range(3):
        apply("tk_scale", f, a, scale=2.0)
    hits = dcache.cache_info()["hits"]
    assert hits >= 1
    out = apply("tk_scale", f, a, scale=4.0)   # static kwarg keys the entry
    assert dcache.cache_info()["hits"] == hits
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.asarray(a._data) * 4.0)


def test_grad_vs_no_grad_are_distinct_entries():
    x = _t(grad=True)
    with paddle.no_grad():
        for _ in range(3):
            y = x * 5.0
        assert y.stop_gradient
    compiles_ng = dcache.cache_info()["compiles"]
    assert compiles_ng == 1
    for _ in range(3):
        y = x * 5.0
    assert not y.stop_gradient
    assert dcache.cache_info()["compiles"] == 2  # separate grad-keyed entry
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._data), 5.0, rtol=0)


def test_warmup_one_compiles_on_first_sighting():
    dcache.configure(warmup=1)
    x = _t()
    y1 = x * 9.0
    info = dcache.cache_info()
    assert info["compiles"] == 1 and info["misses"] == 1
    y2 = x * 9.0
    assert dcache.cache_info()["hits"] == 1
    np.testing.assert_array_equal(np.asarray(y1._data), np.asarray(y2._data))


def test_lru_eviction_is_bounded_and_counted():
    dcache.configure(maxsize=4)
    x = _t()
    for k in range(6):
        for _ in range(3):
            x * float(k)
    info = dcache.cache_info()
    assert info["size"] <= 4
    assert info["evictions"] > 0


# ---------------------------------------------------------------------------
# bypass: unhashable statics, closure arrays, capture seams
# ---------------------------------------------------------------------------

def test_unhashable_static_kwarg_bypasses_uncached():
    x = _t()
    marker = {object()}  # a set of an unhashable-by-value object

    def f(a, tag=None):
        return a + 1.0

    out = apply("tk_unhash", f, x, tag=marker)
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.asarray(x._data) + 1.0)
    info = dcache.cache_info()
    assert info["bypass"].get("static_unhashable", 0) >= 1
    assert info["compiles"] == 0


def test_closure_array_bypasses_uncached():
    x = _t()
    table = np.arange(16, dtype=np.float32).reshape(4, 4)
    for _ in range(3):
        out = apply("tk_closure_arr", lambda a: a + table, x)
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.asarray(x._data) + table)
    info = dcache.cache_info()
    assert info["bypass"].get("closure_array", 0) >= 3
    assert info["compiles"] == 0


def test_hashless_callable_op_bypasses_uncached():
    class HashlessOp:
        __hash__ = None                     # e.g. a dataclass with __eq__

        def __eq__(self, other):
            return self is other

        def __call__(self, a):
            return a * 3.0

    x = _t()
    for _ in range(3):
        y = apply("tk_hashless", HashlessOp(), x)
    np.testing.assert_array_equal(np.asarray(y._data),
                                  np.asarray(x._data) * 3.0)
    info = dcache.cache_info()
    assert info["bypass"].get("static_unhashable", 0) >= 3
    assert info["compiles"] == 0


def test_mutable_list_closure_is_content_keyed_not_stale():
    x = to_tensor(np.arange(6, dtype=np.float32))
    shape = [2, 3]
    for _ in range(3):
        y = paddle.reshape(x, shape)
    assert y.shape_tuple() == (2, 3)
    shape2 = [3, 2]
    y = paddle.reshape(x, shape2)     # content differs -> new key, no stale hit
    assert y.shape_tuple() == (3, 2)


def test_fresh_partial_per_call_is_structurally_keyed():
    import functools

    def base(a, scale):
        return a * scale

    x = _t()
    for _ in range(3):                # a FRESH partial object every call
        y = apply("tk_partial", functools.partial(base, scale=2.0), x)
    info = dcache.cache_info()
    assert info["compiles"] == 1 and info["hits"] >= 1
    np.testing.assert_array_equal(np.asarray(y._data),
                                  np.asarray(x._data) * 2.0)
    apply("tk_partial", functools.partial(base, scale=5.0), x)
    assert dcache.cache_info()["hits"] == info["hits"]  # kwarg keys it


def test_identity_key_churn_cannot_evict_compiled_entries():
    # never-repeating signatures (fresh callable objects) live in the
    # pending table; their churn must not flush hot compiled entries
    dcache.configure(maxsize=8)
    x = _t()
    for _ in range(3):
        x * 42.0                      # hot compiled entry

    class FreshOp:
        def __call__(self, a):
            return a + 0.0

    for _ in range(30):               # 30 distinct identity-keyed misses
        apply("tk_churn", FreshOp(), x)
    hits = dcache.cache_info()["hits"]
    x * 42.0                          # still served compiled
    assert dcache.cache_info()["hits"] == hits + 1


def test_persistent_nontrace_compile_failure_poisons_after_retries():
    x = _t()

    def tracer_hater(a):
        # legal eagerly; a NON-jax error under jit tracing (a ValueError,
        # not ConcretizationTypeError) — retried a bounded number of
        # times, then poisoned
        if type(a).__mro__[0].__name__.endswith("Tracer"):
            raise ValueError("no tracers here")
        return a * 2.0

    for _ in range(6):
        out = apply("tk_valueerr", tracer_hater, x)
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.asarray(x._data) * 2.0)
    info = dcache.cache_info()
    assert info["bypass"].get("compile_retry", 0) == 3
    assert info["bypass"].get("untraceable", 0) >= 2  # poisoned thereafter
    assert info["compiles"] == 0


def test_to_static_capture_bypasses_cache_and_traces_once():
    calls = {"n": 0}

    @paddle.jit.to_static
    def f(v):
        calls["n"] += 1
        return (v * 2.0 + 1.0).sum()

    x = _t(grad=True)
    r1 = f(x)
    r2 = f(x)
    assert calls["n"] == 1            # traced once, replayed compiled
    np.testing.assert_array_equal(np.asarray(r1._data), np.asarray(r2._data))
    info = dcache.cache_info()
    assert info["hits"] == 0 and info["compiles"] == 0
    assert info["bypass"].get("capture", 0) >= 2  # the traced op dispatches


def test_lazy_segment_mode_bypasses_cache():
    x = _t()
    with lazy_mod.segment_mode():
        y = x * 2.0
        z = y + 1.0
        got = float(z.sum())          # concrete read flushes the segment
    want = float((np.asarray(x._data) * 2.0 + 1.0).sum())
    assert got == pytest.approx(want)
    info = dcache.cache_info()
    assert info["hits"] == 0 and info["compiles"] == 0
    assert info["bypass"].get("capture", 0) >= 3


def test_static_graph_hook_bypasses_cache_and_sees_every_op():
    recorded = []
    assert tensor_mod._op_graph_hook is None
    tensor_mod._op_graph_hook = \
        lambda name, f, ins, outs: recorded.append(name)
    try:
        x = _t()
        for _ in range(3):
            x * 2.0
    finally:
        tensor_mod._op_graph_hook = None
    assert recorded.count("multiply") == 3
    info = dcache.cache_info()
    assert info["hits"] == 0 and info["compiles"] == 0
    assert info["bypass"].get("capture", 0) >= 3


def test_symbolic_input_bypasses_cache():
    # a Tensor wrapping a live jax tracer (e.g. user-level jax.jit around
    # paddle ops) must never be baked into a cached executable
    seen = {}

    def jf(a):
        t = tensor_mod.Tensor(a)
        out = t * 2.0
        seen["info"] = dcache.cache_info()
        return out._data

    r = jax.jit(jf)(jnp.ones((3,)))
    np.testing.assert_array_equal(np.asarray(r), 2.0 * np.ones((3,)))
    assert seen["info"]["bypass"].get("symbolic_input", 0) >= 1
    assert seen["info"]["compiles"] == 0


def test_untraceable_fn_is_poisoned_not_retried():
    x = _t()

    def branchy(a):
        # legal eagerly, ConcretizationTypeError under jit tracing
        if float(jnp.sum(a)) > 1e9:
            return a * 0.0
        return a * 2.0

    for _ in range(4):
        out = apply("tk_branchy", branchy, x)
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.asarray(x._data) * 2.0)
    info = dcache.cache_info()
    assert info["compiles"] == 0 and info["hits"] == 0
    assert info["bypass"].get("untraceable", 0) >= 2  # poisoned after 1 try


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------

def test_disabled_cache_touches_nothing():
    dcache.configure(enabled=False)
    x = _t(grad=True)
    y = (x * 2.0).sum()
    y.backward()
    info = dcache.cache_info()
    assert info["hits"] == info["misses"] == info["compiles"] == 0
    assert info["bypass"] == {}
    assert not info["enabled"]


def test_env_flag_parsing(monkeypatch):
    for raw, want in (("0", False), ("false", False), ("off", False),
                      ("no", False), ("1", True), ("true", True), ("", True)):
        monkeypatch.setenv("PADDLE_TPU_EAGER_CACHE", raw)
        assert dcache._env_enabled() is want, raw
    monkeypatch.delenv("PADDLE_TPU_EAGER_CACHE")
    assert dcache._env_enabled() is True
    monkeypatch.setenv("PADDLE_TPU_EAGER_CACHE_SIZE", "64")
    assert dcache._env_int("PADDLE_TPU_EAGER_CACHE_SIZE", 1024) == 64
    monkeypatch.setenv("PADDLE_TPU_EAGER_CACHE_SIZE", "bogus")
    assert dcache._env_int("PADDLE_TPU_EAGER_CACHE_SIZE", 1024) == 1024


# ---------------------------------------------------------------------------
# numerics: cache-on vs cache-off must match bit for bit
# ---------------------------------------------------------------------------

def _model_loss_and_grads(x, w):
    y = paddle.matmul(x, w)
    y = paddle.nn.functional.relu(y)
    y = paddle.nn.functional.softmax(y, axis=-1)
    loss = (y * y).mean()
    loss.backward()
    gx = np.asarray(x.grad._data).copy()
    gw = np.asarray(w.grad._data).copy()
    x.clear_grad()
    w.clear_grad()
    return np.asarray(loss._data).copy(), gx, gw


def test_numerics_identical_cache_on_vs_off():
    x = _t((8, 16), grad=True, seed=1)
    w = _t((16, 16), grad=True, seed=2)
    dcache.configure(enabled=False)
    ref = _model_loss_and_grads(x, w)
    dcache.configure(enabled=True)
    for _ in range(3):  # cold, compiling, hot
        got = _model_loss_and_grads(x, w)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(r, g)
    assert dcache.cache_info()["hits"] > 0


def test_numerics_match_under_amp():
    # bf16 note: the cached path fuses cast+matmul in one XLA program while
    # the eager path runs them op-by-op, so bf16 rounding may differ at eps
    # scale (~8e-3); fp32 paths stay bit-exact (see the test above). The
    # cached path must still be deterministic call-to-call.
    x = _t((8, 16), grad=True, seed=3)
    w = _t((16, 16), grad=True, seed=4)

    def run():
        with paddle.amp.auto_cast(level="O1"):
            loss = paddle.matmul(x, w).sum()
        loss.backward()
        g = np.asarray(x.grad._data).copy()
        x.clear_grad()
        w.clear_grad()
        return np.asarray(loss._data).copy(), g

    dcache.configure(enabled=False)
    ref = run()
    dcache.configure(enabled=True)
    outs = [run() for _ in range(4)]  # cold, compiling, hot, hot
    for got in outs:
        for r, g in zip(ref, got):
            np.testing.assert_allclose(r, g, rtol=2e-2, atol=2e-2)
    for r, g in zip(outs[2], outs[3]):  # hot path: deterministic, bit-exact
        np.testing.assert_array_equal(r, g)
    info = dcache.cache_info()
    assert info["hits"] > 0


def test_amp_scope_keys_separately_from_plain():
    x = _t((4, 8), grad=False, seed=5)
    w = _t((8, 8), grad=False, seed=6)
    for _ in range(3):
        plain = paddle.matmul(x, w)
    assert plain.dtype == jnp.float32
    with paddle.amp.auto_cast(level="O1"):
        for _ in range(3):
            low = paddle.matmul(x, w)
    assert low.dtype == jnp.bfloat16  # cached entry bakes the cast
    info = dcache.cache_info()
    assert info["compiles"] >= 2      # plain and amp entries are distinct


def test_int_input_grads_cached():
    # integer inputs ride through the cached vjp as float0 -> skipped
    x = _t((5, 4), grad=True, seed=7)
    idx = to_tensor(np.array([0, 2, 4]))
    dcache.configure(enabled=False)
    ref = paddle.gather(x, idx).sum()
    ref.backward()
    g_ref = np.asarray(x.grad._data).copy()
    x.clear_grad()
    dcache.configure(enabled=True)
    for _ in range(3):
        loss = paddle.gather(x, idx).sum()
        loss.backward()
        np.testing.assert_array_equal(np.asarray(x.grad._data), g_ref)
        x.clear_grad()


def test_double_grad_through_cached_nodes():
    def run():
        x = to_tensor(np.array([1.5, -2.0, 3.0], np.float32),
                      stop_gradient=False)
        y = (x * x * x).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        (ggx,) = paddle.grad(gx.sum(), [x])
        return np.asarray(gx._data).copy(), np.asarray(ggx._data).copy()

    dcache.configure(enabled=False)
    ref = run()
    dcache.configure(enabled=True)
    for _ in range(3):
        got = run()
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(r, g)


def test_multi_output_op_cached():
    x = to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2),
                  stop_gradient=False)
    for _ in range(3):
        a, b, c = paddle.split(x, 3, axis=0)
    loss = (a.sum() + (b * 2).sum() + (c * 3).sum())
    loss.backward()
    want = np.repeat(np.array([1.0, 2.0, 3.0], np.float32), 2 * 2)
    np.testing.assert_array_equal(np.asarray(x.grad._data).ravel(), want)
    assert dcache.cache_info()["hits"] >= 1


def test_retain_graph_and_second_backward_error_with_cache():
    x = _t((3, 3), grad=True)
    for _ in range(3):
        loss = (x * 2.0).sum()
    loss.backward(retain_graph=True)
    loss.backward()                   # allowed: graph retained once
    with pytest.raises(RuntimeError):
        loss.backward()               # released now -> same error as seed


def test_backward_snapshots_closure_state_at_dispatch_time():
    # the seed's jax.vjp reads the op fn's closure AT DISPATCH; the cached
    # backward must too (warm_bwd), not at first backward() — a caller
    # mutating closure-held state in between must not change the grads
    x = to_tensor(np.arange(6, dtype=np.float32), stop_gradient=False)
    scale = [2.0]

    def f(a):
        return a * scale[0]

    for _ in range(3):                # third call serves from the cache
        y = apply("tk_snapshot", f, x)
    assert dcache.cache_info()["hits"] >= 1
    scale[0] = 100.0                  # mutate AFTER dispatch, BEFORE backward
    y.sum().backward()
    np.testing.assert_array_equal(np.asarray(x.grad._data),
                                  np.full(6, 2.0, np.float32))


def test_poisoned_entries_respect_the_lru_bound():
    dcache.configure(maxsize=4, warmup=1)
    x = _t()
    for k in range(8):                # 8 distinct untraceable signatures
        def branchy(a, _k=float(k)):
            if float(jnp.sum(a)) > 1e9:
                return a * 0.0
            return a * _k
        apply("tk_poison", branchy, x)
    info = dcache.cache_info()
    assert info["size"] <= 4
    assert info["evictions"] >= 4


# ---------------------------------------------------------------------------
# fused nan check
# ---------------------------------------------------------------------------

def test_check_nan_inf_fused_on_cached_path():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = to_tensor(np.array([1.0, 2.0], np.float32))
        big = to_tensor(np.array([1e38, 1e38], np.float32))
        for _ in range(3):
            x * 2.0                   # finite: cached, no raise
        assert dcache.cache_info()["hits"] >= 1
        for _ in range(3):            # overflow -> inf on cold AND hot path
            with pytest.raises(FloatingPointError):
                big * 1e38
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_runtime_set_flags_invalidates_cached_entries():
    # op fns read flags at trace time (tpu_matmul_precision et al.): a
    # set_flags() must retire compiled entries, not serve the baked value
    x = _t((4, 4), seed=8)
    w = _t((4, 4), seed=9)
    for _ in range(3):
        paddle.matmul(x, w)
    hits = dcache.cache_info()["hits"]
    assert hits >= 1
    prev = paddle.get_flags("FLAGS_tpu_matmul_precision")[
        "FLAGS_tpu_matmul_precision"]
    paddle.set_flags({"FLAGS_tpu_matmul_precision": "high"})
    try:
        out_hi = paddle.matmul(x, w)      # must NOT hit the stale entry
        assert dcache.cache_info()["hits"] == hits
        dcache.configure(enabled=False)   # flag honored same as cache-off
        ref = paddle.matmul(x, w)
        np.testing.assert_array_equal(np.asarray(out_hi._data),
                                      np.asarray(ref._data))
    finally:
        paddle.set_flags({"FLAGS_tpu_matmul_precision": prev})
        dcache.configure(enabled=True)


def test_nan_check_flag_is_a_key_component():
    x = _t()
    for _ in range(3):
        x * 7.0
    compiles = dcache.cache_info()["compiles"]
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        for _ in range(3):
            x * 7.0                   # same op, nan-checked variant
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    assert dcache.cache_info()["compiles"] == compiles + 1


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_cache_counters_in_snapshot_and_prometheus():
    obs.reset()
    obs.enable()
    try:
        x = _t()
        for _ in range(3):
            x * 11.0
        table = np.ones((4, 4), np.float32)
        apply("tk_obs_bypass", lambda a: a + table, x)
        snap = obs.snapshot()
        assert snap.get("dispatch.cache_hits_total", 0) >= 1
        assert snap.get("dispatch.cache_misses_total", 0) >= 2
        assert snap.get("dispatch.cache_compiles_total", 0) >= 1
        bypass = snap.get("dispatch.cache_bypass_total", {})
        assert any("closure_array" in k for k in bypass)
        text = obs.prometheus_text()
        assert "dispatch_cache_hits_total" in text
        assert "dispatch_cache_bypass_total" in text
    finally:
        obs.disable()
        obs.reset()


def test_disabled_observability_leaves_hook_unset():
    assert dcache._obs_hook is None
    obs.enable()
    assert dcache._obs_hook is not None
    obs.disable()
    assert dcache._obs_hook is None


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_removable_handle_ids_unique_across_threads():
    ids = []
    lock = threading.Lock()

    def worker():
        t = _t((2,))
        got = [t.register_hook(lambda g: g).hook_id for _ in range(200)]
        with lock:
            ids.extend(got)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(ids) == len(set(ids)) == 1600


def test_shape_tuple_is_allocation_free_metadata():
    t = _t((3, 5))
    assert t.shape_tuple() == (3, 5)
    assert isinstance(t.shape_tuple(), tuple)
    # same object as the payload's shape: no per-access list build
    assert t.shape_tuple() is t._data.shape
    assert t.shape == [3, 5]          # the paddle-parity list view survives


def test_to_tensor_casts_committed_jax_array():
    committed = jax.device_put(np.arange(4, dtype=np.int32),
                               jax.devices("cpu")[0])
    t = to_tensor(committed, dtype="float32")
    assert t.dtype == jnp.float32
    np.testing.assert_array_equal(t.numpy(),
                                  np.arange(4, dtype=np.float32))
    tr = to_tensor(paddle.to_tensor(np.ones(3, np.int32)), dtype="float64")
    assert str(tr.dtype) in ("float64", "float32")  # x64 may be disabled
