"""Op tests: forward vs NumPy reference + tape grads vs jax.grad.

This is the OpTest pattern from the reference's test/legacy_test/op_test.py
(SURVEY.md §4): every op checked against a NumPy implementation, gradients
checked against an independent autodiff of the same composite.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.default_rng(0)


def t(a, sg=True):
    return paddle.to_tensor(a, stop_gradient=sg)


UNARY_CASES = [
    ("abs", np.abs, lambda s: RNG.standard_normal(s, np.float32)),
    ("exp", np.exp, lambda s: RNG.standard_normal(s, np.float32)),
    ("log", np.log, lambda s: RNG.uniform(0.1, 3, s).astype(np.float32)),
    ("sqrt", np.sqrt, lambda s: RNG.uniform(0.1, 3, s).astype(np.float32)),
    ("rsqrt", lambda x: 1 / np.sqrt(x), lambda s: RNG.uniform(0.1, 3, s).astype(np.float32)),
    ("sin", np.sin, lambda s: RNG.standard_normal(s, np.float32)),
    ("cos", np.cos, lambda s: RNG.standard_normal(s, np.float32)),
    ("tanh", np.tanh, lambda s: RNG.standard_normal(s, np.float32)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), lambda s: RNG.standard_normal(s, np.float32)),
    ("floor", np.floor, lambda s: RNG.standard_normal(s, np.float32) * 3),
    ("ceil", np.ceil, lambda s: RNG.standard_normal(s, np.float32) * 3),
    ("square", np.square, lambda s: RNG.standard_normal(s, np.float32)),
    ("sign", np.sign, lambda s: RNG.standard_normal(s, np.float32)),
    ("log1p", np.log1p, lambda s: RNG.uniform(0, 2, s).astype(np.float32)),
    ("erf", None, lambda s: RNG.standard_normal(s, np.float32)),
    ("reciprocal", lambda x: 1 / x, lambda s: RNG.uniform(0.5, 2, s).astype(np.float32)),
]


@pytest.mark.parametrize("name,ref,gen", UNARY_CASES, ids=[c[0] for c in UNARY_CASES])
def test_unary_forward(name, ref, gen):
    x = gen((3, 4))
    out = getattr(paddle, name)(t(x))
    if ref is None:
        import scipy.special  # available? fall back to jax reference
        expected = np.asarray(getattr(jax.scipy.special, name)(x))
    else:
        expected = ref(x)
    # XLA CPU uses vectorized approximations for transcendentals (~1e-4 rel)
    np.testing.assert_allclose(out.numpy(), expected, rtol=2e-4, atol=1e-6)


BINARY_CASES = [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
    ("pow", np.power), ("atan2", np.arctan2),
]


@pytest.mark.parametrize("name,ref", BINARY_CASES, ids=[c[0] for c in BINARY_CASES])
def test_binary_forward(name, ref):
    x = RNG.uniform(0.5, 2, (3, 4)).astype(np.float32)
    y = RNG.uniform(0.5, 2, (3, 4)).astype(np.float32)
    out = getattr(paddle, name)(t(x), t(y))
    np.testing.assert_allclose(out.numpy(), ref(x, y), rtol=2e-4)


def test_broadcasting_and_scalars():
    x = RNG.standard_normal((3, 4)).astype(np.float32)
    assert np.allclose((t(x) + 1.5).numpy(), x + 1.5)
    assert np.allclose((2.0 * t(x)).numpy(), 2 * x)
    y = RNG.standard_normal((4,)).astype(np.float32)
    assert np.allclose((t(x) * t(y)).numpy(), x * y)
    assert np.allclose((1.0 / t(x)).numpy(), 1 / x, rtol=1e-5)


def test_comparisons_bool():
    x, y = t(np.array([1.0, 2.0, 3.0])), t(np.array([2.0, 2.0, 2.0]))
    assert (x < y).numpy().tolist() == [True, False, False]
    assert (x == y).numpy().tolist() == [False, True, False]
    assert (x >= y).numpy().tolist() == [False, True, True]


GRAD_COMPOSITES = [
    ("mlp", lambda p, x: jnp.mean(jax.nn.relu(x @ p) ** 2),
     lambda P, X: (paddle.mean(paddle.relu(paddle.matmul(X, P)) ** 2))),
    ("softmax_ce", lambda p, x: -jnp.sum(jax.nn.log_softmax(x @ p)[..., 0]),
     lambda P, X: -paddle.sum(paddle.log_softmax(paddle.matmul(X, P))[..., 0])),
    ("norm_chain", lambda p, x: jnp.sum(jnp.tanh(x @ p) / (1 + jnp.exp(-(x @ p)))),
     lambda P, X: paddle.sum(paddle.tanh(paddle.matmul(X, P)) /
                             (1 + paddle.exp(-paddle.matmul(X, P))))),
]


@pytest.mark.parametrize("name,jref,pfn", GRAD_COMPOSITES, ids=[c[0] for c in GRAD_COMPOSITES])
def test_tape_grad_matches_jax(name, jref, pfn):
    p = RNG.standard_normal((4, 4)).astype(np.float32)
    x = RNG.standard_normal((5, 4)).astype(np.float32)
    P, X = t(p, sg=False), t(x)
    loss = pfn(P, X)
    loss.backward()
    expected = jax.grad(jref)(jnp.asarray(p), jnp.asarray(x))
    np.testing.assert_allclose(P.grad.numpy(), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


def test_numeric_gradcheck_matmul():
    """Finite-difference check (the reference's check_grad pattern)."""
    a = RNG.standard_normal((3, 3)).astype(np.float32)
    b = RNG.standard_normal((3, 3)).astype(np.float32)
    A = t(a, sg=False)
    loss = paddle.sum(paddle.matmul(A, t(b)) ** 2)
    loss.backward()
    g = A.grad.numpy()
    eps = 1e-3
    for i in range(3):
        for j in range(3):
            ap = a.copy(); ap[i, j] += eps
            am = a.copy(); am[i, j] -= eps
            fp = float(np.sum((ap @ b) ** 2))
            fm = float(np.sum((am @ b) ** 2))
            num = (fp - fm) / (2 * eps)
            assert abs(num - g[i, j]) < 2e-1 * max(1.0, abs(num)), (i, j)


def test_reductions():
    x = RNG.standard_normal((3, 4, 5)).astype(np.float32)
    assert np.allclose(paddle.sum(t(x)).numpy(), x.sum(), rtol=1e-5)
    assert np.allclose(paddle.sum(t(x), axis=1).numpy(), x.sum(1), rtol=1e-5)
    assert np.allclose(paddle.mean(t(x), axis=[0, 2]).numpy(), x.mean((0, 2)), rtol=1e-5)
    assert np.allclose(paddle.max(t(x), axis=-1).numpy(), x.max(-1))
    assert np.allclose(paddle.std(t(x)).numpy(), x.std(ddof=1), rtol=1e-4)
    assert paddle.argmax(t(x)).item() == int(x.argmax())
    assert np.allclose(paddle.logsumexp(t(x), axis=1).numpy(),
                       np.log(np.exp(x).sum(1)), rtol=1e-4)
    assert np.allclose(paddle.cumsum(t(x), axis=1).numpy(), x.cumsum(1), rtol=1e-4)


def test_matmul_family():
    a = RNG.standard_normal((2, 3, 4)).astype(np.float32)
    b = RNG.standard_normal((2, 4, 5)).astype(np.float32)
    assert np.allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-4, atol=1e-5)
    assert np.allclose(paddle.bmm(t(a), t(b)).numpy(), a @ b, rtol=1e-4, atol=1e-5)
    m = RNG.standard_normal((4, 5)).astype(np.float32)
    assert np.allclose(
        paddle.matmul(t(a[0]), t(m), transpose_y=False).numpy(), a[0] @ m,
        rtol=1e-4, atol=1e-5)
    assert np.allclose(
        paddle.matmul(t(a[0]), t(m.T), transpose_y=True).numpy(), a[0] @ m,
        rtol=1e-4, atol=1e-5)
    assert np.allclose(paddle.einsum("bij,bjk->bik", t(a), t(b)).numpy(), a @ b,
                       rtol=1e-4, atol=1e-5)
