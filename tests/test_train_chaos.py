"""Training under fire (ISSUE 10): the fault-tolerant training supervisor.

The acceptance surface for ``resilience.trainer``:

* **kill-at-step proof** — a seeded ``KillPoint`` at ``train.step`` call N
  escapes the supervisor (simulated process death), and a FRESH supervisor
  (fresh model/optimizer/loader, same construction order) with
  ``resume=True`` restores the last verified ``TrainState`` and produces a
  loss trajectory bitwise identical to an uninterrupted run — RNG,
  optimizer step/moments, LR-schedule position, and dataloader cursor all
  resume exactly;
* **watchdog trip** and **NaN escalation** each have a deterministic
  regression test (restore-last-good keeps the trajectory bitwise);
* **seeded FaultSchedule sweep** over the ``train.*`` sites x >= 3 seeds
  with the invariants: every run terminates typed, same seed => same
  retry/restart trace AND same losses, and any run that completes decodes
  the exact fault-free trajectory (pre-step faults never corrupt a step);
* the DataLoader resume-mid-epoch parity and the verified ModelCheckpoint
  fallback chain (PR 10 satellites) are pinned here too.

"Fresh process" is simulated by resetting ``Parameter._param_counter``
before each rebuild: optimizer state keys derive from auto-generated
param names, which are deterministic per construction order in a real
restart but drift when several models are built in one test process.
"""

import math
import os
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Parameter
from paddle_tpu.resilience import faults, reset_policies
from paddle_tpu.resilience.trainer import (FaultTolerance, NonFiniteLossError,
                                           TrainAborted, TrainState,
                                           TrainingSupervisor)


@pytest.fixture(autouse=True)
def _fast_retry_policies(monkeypatch):
    """Millisecond backoff for the train.* policies: the retry SCHEDULE is
    under test, not the wall clock."""
    for site in ("STEP", "DATA", "SAVE"):
        monkeypatch.setenv(f"PADDLE_TPU_RETRY_TRAIN_{site}_BASE_DELAY",
                           "0.001")
        monkeypatch.setenv(f"PADDLE_TPU_RETRY_TRAIN_{site}_MAX_DELAY",
                           "0.002")
    reset_policies()
    yield
    reset_policies()


def build_run(seed=7, *, lr_sched=False, n=32, batch_size=8):
    """One complete training setup, as a fresh process would construct it."""
    Parameter._param_counter = 0   # fresh-process simulation (see module doc)
    paddle.seed(seed)
    net = paddle.nn.Linear(8, 4)
    lr = (paddle.optimizer.lr.StepDecay(0.05, step_size=3, gamma=0.5)
          if lr_sched else 0.05)
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=net.parameters())
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, 8)).astype(np.float32)
    ys = rng.normal(size=(n, 4)).astype(np.float32)
    ds = paddle.io.TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
    loader = paddle.io.DataLoader(ds, batch_size=batch_size, shuffle=True)
    loss_fn = paddle.nn.MSELoss()

    def step_fn(batch):
        x, y = batch
        loss = loss_fn(net(x), y)
        loss.backward()
        return loss

    def update_fn():
        opt.step()
        opt.clear_grad()
        if lr_sched:
            opt._learning_rate.step()

    def clear_fn():
        opt.clear_grad()

    return SimpleNamespace(net=net, opt=opt, loader=loader, step=step_fn,
                           update=update_fn, clear=clear_fn)


def run_supervised(r, tmpdir, *, epochs=2, save_every=2, **knobs):
    sup = TrainingSupervisor(r.net, r.opt, r.loader,
                             ckpt_dir=str(tmpdir) if tmpdir else None,
                             save_every=save_every, **knobs)
    return sup.run(r.step, r.loader, epochs=epochs, update_fn=r.update,
                   clear_fn=r.clear)


def reference_losses(tmp_path, **build_kw):
    r = build_run(**build_kw)
    return run_supervised(r, tmp_path / "ref").losses


# ---------------------------------------------------------------------------
# the acceptance proof: kill-at-step, restart, bitwise-identical trajectory
# ---------------------------------------------------------------------------

class TestKillAtStepBitIdentical:
    def test_kill_resume_trajectory_bitwise(self, tmp_path):
        ref = reference_losses(tmp_path, lr_sched=True)
        assert len(ref) == 8       # 2 epochs x 4 batches

        r = build_run(lr_sched=True)
        ck = tmp_path / "ck"
        sched = faults.FaultSchedule().kill("train.step", on=(6,))
        with faults.installed(sched):
            with pytest.raises(faults.KillPoint):
                run_supervised(r, ck, save_every=1)
        assert sched.trace == [("train.step", 6, "kill")]

        # "process restart": rebuild everything in construction order and
        # resume from the last verified TrainState (step 5, mid-epoch 2)
        r2 = build_run(lr_sched=True)
        sup = TrainingSupervisor(r2.net, r2.opt, r2.loader,
                                 ckpt_dir=str(ck), save_every=1)
        rep = sup.run(r2.step, r2.loader, epochs=2, update_fn=r2.update,
                      clear_fn=r2.clear, resume=True)
        assert rep.resumed_from == str(ck / "step-5")
        assert rep.steps == 3
        # the pinned claim: bitwise equality, not allclose
        assert rep.losses == ref[5:]

    def test_kill_mid_commit_resumes_from_previous_good(self, tmp_path):
        ref = reference_losses(tmp_path)
        r = build_run()
        ck = tmp_path / "ck"
        # the 3rd TrainState save dies INSIDE the writer's commit window:
        # pointer rotation never happens, last-good stays step-2
        sched = faults.FaultSchedule().kill("checkpoint.commit", on=(3,))
        with faults.installed(sched):
            with pytest.raises(faults.KillPoint):
                run_supervised(r, ck, save_every=1)
        r2 = build_run()
        sup = TrainingSupervisor(r2.net, r2.opt, r2.loader, ckpt_dir=str(ck),
                                 save_every=1)
        rep = sup.run(r2.step, r2.loader, epochs=2, update_fn=r2.update,
                      clear_fn=r2.clear, resume=True)
        assert rep.resumed_from == str(ck / "step-2")
        assert rep.losses == ref[2:]


# ---------------------------------------------------------------------------
# in-process recovery: retry, restore-last-good, watchdog, NaN
# ---------------------------------------------------------------------------

class TestInProcessRecovery:
    def test_transient_fault_is_retried_trajectory_unchanged(self, tmp_path):
        ref = reference_losses(tmp_path)
        r = build_run()
        sched = faults.FaultSchedule().error("train.step", on=(2,))
        with faults.installed(sched):
            rep = run_supervised(r, tmp_path / "ck")
        assert rep.retries == 1 and rep.restarts == 0
        assert rep.losses == ref

    def test_retry_budget_exhausted_restores_last_good(self, tmp_path):
        ref = reference_losses(tmp_path)
        r = build_run()
        # attempt 3 of step 3 plus its two retries: the train.step policy
        # budget (3 attempts) is spent, the supervisor rolls back to the
        # step-2 checkpoint and re-runs the batch
        sched = faults.FaultSchedule().error("train.step", on=(3, 4, 5))
        with faults.installed(sched):
            rep = run_supervised(r, tmp_path / "ck")
        assert rep.retries == 2 and rep.restarts == 1
        assert rep.losses == ref

    def test_data_fault_retry_and_restore(self, tmp_path):
        ref = reference_losses(tmp_path)
        r = build_run()
        sched = faults.FaultSchedule().error("train.data", on=(3, 4, 5))
        with faults.installed(sched):
            rep = run_supervised(r, tmp_path / "ck")
        assert rep.restarts == 1
        assert rep.losses == ref

    def test_real_iterator_fault_restores_instead_of_truncating(self,
                                                                tmp_path):
        # review regression: an exception raised by the loader ITSELF (not
        # a pre-next() injected fault) closes the generator; retrying
        # next() on it would read StopIteration as a silent epoch end.
        # The supervisor must restore-last-good and replay the full epoch.
        class FlakyDataset(paddle.io.Dataset):
            def __init__(self, xs, ys):
                self.xs, self.ys = xs, ys
                self.fail_once = True

            def __getitem__(self, i):
                if i == 20 and self.fail_once:
                    self.fail_once = False
                    raise IOError("transient storage fault")
                return self.xs[i], self.ys[i]

            def __len__(self):
                return len(self.xs)

        rng = np.random.default_rng(7)
        xs = rng.normal(size=(32, 8)).astype(np.float32)
        ys = rng.normal(size=(32, 4)).astype(np.float32)
        flaky = paddle.io.DataLoader(FlakyDataset(xs, ys), batch_size=8)
        # the reference for THIS data (unshuffled, clean pass)
        r_ref = build_run()
        clean = paddle.io.DataLoader(
            paddle.io.TensorDataset(
                [paddle.to_tensor(xs), paddle.to_tensor(ys)]), batch_size=8)
        sup = TrainingSupervisor(r_ref.net, r_ref.opt, clean,
                                 ckpt_dir=str(tmp_path / "ref2"),
                                 save_every=2)
        want = sup.run(r_ref.step, clean, epochs=2, update_fn=r_ref.update,
                       clear_fn=r_ref.clear)
        r = build_run()
        sup = TrainingSupervisor(r.net, r.opt, flaky,
                                 ckpt_dir=str(tmp_path / "ck"), save_every=2)
        rep = sup.run(r.step, flaky, epochs=2, update_fn=r.update,
                      clear_fn=r.clear)
        assert rep.restarts == 1
        assert rep.steps == 8, "epoch was truncated"   # 2 epochs x 4 batches
        assert rep.losses == want.losses

    def test_restart_budget_exhausted_aborts_typed(self, tmp_path):
        r = build_run()
        sched = faults.FaultSchedule().error("train.step",
                                             on=tuple(range(3, 40)))
        with faults.installed(sched):
            with pytest.raises(TrainAborted) as ei:
                run_supervised(r, tmp_path / "ck", max_restarts=1)
        assert isinstance(ei.value.__cause__, faults.FaultInjected)

    def test_unrecoverable_without_checkpoint_aborts_typed(self, tmp_path):
        r = build_run()
        sched = faults.FaultSchedule().error("train.step", on=(1, 2, 3))
        with faults.installed(sched):
            with pytest.raises(TrainAborted):
                run_supervised(r, None)   # no ckpt_dir: nothing to roll to

    def test_watchdog_trip_restores_bitwise(self, tmp_path):
        ref = reference_losses(tmp_path)
        r = build_run()
        # a delay fault INSIDE the armed window simulates a hung device
        # step; the step returns past budget, its outputs are distrusted,
        # the run restores step-2 and re-runs — deterministically, because
        # the delay is scripted on one call index
        sched = faults.FaultSchedule().delay("train.step", on=(3,),
                                             seconds=0.5)
        with faults.installed(sched):
            rep = run_supervised(r, tmp_path / "ck", watchdog_s=0.12)
        assert rep.restarts == 1
        assert rep.losses == ref

    def test_nan_skip_withholds_update_and_counts(self, tmp_path):
        r = build_run()
        calls = [0]
        real_step = r.step

        def step(batch):
            calls[0] += 1
            if calls[0] == 2:
                return paddle.to_tensor(np.float32(np.nan))
            return real_step(batch)

        w_probe = []

        def update():
            w_probe.append(np.asarray(r.net.weight._data).copy())
            r.update()

        sup = TrainingSupervisor(r.net, r.opt, r.loader, max_skipped=3)
        rep = sup.run(step, r.loader, epochs=1, update_fn=update,
                      clear_fn=r.clear)
        # 4 batches, one skipped: 3 applied steps, the NaN batch's update
        # never ran (update_fn not called for it)
        assert rep.steps == 3 and rep.skipped_batches == 1
        assert len(w_probe) == 3
        assert all(math.isfinite(l) for l in rep.losses)

    def test_nan_escalation_rolls_back_then_recovers(self, tmp_path):
        ref = reference_losses(tmp_path)
        r = build_run()
        calls = [0]
        real_step = r.step

        def step(batch):
            calls[0] += 1
            if calls[0] in (4, 5, 6):     # 3 consecutive non-finite losses
                return paddle.to_tensor(np.float32(np.inf))
            return real_step(batch)

        sup = TrainingSupervisor(r.net, r.opt, r.loader,
                                 ckpt_dir=str(tmp_path / "ck"), save_every=2,
                                 max_skipped=3)
        rep = sup.run(step, r.loader, epochs=2, update_fn=r.update,
                      clear_fn=r.clear)
        assert rep.restarts == 1 and rep.skipped_batches == 3
        assert rep.losses == ref

    def test_nan_policy_raise_is_immediate_and_typed(self):
        r = build_run()

        def step(batch):
            return paddle.to_tensor(np.float32(np.nan))

        sup = TrainingSupervisor(r.net, r.opt, r.loader, nan_policy="raise")
        with pytest.raises(NonFiniteLossError):
            sup.run(step, r.loader, epochs=1, update_fn=r.update,
                    clear_fn=r.clear)


# ---------------------------------------------------------------------------
# seeded chaos sweep over the train.* sites
# ---------------------------------------------------------------------------

def _chaos_schedule(seed):
    sched = faults.FaultSchedule(seed)
    sched.error("train.step", prob=0.12)
    sched.error("train.data", prob=0.08)
    sched.error("train.save", prob=0.10)
    return sched


def _chaos_run(seed, tmp_path, tag):
    r = build_run(seed=3)
    sched = _chaos_schedule(seed)
    outcome = {"trace": None}
    with faults.installed(sched):
        try:
            rep = run_supervised(r, tmp_path / f"ck-{tag}", save_every=1,
                                 max_restarts=4)
            outcome.update(kind="completed", losses=rep.losses,
                           retries=rep.retries, restarts=rep.restarts)
        except TrainAborted as e:
            outcome.update(kind="aborted",
                           cause=type(e.__cause__).__name__)
        except faults.FaultInjected:
            # a save that failed past its retry budget surfaces raw — the
            # operator must know checkpoints stopped flowing
            outcome.update(kind="save_failed")
    outcome["trace"] = list(sched.trace)
    return outcome


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_chaos_sweep_terminates_typed_and_deterministic(seed, tmp_path):
    ref = reference_losses(tmp_path, seed=3)
    first = _chaos_run(seed, tmp_path, f"{seed}a")
    again = _chaos_run(seed, tmp_path, f"{seed}b")
    # same seed => same injected-fault trace AND same terminal state
    assert first["trace"] == again["trace"]
    assert first["kind"] == again["kind"]
    if first["kind"] == "completed":
        assert first["losses"] == again["losses"]
        assert (first["retries"], first["restarts"]) == \
            (again["retries"], again["restarts"])
        # pre-step faults may delay/retry/roll back but can NEVER corrupt
        # a step: a completed chaos run decodes the exact clean trajectory
        assert first["losses"] == ref


@pytest.mark.parametrize("seed", [0, 2])
def test_chaos_sweep_trace_invariants(seed, tmp_path, tracing):
    """ISSUE 12: the same seeded sweep with tracing on — every span
    balanced through retries/restores/aborts, the retry/restore events
    ride the step spans, and every abort path leaves a parseable flight
    dump whose tail names a train.* fault site."""
    import json

    outcome = _chaos_run(seed, tmp_path, f"{seed}t")
    evs = tracing.events()
    assert tracing.span_problems(evs) == []
    names = {e["name"] for e in evs}
    assert {"train.run", "train.step", "train.fwd_bwd"} <= names
    if any(site == "train.step" and kind == "error"
           for site, _, kind in outcome["trace"]):
        assert "train.retry" in names or "train.restore" in names
    if outcome["kind"] == "aborted":
        dump = os.path.join(
            str(tmp_path), f"flight-{os.getpid()}-train_aborted.json")
        assert os.path.exists(dump)
        doc = json.load(open(dump))
        sites = [e["attrs"].get("site") for e in doc["events"]
                 if e["name"] == "fault"]
        assert sites and sites[-1].startswith("train.")
    # the chrome export of the whole chaos run still loads
    json.dumps(tracing.export_chrome())


def test_kill_at_step_leaves_parseable_dump_with_fault_site(tmp_path,
                                                            tracing):
    """ISSUE 12 acceptance: a killed run's flight dump tail matches the
    injected fault site (here the kill itself at train.step)."""
    import json

    r = build_run()
    sched = faults.FaultSchedule().kill("train.step", on=(3,))
    with faults.installed(sched):
        with pytest.raises(faults.KillPoint):
            run_supervised(r, tmp_path / "ck", save_every=1)
    dump = os.path.join(
        str(tmp_path), f"flight-{os.getpid()}-supervisor_exit.json")
    assert os.path.exists(dump)
    doc = json.load(open(dump))
    assert doc["info"]["error"] == "KillPoint"
    fault_evs = [e for e in doc["events"] if e["name"] == "fault"]
    assert fault_evs and fault_evs[-1]["attrs"]["site"] == "train.step"
    assert fault_evs[-1]["attrs"]["injected"] == "kill"
    # spans unwound (balanced) even through the BaseException kill
    assert tracing.span_problems() == []


# ---------------------------------------------------------------------------
# TrainState: verified persistence + pointer-chain fallback
# ---------------------------------------------------------------------------

class TestTrainState:
    def test_restore_latest_falls_back_past_corrupt_manifest(self, tmp_path):
        r = build_run()
        run_supervised(r, tmp_path / "ck", save_every=1, epochs=1)
        ck = tmp_path / "ck"
        assert (ck / "latest").read_text().strip() == "step-4"
        # interrupt the newest save after the fact: no committed manifest
        os.remove(ck / "step-4" / "manifest.json")
        r2 = build_run()
        st = TrainState(r2.net, r2.opt, r2.loader)
        path, py = st.restore_latest(str(ck))
        assert path == str(ck / "step-3") and py["step"] == 3

    def test_restore_latest_none_when_nothing_committed(self, tmp_path):
        r = build_run()
        st = TrainState(r.net, r.opt, r.loader)
        assert st.restore_latest(str(tmp_path / "empty")) is None

    def test_wrong_tree_is_user_error_not_fallback(self, tmp_path):
        r = build_run()
        run_supervised(r, tmp_path / "ck", save_every=1, epochs=1)
        Parameter._param_counter = 0
        paddle.seed(0)
        other = paddle.nn.Linear(3, 2)     # wrong shapes for this ckpt
        st = TrainState(other, None, None)
        with pytest.raises((KeyError, ValueError)):
            st.restore_latest(str(tmp_path / "ck"))

    def test_metrics_visible(self, tmp_path, metrics):
        r = build_run()
        sched = faults.FaultSchedule().error("train.step", on=(2,))
        with faults.installed(sched):
            run_supervised(r, tmp_path / "ck", epochs=1)
        snap = metrics.snapshot()
        assert snap["train.steps_total"] == 4
        assert snap["train.retries_total"]["site=train.step"] == 1
        assert snap["train.saves_total"] == 2
        assert snap["train.step_seconds"]["count"] >= 4
        text = metrics.prometheus_text()
        assert "train_steps_total" in text


# ---------------------------------------------------------------------------
# satellites: DataLoader resume parity, watchdog extraction, ModelCheckpoint
# ---------------------------------------------------------------------------

class TestDataLoaderResume:
    def _loader(self, n=24, bs=4):
        xs = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        ds = paddle.io.TensorDataset([paddle.to_tensor(xs)])
        return paddle.io.DataLoader(ds, batch_size=bs, shuffle=True)

    def test_resume_mid_epoch_matches_uninterrupted(self):
        paddle.seed(11)
        loader = self._loader()
        ref = [np.asarray(b[0]._data).copy() for b in loader]

        paddle.seed(11)
        loader2 = self._loader()
        it = iter(loader2)
        got = [np.asarray(next(it)[0]._data).copy() for _ in range(2)]
        state = loader2.state_dict()
        assert state["in_epoch"] and state["batch"] == 2
        it = None  # abandon the interrupted iteration

        # "restart": fresh loader + the saved cursor; the global RNG at
        # this point is arbitrary — resume must not depend on it
        paddle.seed(999)
        loader3 = self._loader()
        loader3.load_state_dict(state)
        rng_before = np.asarray(
            paddle.get_rng_state()[0]._data).copy()
        rest = [np.asarray(b[0]._data).copy() for b in loader3]
        # rng-neutral: replaying the epoch's shuffle draw left the live
        # generator untouched
        np.testing.assert_array_equal(
            np.asarray(paddle.get_rng_state()[0]._data), rng_before)
        full = got + rest
        assert len(full) == len(ref)
        for a, b in zip(full, ref):
            np.testing.assert_array_equal(a, b)

    def test_state_roundtrip_between_epochs(self):
        paddle.seed(5)
        loader = self._loader()
        list(loader)
        st = loader.state_dict()
        assert st["epochs_completed"] == 1 and not st["in_epoch"]
        assert st["batch"] == 0
        loader.load_state_dict(st)
        assert len(list(loader)) == len(loader)

    def test_version_gate(self):
        loader = self._loader()
        with pytest.raises(ValueError):
            loader.load_state_dict({"version": 99})
        with pytest.raises(ValueError):
            loader.load_state_dict({"batch": 1})


def test_watchdog_backcompat_reexport():
    from paddle_tpu import serving
    from paddle_tpu.resilience import watchdog as rwd
    from paddle_tpu.serving import watchdog as swd
    assert swd.StepWatchdog is rwd.StepWatchdog
    assert serving.WatchdogTimeout is rwd.WatchdogTimeout


def test_watchdog_train_metric_name(metrics):
    import time
    from paddle_tpu.resilience.watchdog import StepWatchdog
    wd = StepWatchdog(0.1, metric="train.watchdog_trips_total",
                      label="train")
    gen = wd.arm()
    time.sleep(0.15)              # past budget, inside 2x (no zombie)
    verdict = wd.disarm(gen)
    wd.stop()
    assert verdict == "hung"
    snap = metrics.snapshot()
    assert snap["train.watchdog_trips_total"]["kind=hung"] == 1


class TestSupervisedFit:
    def _model(self, n=32):
        Parameter._param_counter = 0
        paddle.seed(4)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.05,
                                           parameters=net.parameters()),
            loss=paddle.nn.MSELoss())
        rng = np.random.default_rng(1)
        xs = rng.normal(size=(n, 8)).astype(np.float32)
        ys = rng.normal(size=(n, 4)).astype(np.float32)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(xs), paddle.to_tensor(ys)])
        return model, ds

    def test_supervised_fit_matches_plain_fit(self, tmp_path):
        model, ds = self._model()
        events = []

        class Rec(paddle.hapi.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                events.append(logs["loss"])

        plain = model.fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[Rec()])
        plain_steps = list(events)

        model2, ds2 = self._model()
        events.clear()
        hist = model2.fit(
            ds2, batch_size=8, epochs=2, verbose=0, callbacks=[Rec()],
            fault_tolerance={"ckpt_dir": str(tmp_path / "ck"),
                             "save_every": 2})
        assert events == plain_steps          # bitwise, via the callback
        assert hist["supervisor"].steps == 8
        assert hist["loss"] == plain["loss"]

    def test_epoch_end_hooks_not_duplicated_by_rollback(self, tmp_path):
        # review regression: a restore that rolls back ACROSS an epoch
        # boundary replays that epoch's end; history/eval/EarlyStopping
        # bookkeeping must record each epoch exactly once
        model, ds = self._model()
        clean = model.fit(ds, batch_size=8, epochs=2, verbose=0)["loss"]

        model2, ds2 = self._model()
        # 4 batches/epoch, saves at steps 3 and 6; fault at global step 5
        # (epoch 1) exhausts the retry budget and restores to step-3
        # (mid-epoch 0) — epoch 0 then completes a second time
        sched = faults.FaultSchedule().error("train.step", on=(5, 6, 7))
        with faults.installed(sched):
            hist = model2.fit(
                ds2, batch_size=8, epochs=2, verbose=0, eval_data=ds2,
                fault_tolerance={"ckpt_dir": str(tmp_path / "ck"),
                                 "save_every": 3})
        assert hist["supervisor"].restarts == 1
        assert len(hist["loss"]) == 2
        assert len(hist["eval_loss"]) == 2
        assert hist["loss"] == clean

    def test_multiplicative_decay_state_roundtrip(self):
        # review regression: the _bound_opts exclusion must not drop
        # MultiplicativeDecay._cur (the accumulated product IS the
        # schedule position)
        sched = paddle.optimizer.lr.MultiplicativeDecay(
            0.1, lambda e: 0.5)
        for _ in range(3):
            sched.step()
        state = sched.state_dict()
        assert "_cur" in state and "_bound_opts" not in state
        fresh = paddle.optimizer.lr.MultiplicativeDecay(0.1, lambda e: 0.5)
        fresh.set_state_dict(state)
        sched.step()
        fresh.step()
        assert fresh.last_lr == sched.last_lr

    def test_supervised_fit_recovers_from_injected_fault(self, tmp_path):
        model, ds = self._model()
        clean = model.fit(ds, batch_size=8, epochs=2, verbose=0)["loss"]

        model2, ds2 = self._model()
        sched = faults.FaultSchedule().error("train.step", on=(3, 4, 5))
        with faults.installed(sched):
            hist = model2.fit(
                ds2, batch_size=8, epochs=2, verbose=0,
                fault_tolerance={"ckpt_dir": str(tmp_path / "ck"),
                                 "save_every": 1})
        assert hist["supervisor"].restarts == 1
        assert hist["loss"] == clean
