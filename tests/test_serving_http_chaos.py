"""Seeded chaos for the HTTP serving tier (ISSUE 15 acceptance).

Storms over the new fault sites — ``router.pick`` / ``router.forward`` /
``http.write`` — composed with the PR 8 serving sites, driving K=3 toy-LM
replicas behind the router and the streaming front door, pinning the
tier's contract:

* **exactly one typed outcome per HTTP request** — every request
  terminates as exactly one of {complete(200), 429, 503, 504} (a
  double-injected ``http.write`` fault is the deliberate client
  disconnect: those are bounded by the schedule's write-fault fires and
  are cancelled upstream);
* **at-most-once admission witness** — no token is ever emitted twice
  for one request: a completed stream's bytes are exactly its result's
  tokens, which are exactly the no-fault dense reference;
* **no leaks** — after the storm + drain, ``outstanding_pages == 0`` on
  every replica, zero active slots, zero queued requests;
* **determinism** — same seed ⇒ same router decision trace (and the same
  per-request outcomes), with rids normalized to submission order;
* **replica-kill failover proof** — kill one of three replicas mid-batch:
  its queued (never-admitted) work fails over and completes bit-identical
  to the no-fault reference on the survivors, its in-flight streams end
  with the typed :class:`DrainTimeout` well inside the deadline budget.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (backend pin via conftest)
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.resilience import DeadlineExceeded, faults

from test_serving import PROMPTS, V, dense_reference, make_engine
from test_serving_http import make_router, read_sse

EXPECTED_ERRORS = (faults.FaultInjected, serving.WatchdogTimeout,
                   DeadlineExceeded, serving.DrainTimeout,
                   serving.EngineStopped, serving.NoHealthyReplica,
                   serving.QueueFull)

_REF_CACHE = {}


def reference(prompt, n_new):
    key = (tuple(int(t) for t in prompt), n_new)
    if key not in _REF_CACHE:
        _REF_CACHE[key] = dense_reference(np.asarray(prompt, np.int32),
                                          n_new)
    return _REF_CACHE[key]


def _storm_schedule(seed: int) -> faults.FaultSchedule:
    sched = faults.FaultSchedule(seed)
    sched.error("router.pick", prob=0.05)
    sched.error("router.forward", prob=0.08)
    sched.error("http.write", prob=0.02)
    sched.error("serving.admit", prob=0.08)
    sched.error("serving.step", prob=0.04)
    return sched


def _stream_request(fd, prompt, n_new, deadline_s=None, timeout=60.0):
    """One streamed generate; returns (status, tokens, terminals)."""
    conn = http.client.HTTPConnection(fd.host, fd.port, timeout=timeout)
    try:
        headers = {}
        if deadline_s is not None:
            headers["X-Deadline-S"] = str(deadline_s)
        conn.request("POST", "/v1/generate", body=json.dumps({
            "prompt": np.asarray(prompt).tolist(),
            "max_new_tokens": n_new, "stream": True}).encode(),
            headers=headers)
        resp = conn.getresponse()
        raw = resp.read()
        if resp.status != 200:      # typed sync rejection: JSON error doc
            return resp.status, [], [("error", json.loads(raw))]
        tokens, terminals = read_sse(raw)
        return 200, tokens, terminals
    finally:
        conn.close()


# the shared ``metrics`` fixture (fresh enabled obs registry) lives in
# tests/conftest.py


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_storm_every_request_one_typed_outcome(seed, metrics):
    rng = np.random.default_rng(seed)
    router, engines = make_router(k=3, max_batch=4, seed=seed,
                                  max_queue=16)
    for eng in engines.values():
        eng.warmup()
    fd = serving.FrontDoor(router)
    router.start()
    sched = _storm_schedule(seed)
    n_req = 12
    jobs = [(rng.integers(0, V, (int(rng.integers(3, 11)),),
                          dtype=np.int32),
             int(rng.integers(3, 8)),
             30.0 if i % 2 else None) for i in range(n_req)]
    results = [None] * n_req
    try:
        with faults.installed(sched):
            def worker(i):
                p, n, dl = jobs[i]
                results[i] = _stream_request(fd, p, n, deadline_s=dl)

            threads = [threading.Thread(target=worker, args=(i,),
                                        daemon=True) for i in range(n_req)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=90)
                assert not t.is_alive(), "request never terminated"
            router.stop(drain=True, timeout=20)
    finally:
        fd.close()

    write_faults = sum(1 for s, _i, k in sched.trace if s == "http.write")
    disconnects = 0
    for i, res in enumerate(results):
        assert res is not None, "client thread died"
        status, tokens, terminals = res
        p, n, _dl = jobs[i]
        if not terminals:
            # EOF without a terminal event: the double-write-fault client
            # disconnect — allowed ONLY when the schedule actually fired
            # at http.write; the request was cancelled upstream
            disconnects += 1
            continue
        assert len(terminals) == 1, "stream must terminate exactly once"
        kind, doc = terminals[0]
        if kind == "done":
            ref = reference(p, n)
            # at-most-once witness: the streamed bytes are exactly the
            # result, which is exactly the no-fault reference — no token
            # emitted twice, no corruption under any recovery path
            assert tokens == doc["tokens"] == ref
        else:
            assert doc["status"] in (429, 503, 504), doc
            # a failed stream's tokens are a clean prefix of the
            # reference: faults delay or kill a request, never corrupt
            # or duplicate its emission
            assert tokens == reference(p, n)[:len(tokens)]
    assert disconnects <= max(0, write_faults)

    # no leaks on any replica, whatever the storm did
    for eng in engines.values():
        assert eng.kv.outstanding_pages == 0
        assert eng.active_requests == 0 and eng.queue_depth == 0

    # the front door counted one terminal status per request (a
    # double-faulted TERMINAL write can leave a counted-but-disconnected
    # stream, so the lower bound subtracts the disconnects)
    snap = obs.snapshot()
    by_status = snap.get("serving.http.requests_total", {})
    assert n_req - disconnects <= sum(by_status.values()) <= n_req


def test_same_seed_same_router_trace(metrics):
    """The determinism acceptance: identical seeds (router pick-2 RNG +
    fault schedule) produce identical router decision traces and
    identical per-request outcomes, rids normalized to submission
    order. Offline engines: every router decision runs on this thread."""

    def run_once():
        sched = faults.FaultSchedule(11)
        sched.error("router.pick", on=[3])
        sched.error("router.forward", on=[2, 7], prob=None)
        sched.error("router.forward", prob=0.1)
        router, engines = make_router(k=3, max_batch=4, seed=42)
        ridmap = {}
        outcomes = []
        futs = []
        with faults.installed(sched):
            for i in range(8):
                req = serving.GenerationRequest(
                    PROMPTS[i % len(PROMPTS)], max_new_tokens=3)
                ridmap[req.request_id] = i
                try:
                    futs.append((i, router.submit(req)))
                except EXPECTED_ERRORS as exc:
                    outcomes.append((i, "reject", type(exc).__name__))
        for eng in engines.values():
            eng.run()
        router.stop(drain=True, timeout=10)
        for i, f in enumerate_sorted(futs):
            try:
                outcomes.append((i, "ok", tuple(f.result(timeout=0).tokens)))
            except EXPECTED_ERRORS as exc:
                outcomes.append((i, "err", type(exc).__name__))
        norm_trace = [tuple(ridmap.get(x, x) for x in t)
                      for t in router.trace]
        return sorted(outcomes), norm_trace, list(sched.trace)

    def enumerate_sorted(futs):
        return sorted(futs, key=lambda p: p[0])

    first = run_once()
    second = run_once()
    assert first[0] == second[0]
    assert first[1] == second[1]
    assert first[2] == second[2]
    # the storm actually exercised the router sites
    assert any(s.startswith("router.") for s, _i, _k in first[2])
    assert any(t[0] in ("pick_fault", "forward_fault")
               for t in first[1])


def test_replica_kill_failover_proof(metrics):
    """K=3, one replica killed mid-batch: queued work fails over and
    completes bit-identical to the no-fault reference on the survivors;
    the killed replica's in-flight requests end with the typed
    DrainTimeout well inside their deadline budget; zero leaked pages
    anywhere; no token ever reaches a client twice."""
    router, engines = make_router(k=3, max_batch=4, max_queue=32)
    for eng in engines.values():
        eng.warmup()
    router.start()
    n_req, n_new = 18, 20
    streams = {i: [] for i in range(n_req)}
    reqs, futs = [], []

    def mk_stream(i):
        def cb(rid, tok):
            streams[i].append(tok)
            time.sleep(0.002)   # throttle decode: the kill must land
            # while queues are still populated on every replica
        return cb

    t_kill = None
    try:
        for i in range(n_req):
            req = serving.GenerationRequest(
                PROMPTS[i % len(PROMPTS)], max_new_tokens=n_new,
                deadline_s=30.0, stream=mk_stream(i))
            reqs.append(req)
            futs.append(router.submit(req))
        # wait until the victim provably holds BOTH in-flight slots and
        # queued work: the kill then exercises both recovery paths
        victim = "a"
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if engines[victim].active_requests > 0 and \
                    engines[victim].queue_depth > 0:
                break
            time.sleep(0.002)
        assert engines[victim].active_requests > 0
        assert engines[victim].queue_depth > 0
        t_kill = time.monotonic()
        router.drain_replica(victim, timeout=0.0, on_timeout="fail")
        assert victim not in router.in_rotation()

        killed_inflight, completed = 0, 0
        for i, f in enumerate(futs):
            try:
                res = f.result(timeout=60)
            except serving.DrainTimeout:
                killed_inflight += 1
                # typed, and resolved well inside the 30 s deadline
                assert time.monotonic() - t_kill < 30.0
                continue
            completed += 1
            ref = reference(reqs[i].prompt, n_new)
            assert res.tokens == ref                   # bit-identical
            assert streams[i] == res.tokens            # at-most-once
        assert killed_inflight > 0, "kill missed every in-flight slot"
        assert completed > 0
        assert completed + killed_inflight == n_req
        # the queued-on-victim work DID fail over (trace + metric agree)
        fails = [t for t in router.trace if t[0] == "failover"]
        assert fails
        assert obs.snapshot().get("serving.router.failovers_total", 0) \
            == len(fails)
        # failover happened only after the victim left the rotation
        out_at = router.trace.index(("out", victim))
        assert all(router.trace.index(t) > out_at for t in fails)
    finally:
        router.stop(drain=True, timeout=30)
    for eng in engines.values():
        assert eng.kv.outstanding_pages == 0
        assert eng.active_requests == 0 and eng.queue_depth == 0
    # terminal accounting: every submitted request resolved exactly once
    assert all(f.done() for f in futs)


class TestWriteFaultSeam:
    def _serve_one(self, metrics, sched, n_new=6):
        eng = make_engine().warmup()
        fd = serving.FrontDoor(eng)
        eng.start()
        try:
            with faults.installed(sched):
                status, tokens, terminals = _stream_request(
                    fd, PROMPTS[0], n_new)
        finally:
            eng.stop(drain=True, timeout=10)
            fd.close()
        return eng, status, tokens, terminals

    def test_single_write_fault_retried_invisibly(self, metrics):
        sched = faults.FaultSchedule()
        sched.error("http.write", on=[2])
        eng, status, tokens, terminals = self._serve_one(metrics, sched)
        assert status == 200
        assert tokens == dense_reference(PROMPTS[0], 6)
        assert terminals == [("done", terminals[0][1])]
        assert terminals[0][1]["tokens"] == tokens
        snap = obs.snapshot()
        assert snap.get("serving.http.write_retries_total", 0) == 1
        assert snap.get("serving.http.disconnects_total", 0) == 0

    def test_double_write_fault_is_client_disconnect(self, metrics):
        sched = faults.FaultSchedule()
        sched.error("http.write", on=[3, 4])
        eng, status, tokens, terminals = self._serve_one(
            metrics, sched, n_new=12)
        assert status == 200
        assert terminals == []                 # stream cut, no terminal
        assert tokens == dense_reference(PROMPTS[0], 12)[:2]
        # the request was cancelled upstream: slot + pages free, counted
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            snap = obs.snapshot()
            if snap.get("serving.requests_total", {}).get(
                    "status=cancelled", 0) >= 1:
                break
            time.sleep(0.01)
        snap = obs.snapshot()
        assert snap["serving.requests_total"].get("status=cancelled") == 1
        assert snap.get("serving.http.disconnects_total", 0) == 1
        assert eng.kv.outstanding_pages == 0
