"""paddle_tpu.serving — continuous batching over the paged KV cache.

All CPU-deterministic (no chip): the engine is driven with a tiny pure-jnp
toy LM whose next token is a *cache-dependent* greedy argmax — position-
weighted so paging mistakes (page permutation, stale bytes, wrong
write-back page) change the decoded sequence, not just some hidden state.
The dense single-sequence loop over the same two callables is the parity
oracle, exactly the role the bs=1 per-token loop plays for
``bench_generation.py --serving``.

Covers the ISSUE 7 acceptance surface:
* kv_cache unit behavior (alloc/free, page math, absmax-int8 grid) and
  the dense-vs-int8 logits-tolerance parity test;
* scheduler edge cases: queue overflow, FIFO no-slip-ahead, prefill
  token budget, cancel (queued and active), admission at full batch,
  page-pool gating, the zero-active-slot idle step;
* engine end-to-end greedy parity (batched == sequential) incl.
  continuous admission across evictions, on every kv dtype leg;
* deterministic fault injection through the existing
  ``resilience.FaultSchedule`` seams: a faulted slot fails ALONE —
  co-batched requests complete with bit-identical tokens.

ISSUE 8 ("serving under fire") adds the overload/containment surface:
* per-request deadlines + TTFT budgets: expired-in-queue requests shed
  with a typed ``DeadlineExceeded`` at the admission boundary, batchmates
  bit-identical to the no-fault run;
* load shedding: queue-wait-aware reject-on-arrival, the
  ``PADDLE_TPU_SERVING_MAX_QUEUE_WAIT`` hard cap, and
  ``serving.rejected_total{reason}`` visibility;
* the step watchdog: a hung compiled step (delay fault at
  ``serving.watchdog``) trips, its outputs are abandoned, and its slots
  recover via bounded prefill replay — zero stranded futures, zero
  leaked pages;
* graceful drain: ``stop(drain=True)`` finishes in-flight work, is
  idempotent, and ``on_timeout="requeue"`` resumes bit-identically after
  a restart.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (backend pin via conftest)
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.core.tensor import Tensor as T
from paddle_tpu.resilience import faults
from paddle_tpu.serving import kv_cache as kvc


# ---------------------------------------------------------------------------
# toy LM over the stacked-cache layout (L, 2, B, H, M, D)
# ---------------------------------------------------------------------------

V = 31
L, H, D, M = 2, 2, 4, 64

_W = jnp.asarray(np.linspace(-1.0, 1.0, D * V).reshape(D, V)
                 .astype(np.float32))
_POSW = (jnp.arange(M, dtype=jnp.float32) + 1.0) / M   # order-sensitivity


def _kv_of(tok_f):
    """token value -> (…, H, D) K/V payload; head- and dim-ramped so every
    cache axis carries signal."""
    ramp_d = (jnp.arange(D, dtype=jnp.float32) + 1.0) / D
    ramp_h = (jnp.arange(H, dtype=jnp.float32) + 1.0) / H
    base = (tok_f[..., None, None] + 1.0) / V
    return base * ramp_h[:, None] * ramp_d[None, :]


def _readout(cache00, valid):
    """(…, H, M, D) x (…, M) -> (…, V): the position-weighted "attention"
    readout. Masking by the write position mirrors the span mask of the
    real decode step — scratch-page garbage beyond ``t`` must never leak
    into logits."""
    feat = jnp.einsum("...hmd,...m,m->...d", cache00.astype(jnp.float32),
                      valid.astype(jnp.float32), _POSW)
    return feat @ _W


def toy_step(tok, cache, t):
    """(B, 1) int32, (L, 2, B, H, M, D), (B,) int32 -> next tok + cache."""
    tok_d, c, td = tok._data, cache._data, t._data.astype(jnp.int32)
    kv = _kv_of(tok_d[:, 0].astype(jnp.float32))         # (B, H, D)

    def wr(cb, kvb, tb):                                 # cb (L, 2, H, M, D)
        page = jnp.broadcast_to(kvb[None, None, :, None, :],
                                (L, 2, H, 1, D)).astype(cb.dtype)
        return jax.lax.dynamic_update_slice(cb, page, (0, 0, 0, tb, 0))

    c2 = jax.vmap(wr, in_axes=(2, 0, 0), out_axes=2)(c, kv, td)
    valid = jnp.arange(M)[None, :] <= td[:, None]        # (B, M)
    logits = _readout(c2[0, 0], valid)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    return T(nxt), T(c2)


def toy_prefill(ids, cache):
    """(1, Lp) int32, zeroed (L, 2, 1, H, M, D) -> first tok + cache."""
    idsd, c = ids._data, cache._data
    lp = idsd.shape[1]
    kv = jnp.transpose(_kv_of(idsd[0].astype(jnp.float32)), (1, 0, 2))
    c = c.at[:, :, 0, :, :lp, :].set(
        jnp.broadcast_to(kv, (L, 2, H, lp, D)).astype(c.dtype))
    valid = (jnp.arange(M) < lp)[None, :]
    logits = _readout(c[0, 0], valid)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    return T(nxt), T(c)


def dense_reference(prompt, n_new):
    """The bs=1 dense loop — same callables, no paging. Greedy oracle."""
    cache = T(jnp.zeros((L, 2, 1, H, M, D), jnp.float32))
    tok, cache = toy_prefill(T(jnp.asarray(prompt[None, :], jnp.int32)),
                             cache)
    toks = [int(np.asarray(tok._data)[0, 0])]
    t = int(prompt.size)
    for _ in range(n_new - 1):
        tok, cache = toy_step(tok, cache, T(jnp.asarray([t], jnp.int32)))
        toks.append(int(np.asarray(tok._data)[0, 0]))
        t += 1
    return toks


def make_engine(max_batch=4, page_size=16, kv_dtype="native", **kw):
    cfg = serving.ServingConfig(
        num_layers=L, num_heads=H, head_dim=D, max_len=M,
        max_batch=max_batch,
        buckets=tuple(b for b in (1, 4, 16) if b <= max_batch) or (max_batch,),
        page_size=page_size, kv_dtype=kv_dtype, **kw)
    return serving.Engine(toy_prefill, toy_step, cfg)


_RNG = np.random.default_rng(0)
PROMPTS = [_RNG.integers(0, V, (n,), dtype=np.int32)
           for n in (8, 8, 8, 5, 11)]


# the shared ``metrics`` fixture (fresh enabled obs registry) lives in
# tests/conftest.py


# ---------------------------------------------------------------------------
# kv_cache: page math + the int8 grid
# ---------------------------------------------------------------------------

class TestKVCache:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="multiple"):
            kvc.KVCacheConfig(num_layers=L, num_heads=H, head_dim=D,
                              max_len=60, page_size=16)
        with pytest.raises(ValueError, match="num_pages"):
            kvc.PagedKVCache(kvc.KVCacheConfig(
                num_layers=L, num_heads=H, head_dim=D, max_len=M,
                page_size=16))
        with pytest.raises(ValueError, match="scratch"):
            kvc.PagedKVCache(kvc.KVCacheConfig(
                num_layers=L, num_heads=H, head_dim=D, max_len=M,
                page_size=16, num_pages=1))

    def test_alloc_free_accounting(self):
        pool = kvc.PagedKVCache(kvc.KVCacheConfig(
            num_layers=L, num_heads=H, head_dim=D, max_len=M,
            page_size=16, num_pages=5))
        assert pool.free_pages == 4           # page 0 reserved
        ids = pool.alloc(3)
        assert len(ids) == 3 and 0 not in ids
        assert pool.alloc(2) is None          # partial claims never escape
        assert pool.free_pages == 1
        pool.free(ids)
        assert pool.free_pages == 4
        with pytest.raises(ValueError):
            pool.free(ids[:1])                # double free
        with pytest.raises(ValueError):
            pool.free([0])                    # scratch is not freeable

    def test_pages_for_rounding(self):
        pool = kvc.PagedKVCache(kvc.KVCacheConfig(
            num_layers=L, num_heads=H, head_dim=D, max_len=M,
            page_size=16, num_pages=5))
        assert pool.pages_for(1) == 1
        assert pool.pages_for(16) == 1
        assert pool.pages_for(17) == 2
        assert pool.pages_for(10_000) == 4    # capped at pages_per_slot

    def test_quantize_pages_absmax_grid(self):
        rng = np.random.default_rng(1)
        pages = jnp.asarray(rng.standard_normal(
            (3, L, 2, H, 16, D)).astype(np.float32)) * 4.0
        q, scale = kvc.quantize_pages(pages)
        assert q.dtype == jnp.int8 and scale.shape == (3, L, 2, H)
        absmax = np.max(np.abs(np.asarray(pages)), axis=(-2, -1))
        np.testing.assert_allclose(np.asarray(scale), absmax / 127.0,
                                   rtol=1e-6)
        # reconstruction error bounded by half a quantization step
        recon = np.asarray(q, np.float32) * np.asarray(scale)[..., None, None]
        err = np.abs(recon - np.asarray(pages))
        assert (err <= np.asarray(scale)[..., None, None] * 0.5 + 1e-6).all()
        # all-zero page quantizes with scale 1 (no 0/0)
        qz, sz = kvc.quantize_pages(jnp.zeros((1, L, 2, H, 16, D)))
        assert (np.asarray(sz) == 1.0).all() and (np.asarray(qz) == 0).all()

    def _roundtrip(self, kv_dtype):
        cfg = kvc.KVCacheConfig(num_layers=L, num_heads=H, head_dim=D,
                                max_len=M, page_size=16, num_pages=5,
                                kv_dtype=kv_dtype)
        pool = kvc.PagedKVCache(cfg)
        rng = np.random.default_rng(2)
        lp = 40                                # 3 pages, last partial
        dense = jnp.asarray(rng.standard_normal(
            (L, 2, 1, H, M, D)).astype(np.float32))
        dense = dense.at[:, :, :, :, lp:, :].set(0.0)
        page_ids = pool.alloc(pool.pages_for(lp))
        row = pool.table_row(page_ids)   # 3 real pages + 1 scratch entry;
        # the engine passes the FULL row — trailing scratch entries absorb
        # the masked-to-zero pages past the prompt
        p2, s2 = kvc.scatter_prefill_pages(
            dense, pool.pool, pool.scales, jnp.asarray(row),
            jnp.asarray(lp, jnp.int32), 16)
        back = kvc.gather_pages(p2, s2, jnp.asarray(row[None, :]),
                                jnp.float32)
        return np.asarray(dense[:, :, 0]), np.asarray(back[:, :, 0]), lp

    def test_gather_scatter_roundtrip_native(self):
        dense, back, lp = self._roundtrip("native")
        np.testing.assert_array_equal(back[..., :lp, :], dense[..., :lp, :])

    def test_int8_roundtrip_tolerance(self):
        dense, back, lp = self._roundtrip("int8")
        absmax = np.abs(dense).max()
        assert np.abs(back[..., :lp, :] - dense[..., :lp, :]).max() \
            <= absmax / 127.0 * 0.5 + 1e-6

    def test_int8_logits_tolerance_parity(self):
        """The ISSUE-named parity gate: logits computed off the paged-int8
        cache match the dense-cache logits within the absmax grid's error
        budget — and are NOT trivially identical."""
        dense, back, lp = self._roundtrip("int8")
        valid = (np.arange(M) < lp)[None, :]
        ref = np.asarray(_readout(jnp.asarray(dense[0, 0][None]),
                                  jnp.asarray(valid)))
        got = np.asarray(_readout(jnp.asarray(back[0, 0][None]),
                                  jnp.asarray(valid)))
        delta = np.abs(got - ref).max()
        assert 0.0 < delta <= 0.05 * np.abs(ref).max(), delta

    def test_scatter_token_masks_future_positions(self):
        """A freshly claimed page must not inherit stale pool bytes: the
        single-token write-back zeroes positions > t inside its page."""
        cfg = kvc.KVCacheConfig(num_layers=L, num_heads=H, head_dim=D,
                                max_len=M, page_size=16, num_pages=5)
        pool = jnp.full((5,) + cfg.page_shape(), 7.0, jnp.float32)  # stale
        tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        dense = jnp.asarray(np.random.default_rng(3).standard_normal(
            (L, 2, 1, H, M, D)).astype(np.float32))
        t = jnp.asarray([17], jnp.int32)       # page 1 of the slot
        p2, _ = kvc.scatter_token_page(dense, pool, None, tables, t, 16)
        page = np.asarray(p2)[2]               # pool page id 2
        np.testing.assert_array_equal(page[:, :, :, 2:, :], 0.0)
        np.testing.assert_array_equal(
            page[:, :, :, :2, :], np.asarray(dense)[:, :, 0, :, 16:18, :])


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_queue_overflow_rejects(self, metrics):
        s = serving.Scheduler(max_queue=2)
        s.submit(serving.GenerationRequest(PROMPTS[0]))
        s.submit(serving.GenerationRequest(PROMPTS[1]))
        with pytest.raises(serving.QueueFull):
            s.submit(serving.GenerationRequest(PROMPTS[2]))
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=rejected"] == 1
        assert s.queue_depth == 2

    def test_fifo_no_slip_ahead(self):
        s = serving.Scheduler()
        big = serving.GenerationRequest(PROMPTS[4])     # head
        small = serving.GenerationRequest(PROMPTS[3])
        s.submit(big), s.submit(small)
        # head does not fit -> nothing admitted, even though `small` would
        taken = s.next_admissions(
            2, lambda r: r.request_id != big.request_id)
        assert taken == [] and s.queue_depth == 2

    def test_budget_policy_bounds_prefill_tokens(self):
        s = serving.Scheduler(policy="budget", prefill_token_budget=12)
        for p in PROMPTS[:3]:                           # 8 + 8 + 8 tokens
            s.submit(serving.GenerationRequest(p))
        taken = s.next_admissions(3, lambda r: True)
        assert len(taken) == 1                          # 8 + 8 > 12
        taken = s.next_admissions(3, lambda r: True)
        assert len(taken) == 1
        # the first request always passes, even over budget: progress
        s2 = serving.Scheduler(policy="budget", prefill_token_budget=4)
        s2.submit(serving.GenerationRequest(PROMPTS[0]))
        assert len(s2.next_admissions(1, lambda r: True)) == 1

    def test_budget_policy_validation(self):
        with pytest.raises(ValueError):
            serving.Scheduler(policy="budget")
        with pytest.raises(ValueError):
            serving.Scheduler(policy="wrfq")

    def test_cancel_queued_resolves_future(self, metrics):
        s = serving.Scheduler()
        req = serving.GenerationRequest(PROMPTS[0])
        fut = s.submit(req)
        assert s.cancel(req.request_id) is True
        res = fut.result(timeout=1)
        assert res.finish_reason == "cancelled" and res.tokens == []
        assert s.queue_depth == 0

    def test_cancel_active_is_deferred_to_engine(self):
        s = serving.Scheduler()
        assert s.cancel(12345) is True                  # flagged, not lost
        assert s.take_cancelled_active() == {12345}
        assert s.take_cancelled_active() == set()       # drained

    def test_requeue_preserves_order(self):
        s = serving.Scheduler()
        reqs = [serving.GenerationRequest(p) for p in PROMPTS[:3]]
        for r in reqs:
            s.submit(r)
        taken = s.next_admissions(2, lambda r: True)
        s.requeue(taken)
        order = [p.request.request_id
                 for p in s.next_admissions(3, lambda r: True)]
        assert order == [r.request_id for r in reqs]


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------

class TestEngine:
    def test_batched_matches_sequential(self, metrics):
        """5 requests (> max_batch=4, mixed prompt lengths and budgets)
        through the continuously-batched engine decode the exact sequences
        of the dense bs=1 loop — the scan_greedy_parity gate, on CPU."""
        n_new = [6, 4, 6, 5, 3]
        eng = make_engine()
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=n))
                for p, n in zip(PROMPTS, n_new)]
        eng.run()
        for p, n, f in zip(PROMPTS, n_new, futs):
            res = f.result(timeout=5)
            assert res.finish_reason == "length"
            assert res.tokens == dense_reference(p, n)
            assert res.ttft_s is not None and res.tpot_s is not None
        # all pages returned to the pool
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=completed"] == 5
        assert snap["serving.tokens_total"] == sum(n_new)
        for hist in ("serving.ttft_seconds", "serving.tpot_seconds"):
            assert snap[hist]["count"] >= 1
        assert "serving.batch_utilization" in snap

    @pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
    def test_quantized_legs_match_reference(self, kv_dtype):
        """The storage-dtype legs keep greedy parity on the toy LM (logit
        gaps here dwarf the absmax grid error — the tolerance-level parity
        is pinned in test_int8_logits_tolerance_parity)."""
        eng = make_engine(kv_dtype=kv_dtype)
        assert eng.kv.pool.dtype == (jnp.int8 if kv_dtype == "int8"
                                     else jnp.bfloat16)
        assert (eng.kv.scales is not None) == (kv_dtype == "int8")
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=5))
                for p in PROMPTS[:3]]
        eng.run()
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 5)

    def test_env_knob_selects_kv_dtype(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "int8")
        eng = make_engine(kv_dtype="")          # defer to env
        assert eng.kv.config.quantized
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "bogus")
        with pytest.raises(ValueError, match="PADDLE_TPU_KV_DTYPE"):
            make_engine(kv_dtype="")

    def test_admission_at_full_batch(self):
        """max_batch=1: the second request waits queued, joins the moment
        the first evicts, and still decodes its exact reference sequence
        — continuous batching across an eviction boundary."""
        eng = make_engine(max_batch=1)
        f0 = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=3))
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=3))
        eng.step()
        assert eng.active_requests == 1 and eng.queue_depth == 1
        eng.run()
        assert f0.result(timeout=5).tokens == dense_reference(PROMPTS[0], 3)
        assert f1.result(timeout=5).tokens == dense_reference(PROMPTS[1], 3)

    def test_page_pool_gating(self):
        """A pool sized for ONE resident request serializes two: the
        second is admitted only after the first's pages free."""
        eng = make_engine(max_batch=4, num_pages=5)   # 4 usable = 1 slot
        n = M // 16                                    # whole-lifetime claim
        futs = [eng.submit(serving.GenerationRequest(
            PROMPTS[i], max_new_tokens=M - PROMPTS[i].size))
            for i in range(2)]
        eng.step()
        assert eng.active_requests == 1 and eng.queue_depth == 1
        assert eng.kv.free_pages == 4 - n
        eng.run()
        for f in futs:
            assert f.result(timeout=5).finish_reason == "length"
        assert eng.kv.free_pages == 4

    def test_admission_batch_no_overcommit_no_slip_ahead(self):
        """Pages must be reserved WITHIN one boundary's admission batch:
        6 usable pages, A and B need 4 each, C needs 2. B must stay
        queued (pool can't cover it beside A) and C must NOT slip past B
        even though C alone would fit — strict FIFO survives admission."""
        eng = make_engine(max_batch=4, num_pages=7)    # 6 usable
        fa = eng.submit(serving.GenerationRequest(      # 8+56=64 -> 4 pages
            PROMPTS[0], max_new_tokens=56))
        fb = eng.submit(serving.GenerationRequest(
            PROMPTS[1], max_new_tokens=56))
        fc = eng.submit(serving.GenerationRequest(      # 8+24=32 -> 2 pages
            PROMPTS[2], max_new_tokens=24))
        eng.step()
        assert eng.active_requests == 1                 # A alone
        assert eng.queue_depth == 2                     # B then C, in order
        assert eng.kv.free_pages == 2                   # no over-commit
        eng.run()
        assert fa.result(timeout=5).tokens == \
            dense_reference(PROMPTS[0], 56)
        assert fb.result(timeout=5).tokens == \
            dense_reference(PROMPTS[1], 56)
        assert fc.result(timeout=5).tokens == \
            dense_reference(PROMPTS[2], 24)
        assert eng.kv.free_pages == 6

    def test_submit_validation(self):
        eng = make_engine(max_queue=1)
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(serving.GenerationRequest(
                np.zeros(M, np.int32), max_new_tokens=1))
        eng.submit(serving.GenerationRequest(PROMPTS[0], max_new_tokens=4))
        with pytest.raises(serving.QueueFull):
            eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                 max_new_tokens=4))

    def test_zero_active_idle_step(self, metrics):
        eng = make_engine()
        assert eng.step() is False              # no device touch
        snap = obs.snapshot()
        assert snap.get("serving.steps_total") is None
        assert snap["serving.active_slots"] == 0

    def test_eviction_on_eos(self):
        ref = dense_reference(PROMPTS[0], 6)
        eos = ref[2]
        k = ref.index(eos)              # first occurrence stops the decode
        eng = make_engine()
        fut = eng.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=6, eos_token_id=eos))
        eng.run()
        res = fut.result(timeout=5)
        assert res.finish_reason == "eos" and res.tokens == ref[:k + 1]
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_cancel_active_mid_flight(self):
        eng = make_engine()
        req0 = serving.GenerationRequest(PROMPTS[0], max_new_tokens=8)
        f0 = eng.submit(req0)
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=8))
        eng.step()                              # both admitted + 1 token
        eng.step()
        eng.cancel(req0.request_id)
        eng.run()
        res0 = f0.result(timeout=5)
        assert res0.finish_reason == "cancelled"
        assert 1 <= len(res0.tokens) < 8        # partial transcript kept
        assert res0.tokens == dense_reference(PROMPTS[0], 8)[:len(res0.tokens)]
        assert f1.result(timeout=5).tokens == dense_reference(PROMPTS[1], 8)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_streaming_callback(self):
        seen = []
        eng = make_engine()
        req = serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=4,
            stream=lambda rid, tok: seen.append((rid, tok)))
        fut = eng.submit(req)
        eng.run()
        assert [t for _, t in seen] == fut.result(timeout=5).tokens
        assert {rid for rid, _ in seen} == {req.request_id}

    def test_raising_stream_callback_fails_request_alone(self):
        """A raising callback is the REQUEST's failure: its Future gets
        the exception and its pages free; batchmates are untouched (the
        step loop — incl. the start() thread — must not unwind)."""
        class CbErr(RuntimeError):
            pass

        def bad(rid, tok):
            raise CbErr("user callback exploded")

        eng = make_engine()
        f0 = eng.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=4, stream=bad))
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=4))
        eng.run()
        with pytest.raises(CbErr):
            f0.result(timeout=5)
        assert f1.result(timeout=5).tokens == dense_reference(PROMPTS[1], 4)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_background_thread_serving(self):
        eng = make_engine()
        eng.start()
        try:
            fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                       max_new_tokens=4))
            assert fut.result(timeout=30).tokens == \
                dense_reference(PROMPTS[0], 4)
        finally:
            eng.stop()

    def test_warmup_compiles_every_bucket(self):
        eng = make_engine().warmup(prompt_lens=[8])
        # warmup must leave the pool allocatable and the engine clean
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=3))
        eng.run()
        assert fut.result(timeout=5).tokens == dense_reference(PROMPTS[0], 3)


# ---------------------------------------------------------------------------
# fault injection: a faulted slot fails alone
# ---------------------------------------------------------------------------

class TestFaults:
    def _run_with_schedule(self, sched, n_new=5):
        eng = make_engine()
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=n_new)) for p in PROMPTS[:3]]
            eng.run()
        return eng, futs

    def test_faulted_slot_fails_alone(self, metrics):
        """serving.step fires once per (step, slot) in admission order:
        calls 2 and 5 target slot B at two consecutive boundaries — one
        retry, then failure. A and C must complete bit-identically."""
        sched = faults.FaultSchedule().error("serving.step", on=(2, 5))
        eng, (fa, fb, fc) = self._run_with_schedule(sched)
        with pytest.raises(faults.FaultInjected):
            fb.result(timeout=5)
        assert fa.result(timeout=5).tokens == dense_reference(PROMPTS[0], 5)
        assert fc.result(timeout=5).tokens == dense_reference(PROMPTS[2], 5)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1  # B freed
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=failed"] == 1
        assert snap["serving.requests_total"]["status=completed"] == 2
        # determinism: same schedule => same (site, call, kind) trace
        trace = [t for t in sched.trace if t[0] == "serving.step"]
        assert trace == [("serving.step", 2, "error"),
                         ("serving.step", 5, "error")]

    def test_step_fault_retries_once_then_completes(self, metrics):
        """A single fault only delays its slot one boundary; the transcript
        is still exact (functional cache state — nothing half-written)."""
        sched = faults.FaultSchedule().error("serving.step", on=(2,))
        _, futs = self._run_with_schedule(sched)
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 5)
        assert obs.snapshot()["serving.step_retries_total"] == 1

    def test_admit_fault_retry_then_success(self, metrics):
        sched = faults.FaultSchedule().error("serving.admit", on=(1,))
        eng, futs = self._run_with_schedule(sched)
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 5)
        assert obs.snapshot()["serving.admit_retries_total"] == 1

    def test_admit_double_fault_fails_request_frees_pages(self, metrics):
        sched = faults.FaultSchedule().error("serving.admit", on=(1, 2))
        eng, (fa, fb, fc) = self._run_with_schedule(sched)
        with pytest.raises(faults.FaultInjected):
            fa.result(timeout=5)
        assert fb.result(timeout=5).tokens == dense_reference(PROMPTS[1], 5)
        assert fc.result(timeout=5).tokens == dense_reference(PROMPTS[2], 5)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1


# ---------------------------------------------------------------------------
# ISSUE 18: admission/drain containment — fixes found by the
# resource-discipline lint pass. An unexpected raise cutting through
# admission or drain must not strand futures, leak pages, or drop
# queued requests.
# ---------------------------------------------------------------------------

class TestAdmissionContainment:
    def test_admit_one_raise_fails_current_and_requeues_tail(self, metrics):
        eng = make_engine()
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=3))
                for p in PROMPTS[:3]]
        real = eng._admit_one
        calls = []

        def flaky(pending):
            calls.append(pending)
            if len(calls) == 2:
                raise RuntimeError("admission bug")
            return real(pending)

        eng._admit_one = flaky
        try:
            with pytest.raises(RuntimeError, match="admission bug"):
                eng._admit()
        finally:
            eng._admit_one = real
        # first admitted, second's future carries the bug, third went
        # back in order — nothing stranded, nothing dropped
        assert len(eng._slots) == 1 and not futs[0].done()
        with pytest.raises(RuntimeError, match="admission bug"):
            futs[1].result(timeout=1)
        assert not futs[2].done()
        assert eng._admit() is True
        assert len(eng._slots) == 2

    def test_host_tail_raise_is_contained_as_failed_admission(
            self, metrics, monkeypatch):
        eng = make_engine()
        free0 = eng.kv.free_pages

        def wedged(*a, **k):
            raise RuntimeError("host sync wedged")

        monkeypatch.setattr(eng, "_set_pool", wedged)
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=3))
        # the pool swap / first-token host read raising is just another
        # failed admission: pages freed, future resolved, no slot
        assert eng._admit() is False
        with pytest.raises(RuntimeError, match="host sync wedged"):
            fut.result(timeout=1)
        assert eng.kv.free_pages == free0 and eng._slots == []
        assert obs.snapshot()["serving.requests_total"][
            "status=failed"] == 1.0

    def test_drain_fail_settles_futures_before_telemetry(
            self, metrics, monkeypatch):
        from paddle_tpu.serving import engine as engine_mod
        eng = make_engine()
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=3))

        class _DownObs:
            def __getattr__(self, name):
                return getattr(obs, name)

            def inc(self, *a, **k):
                raise RuntimeError("metrics sink down")

        monkeypatch.setattr(engine_mod, "_obs", _DownObs())
        # the straggler sweep's contract is "no Future stays stranded":
        # the queued request's future is settled even though the very
        # first telemetry call blows up
        with pytest.raises(RuntimeError, match="metrics sink down"):
            eng._resolve_stragglers("fail")
        assert isinstance(fut.exception(timeout=1), serving.EngineStopped)


# ---------------------------------------------------------------------------
# ISSUE 8: deadlines, load shedding, queue-wait accounting
# ---------------------------------------------------------------------------

class TestDeadlinesAndShedding:
    def test_request_budget_validation(self):
        with pytest.raises(ValueError, match="deadline_s"):
            serving.GenerationRequest(PROMPTS[0], deadline_s=0.0)
        with pytest.raises(ValueError, match="ttft_budget_s"):
            serving.GenerationRequest(PROMPTS[0], ttft_budget_s=-1.0)

    def test_queue_full_message_has_depth_and_capacity(self, metrics):
        s = serving.Scheduler(max_queue=2)
        s.submit(serving.GenerationRequest(PROMPTS[0]))
        s.submit(serving.GenerationRequest(PROMPTS[1]))
        with pytest.raises(serving.QueueFull, match=r"2/2"):
            s.submit(serving.GenerationRequest(PROMPTS[2]))
        snap = obs.snapshot()
        assert snap["serving.rejected_total"]["reason=queue_full"] == 1

    def test_queue_wait_histogram_recorded_on_every_admission(self, metrics):
        eng = make_engine()
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=3))
                for p in PROMPTS[:3]]
        eng.run()
        for f in futs:
            f.result(timeout=5)
        snap = obs.snapshot()
        assert snap["serving.queue_wait_seconds"]["count"] == 3

    def test_expired_in_queue_sheds_batchmates_bit_identical(self, metrics):
        """Acceptance (a): under a scripted schedule, the expired request
        sheds with a typed DeadlineExceeded at the admission boundary —
        never mid-batch — and its batchmates' outputs are bit-identical
        to the no-fault run."""
        ref = {i: dense_reference(PROMPTS[i], 5) for i in (0, 2)}
        # the scripted delay holds admission long enough for B's TTFT
        # budget to expire while it queues behind A (max_batch=1)
        sched = faults.FaultSchedule().delay("serving.admit", on=(1,),
                                             seconds=0.15)
        eng = make_engine(max_batch=1)
        fa = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=5))
        fb = eng.submit(serving.GenerationRequest(
            PROMPTS[1], max_new_tokens=5, ttft_budget_s=0.05))
        fc = eng.submit(serving.GenerationRequest(PROMPTS[2],
                                                  max_new_tokens=5))
        with faults.installed(sched):
            eng.run()
        with pytest.raises(serving.DeadlineExceeded, match="expired in "
                                                           "queue"):
            fb.result(timeout=5)
        assert fa.result(timeout=5).tokens == ref[0]
        assert fc.result(timeout=5).tokens == ref[2]
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        snap = obs.snapshot()
        assert snap["serving.rejected_total"]["reason=deadline"] == 1
        assert snap["serving.requests_total"]["status=shed"] == 1
        assert snap["serving.requests_total"]["status=completed"] == 2
        # determinism: same scripted schedule => same trace
        assert sched.trace == [("serving.admit", 1, "delay")]

    def test_shed_on_arrival_when_estimated_wait_exceeds_budget(
            self, metrics):
        import time as _t
        s = serving.Scheduler()
        s._ewma_interval = 5.0              # recent drain: 5 s per pop
        s.submit(serving.GenerationRequest(PROMPTS[0]),
                 submit_time=_t.monotonic())   # no budget: queued
        with pytest.raises(serving.DeadlineExceeded, match="shed on "
                                                           "arrival"):
            s.submit(serving.GenerationRequest(PROMPTS[1], deadline_s=1.0),
                     submit_time=_t.monotonic())
        assert s.queue_depth == 1
        snap = obs.snapshot()
        assert snap["serving.rejected_total"]["reason=shed"] == 1
        # a request with headroom still queues
        s.submit(serving.GenerationRequest(PROMPTS[2], deadline_s=60.0),
                 submit_time=_t.monotonic())
        assert s.queue_depth == 2

    def test_max_queue_wait_hard_cap_sheds(self, metrics):
        import time as _t
        s = serving.Scheduler(max_queue_wait_s=0.01)
        fut = s.submit(serving.GenerationRequest(PROMPTS[0]),
                       submit_time=_t.monotonic() - 1.0)   # waited 1 s
        assert s.next_admissions(4, lambda r: True) == []
        with pytest.raises(serving.DeadlineExceeded, match="max_queue_wait"):
            fut.result(timeout=1)
        assert obs.snapshot()["serving.rejected_total"]["reason=shed"] == 1

    def test_requeued_replay_not_shed_by_met_ttft_or_queue_cap(
            self, metrics):
        """Queue-wait accounting must not charge a replayed request for
        its time DECODING: a met TTFT budget cannot expire retroactively,
        and max_queue_wait_s measures this queue stint (queued_at resets
        on requeue), not request age."""
        import time as _t
        from concurrent.futures import Future
        from paddle_tpu.serving.scheduler import _Pending
        s = serving.Scheduler(max_queue_wait_s=0.5)
        old = _t.monotonic() - 10.0       # "admitted 10 s ago, decoding"
        p = _Pending(serving.GenerationRequest(PROMPTS[0],
                                               ttft_budget_s=1.0),
                     Future(), submit_time=old, queued_at=old,
                     ttft_done=True, replays=1, replay_tokens=[3, 4])
        s.requeue([p])                    # crash-recovery re-queue NOW
        assert s.shed_expired() == 0      # neither budget fires
        assert s.queue_depth == 1
        # an end-to-end deadline_s, by contrast, still counts total age
        q = _Pending(serving.GenerationRequest(PROMPTS[1], deadline_s=5.0),
                     Future(), submit_time=old, queued_at=old,
                     ttft_done=True, replays=1)
        s.requeue([q])
        assert s.shed_expired() == 1
        with pytest.raises(serving.DeadlineExceeded):
            q.future.result(timeout=1)

    def test_ewma_wait_model_not_poisoned_by_idle_gap(self):
        """Draining the queue drops the pop-interval reference: the first
        admission after an idle lull must not fold the idle time into the
        drain-rate estimate and shed healthy traffic."""
        s = serving.Scheduler()
        for p in PROMPTS[:2]:
            s.submit(serving.GenerationRequest(p))
        s.next_admissions(2, lambda r: True)   # queue drained
        # BOTH halves of the wait model reset: a drain rate learned under
        # an earlier load regime must not shed the next burst's first
        # requests against an empty queue
        assert s._last_pop_t is None and s._ewma_interval is None
        # ... idle lull happens here; next burst starts a fresh estimate
        s.submit(serving.GenerationRequest(PROMPTS[2]))
        s.next_admissions(1, lambda r: True)
        assert s._ewma_interval is None or s._ewma_interval < 1.0

    def test_ewma_measures_per_request_interval_on_batched_pops(self):
        """One EWMA sample per boundary, dt divided by the pop count: a
        4-wide admission 8 s after the last boundary means ~2 s per
        request — NOT one 8 s sample followed by three dt=0 samples that
        collapse the estimate and disarm shed-on-arrival under exactly
        the batched admission the engine is built for."""
        import time as _t
        s = serving.Scheduler()
        for p in PROMPTS:                       # 5 queued; pop 4, 1 stays
            s.submit(serving.GenerationRequest(p))
        s._last_pop_t = _t.monotonic() - 8.0    # last boundary: 8 s ago
        taken = s.next_admissions(4, lambda r: True)
        assert len(taken) == 4 and s.queue_depth == 1
        assert 1.5 < s._ewma_interval < 2.5     # ~8/4, not ~0

    def test_withdraw_removes_silently(self, metrics):
        s = serving.Scheduler()
        req = serving.GenerationRequest(PROMPTS[0])
        fut = s.submit(req)
        pend = s.withdraw(req.request_id)
        assert pend is not None and pend.future is fut
        assert not fut.done() and s.queue_depth == 0
        assert s.withdraw(req.request_id) is None      # already gone
        snap = obs.snapshot()
        assert "serving.requests_total" not in snap    # no accounting

    def test_env_knobs_resolve_into_config(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SERVING_MAX_QUEUE_WAIT", "0.25")
        monkeypatch.setenv("PADDLE_TPU_SERVING_WATCHDOG_S", "1.5")
        cfg = serving.ServingConfig(num_layers=L, num_heads=H, head_dim=D,
                                    max_len=M, max_batch=1, buckets=(1,))
        assert cfg.max_queue_wait_s == 0.25 and cfg.watchdog_s == 1.5
        # explicit 0 forces OFF even with the env set
        cfg0 = serving.ServingConfig(num_layers=L, num_heads=H, head_dim=D,
                                     max_len=M, max_batch=1, buckets=(1,),
                                     watchdog_s=0, max_queue_wait_s=0)
        assert cfg0.max_queue_wait_s is None and cfg0.watchdog_s is None

    def test_deadline_scope_propagates_request_deadline(self):
        from concurrent.futures import Future
        from paddle_tpu.resilience import current_deadline
        from paddle_tpu.serving.scheduler import _Pending
        eng = make_engine()
        p = _Pending(serving.GenerationRequest(PROMPTS[0], deadline_s=5.0),
                     Future(), submit_time=100.0)
        with eng._deadline_ctx([p]):
            assert current_deadline() == pytest.approx(105.0)
        q = _Pending(serving.GenerationRequest(PROMPTS[1]), Future(),
                     submit_time=100.0)
        with eng._deadline_ctx([q]):
            assert current_deadline() is None
        # batched: the tightest deadline governs
        r = _Pending(serving.GenerationRequest(PROMPTS[2], deadline_s=2.0),
                     Future(), submit_time=100.0)
        with eng._deadline_ctx([p, q, r]):
            assert current_deadline() == pytest.approx(102.0)


# ---------------------------------------------------------------------------
# ISSUE 8: watchdog + bounded prefill replay
# ---------------------------------------------------------------------------

class TestWatchdogRecovery:
    def test_watchdog_unit_trip_and_zombie(self, metrics):
        import time as _t
        wd = serving.StepWatchdog(0.03)
        try:
            gen = wd.arm()
            _t.sleep(0.15)                   # > 2x budget: hung then zombie
            assert wd.disarm(gen) == "zombie"
            gen2 = wd.arm()
            assert wd.disarm(gen2) is None   # came back in time
        finally:
            wd.stop()
        snap = obs.snapshot()
        assert snap["serving.watchdog_trips_total"]["kind=hung"] == 1
        assert snap["serving.watchdog_trips_total"]["kind=zombie"] == 1

    def test_watchdog_trip_recovers_via_replay(self, metrics):
        """Acceptance (b): a hung step (scripted delay at the
        serving.watchdog seam) trips the watchdog; its outputs are
        abandoned and BOTH slots recover through bounded prefill replay —
        the full transcripts stay bit-identical, no future strands, the
        pool free-list returns to full."""
        # budget generous vs CPU scheduling noise (a GC pause must not
        # look hung), delay 4x the budget so the trip is unambiguous;
        # warmup precompiles the decode buckets so a cold compile (no
        # shared disk cache since the conftest change) cannot read as a
        # phantom hung step
        sched = faults.FaultSchedule().delay("serving.watchdog", on=(2,),
                                             seconds=1.0)
        eng = make_engine(watchdog_s=0.25, max_replays=1).warmup()
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=4)) for p in PROMPTS[:2]]
            eng.run()
        eng.stop()                        # reap the watchdog poll thread
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 4)
        assert eng.active_requests == 0 and eng.queue_depth == 0
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        assert eng.kv.outstanding_pages == 0
        snap = obs.snapshot()
        assert snap["serving.watchdog_trips_total"]["kind=hung"] >= 1
        assert snap["serving.replays_total"] == 2
        assert snap["serving.requests_total"]["status=completed"] == 2
        assert sched.trace == [("serving.watchdog", 2, "delay")]

    def test_device_fault_single_retry_still_succeeds(self, metrics):
        sched = faults.FaultSchedule().error("serving.watchdog", on=(1,))
        eng = make_engine()
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=4)) for p in PROMPTS[:2]]
            eng.run()
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 4)
        assert obs.snapshot()["serving.step_retries_total"] == 1
        assert obs.snapshot().get("serving.replays_total") is None

    def test_device_double_fault_replays_not_fails(self, metrics):
        """The crash-recovery contract change: an unrecoverable batched
        step (fault + failed retry) used to fail EVERY in-flight request;
        now the slots replay (prompt + tokens so far) and complete
        bit-identically."""
        sched = faults.FaultSchedule().error("serving.watchdog", on=(2, 3))
        eng = make_engine(max_replays=1)
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=4)) for p in PROMPTS[:2]]
            eng.run()
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 4)
        snap = obs.snapshot()
        assert snap["serving.replays_total"] == 2
        assert snap["serving.requests_total"]["status=completed"] == 2
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_replay_budget_exhausted_fails_with_pages_reclaimed(
            self, metrics):
        sched = faults.FaultSchedule().error("serving.watchdog",
                                             on=(2, 3, 4, 5))
        eng = make_engine(max_replays=0)      # no replay budget at all
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=4)) for p in PROMPTS[:2]]
            eng.run()
        for f in futs:
            with pytest.raises(faults.FaultInjected):
                f.result(timeout=5)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=failed"] == 2
        assert snap.get("serving.replays_total") is None


# ---------------------------------------------------------------------------
# ISSUE 8: graceful drain
# ---------------------------------------------------------------------------

class TestDrain:
    def test_stop_drain_completes_inflight_and_is_idempotent(self, metrics):
        """Acceptance (c): drain finishes the admitted sequences, resolves
        everything, returns every page, and a second stop is a no-op."""
        eng = make_engine()
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=5))
                for p in PROMPTS[:3]]
        eng.step()                        # all three admitted
        assert eng.active_requests == 3
        eng.stop(drain=True, timeout=30)
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=1).tokens == dense_reference(p, 5)
        assert eng.active_requests == 0 and eng.queue_depth == 0
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        eng.stop(drain=True, timeout=1)   # idempotent: nothing to resolve
        eng.stop()
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=completed"] == 3

    def test_stop_drain_from_background_thread(self):
        import threading as _th
        seen = _th.Event()
        eng = make_engine()
        eng.start()
        fut = eng.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=4,
            stream=lambda rid, tok: seen.set()))
        assert seen.wait(timeout=30)      # admitted before we drain
        eng.stop(drain=True, timeout=30)
        assert fut.result(timeout=1).tokens == dense_reference(PROMPTS[0], 4)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_submit_while_draining_raises(self, metrics):
        eng = make_engine()
        eng.stop(drain=True, timeout=1)
        with pytest.raises(serving.EngineStopped):
            eng.submit(serving.GenerationRequest(PROMPTS[0]))
        assert obs.snapshot()["serving.rejected_total"]["reason=shed"] == 1

    def test_drain_timeout_fail_resolves_every_future(self, metrics):
        """timeout=0 with work in flight: the straggler fails with
        DrainTimeout, the never-admitted request with EngineStopped — no
        stranded futures, no leaked pages."""
        eng = make_engine(max_batch=1)
        f0 = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=40))
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=40))
        eng.step()                        # A admitted, B queued
        eng.stop(drain=True, timeout=0)
        with pytest.raises(serving.DrainTimeout):
            f0.result(timeout=1)
        with pytest.raises(serving.EngineStopped):
            f1.result(timeout=1)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=failed"] == 1
        assert snap["serving.requests_total"]["status=shed"] == 1

    def test_stop_without_budget_is_bounded_despite_wedged_loop(
            self, monkeypatch):
        """ISSUE 19 regression (surfaced by the unbounded-wait lint rule):
        ``stop()`` with NO drain budget must still return when the loop
        thread is wedged inside a hung compiled call — the join is
        bounded by PADDLE_TPU_STOP_JOIN_S and the zombie abandoned,
        exactly as the budgeted path always promised."""
        import threading as _th
        import time as _t
        monkeypatch.setenv("PADDLE_TPU_STOP_JOIN_S", "0.2")
        eng = make_engine()
        release = _th.Event()
        wedged = _th.Thread(target=release.wait, daemon=True)
        wedged.start()
        eng._thread = wedged        # stands in for a wedged loop thread
        t0 = _t.monotonic()
        eng.stop()                  # timeout=None: used to join forever
        assert _t.monotonic() - t0 < 5.0
        assert eng._thread is None  # the zombie was abandoned
        release.set()
        wedged.join(timeout=1)

    def test_run_after_requeue_drain_resumes_not_spins(self):
        """run() clears the draining latch like start() does: the offline
        drive mode after stop(drain=True, on_timeout='requeue') must
        resume the requeued work, not refuse admission forever."""
        eng = make_engine(max_batch=1)
        f0 = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=6))
        eng.step()
        eng.stop(drain=True, timeout=0, on_timeout="requeue")
        assert not f0.done() and eng.queue_depth == 1
        eng.run()                         # would busy-spin if still latched
        assert f0.result(timeout=1).tokens == dense_reference(PROMPTS[0], 6)
        assert eng.kv.outstanding_pages == 0

    def test_drain_timeout_requeue_then_restart_resumes_bit_identical(self):
        eng = make_engine(max_batch=1)
        f0 = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=6))
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=6))
        eng.step()                        # A admitted + 1 token
        eng.stop(drain=True, timeout=0, on_timeout="requeue")
        assert not f0.done() and not f1.done()
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        assert eng.queue_depth == 2      # A (with its replay token) then B
        eng.start()                       # clears the draining latch
        try:
            assert f0.result(timeout=30).tokens == \
                dense_reference(PROMPTS[0], 6)
            assert f1.result(timeout=30).tokens == \
                dense_reference(PROMPTS[1], 6)
        finally:
            eng.stop()
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_drain_readmits_crash_recovery_requeues(self, metrics):
        """A double-faulted step DURING a graceful drain must not turn an
        admitted, recoverable request into a never-admitted EngineStopped:
        the drain re-admits crash-recovery requeues (replay_only
        admission) and finishes the sequence."""
        # call 1 fires at the first decode attempt; 2 at its retry — the
        # slot is requeued with replay tokens while the drain is running
        sched = faults.FaultSchedule().error("serving.watchdog", on=(1, 2))
        eng = make_engine(max_batch=1, max_replays=1)
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=5))
        eng.step()                        # admitted (prefill + 1st token)
        with faults.installed(sched):
            eng.stop(drain=True, timeout=30)
        assert fut.result(timeout=1).tokens == dense_reference(PROMPTS[0], 5)
        assert eng.kv.outstanding_pages == 0
        snap = obs.snapshot()
        assert snap["serving.replays_total"] == 1
        assert snap["serving.requests_total"]["status=completed"] == 1
        assert sched.trace == [("serving.watchdog", 1, "error"),
                               ("serving.watchdog", 2, "error")]

    def test_drain_zero_budget_fails_replay_as_drain_timeout(self, metrics):
        """If the drain budget runs out before a crash-recovery requeue
        re-admits, its Future fails with DrainTimeout / status=failed —
        it was admitted once, so reporting it as never-admitted overload
        shed (EngineStopped / status=shed) would lie to the operator."""
        sched = faults.FaultSchedule().error("serving.watchdog", on=(1, 2))
        eng = make_engine(max_batch=1, max_replays=1)
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=5))
        eng.step()
        with faults.installed(sched):
            eng.step()                    # fault + failed retry: requeued
        assert eng.queue_depth == 1 and eng.active_requests == 0
        eng.stop(drain=True, timeout=0)
        with pytest.raises(serving.DrainTimeout, match="replay"):
            fut.result(timeout=1)
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=failed"] == 1
        assert "status=shed" not in snap.get("serving.requests_total", {})
        assert eng.kv.outstanding_pages == 0

    def test_stop_from_stream_callback_raises_not_wedges(self):
        """stop() on the engine step thread would be the loop asking
        itself to drain — with no timeout it would hang forever. The
        guard raises instead; per the stream-callback contract the error
        fails THAT request alone and the loop survives."""
        eng = make_engine()
        eng.start()
        try:
            fut = eng.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=4,
                stream=lambda rid, tok: eng.stop(drain=True)))
            with pytest.raises(RuntimeError, match="step thread"):
                fut.result(timeout=30)
            # the loop survived the callback's failure: new work completes
            f2 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                      max_new_tokens=4))
            assert f2.result(timeout=30).tokens == \
                dense_reference(PROMPTS[1], 4)
        finally:
            eng.stop()
        assert eng.kv.outstanding_pages == 0

    @pytest.mark.slow
    def test_stop_join_bounded_when_step_wedged(self, metrics):
        """Acceptance hardening: stop(drain=True, timeout=...) must
        return even when the loop thread is wedged inside a hung compiled
        call (the exact zombie case the watchdog classifies) — bounded
        join, stragglers resolved without it, late return abandoned
        without double-free."""
        import time as _t
        sched = faults.FaultSchedule().delay("serving.watchdog", on=(2,),
                                             seconds=3.0)
        eng = make_engine(max_batch=1)
        with faults.installed(sched):
            eng.start()
            fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                       max_new_tokens=40))
            while not fut.done() and eng.active_requests == 0:
                _t.sleep(0.01)            # admitted before we drain
            t0 = _t.monotonic()
            eng.stop(drain=True, timeout=0.2)
            # returned well before the 3 s hang released (0.2 budget +
            # 1 s join grace + slack)
            assert _t.monotonic() - t0 < 2.5
            with pytest.raises(serving.DrainTimeout):
                fut.result(timeout=1)
            assert eng.kv.outstanding_pages == 0
            _t.sleep(3.2)                 # let the wedged step return
        # the late return was abandoned: no double-free, no re-resolution
        assert eng.kv.outstanding_pages == 0
        assert fut.exception(timeout=0) is not None

    def test_injected_drain_fault_degrades_to_immediate_stop(self, metrics):
        """An error at the serving.drain seam must not strand anything:
        the drain degrades to an immediate stop and still resolves every
        future."""
        sched = faults.FaultSchedule().error("serving.drain", on=(1,))
        eng = make_engine(max_batch=1)
        f0 = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=40))
        eng.step()
        with faults.installed(sched):
            eng.stop(drain=True, timeout=30)
        with pytest.raises(serving.DrainTimeout):
            f0.result(timeout=1)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        assert sched.trace == [("serving.drain", 1, "error")]
