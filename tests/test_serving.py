"""paddle_tpu.serving — continuous batching over the paged KV cache.

All CPU-deterministic (no chip): the engine is driven with a tiny pure-jnp
toy LM whose next token is a *cache-dependent* greedy argmax — position-
weighted so paging mistakes (page permutation, stale bytes, wrong
write-back page) change the decoded sequence, not just some hidden state.
The dense single-sequence loop over the same two callables is the parity
oracle, exactly the role the bs=1 per-token loop plays for
``bench_generation.py --serving``.

Covers the ISSUE 7 acceptance surface:
* kv_cache unit behavior (alloc/free, page math, absmax-int8 grid) and
  the dense-vs-int8 logits-tolerance parity test;
* scheduler edge cases: queue overflow, FIFO no-slip-ahead, prefill
  token budget, cancel (queued and active), admission at full batch,
  page-pool gating, the zero-active-slot idle step;
* engine end-to-end greedy parity (batched == sequential) incl.
  continuous admission across evictions, on every kv dtype leg;
* deterministic fault injection through the existing
  ``resilience.FaultSchedule`` seams: a faulted slot fails ALONE —
  co-batched requests complete with bit-identical tokens.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (backend pin via conftest)
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.core.tensor import Tensor as T
from paddle_tpu.resilience import faults
from paddle_tpu.serving import kv_cache as kvc


# ---------------------------------------------------------------------------
# toy LM over the stacked-cache layout (L, 2, B, H, M, D)
# ---------------------------------------------------------------------------

V = 31
L, H, D, M = 2, 2, 4, 64

_W = jnp.asarray(np.linspace(-1.0, 1.0, D * V).reshape(D, V)
                 .astype(np.float32))
_POSW = (jnp.arange(M, dtype=jnp.float32) + 1.0) / M   # order-sensitivity


def _kv_of(tok_f):
    """token value -> (…, H, D) K/V payload; head- and dim-ramped so every
    cache axis carries signal."""
    ramp_d = (jnp.arange(D, dtype=jnp.float32) + 1.0) / D
    ramp_h = (jnp.arange(H, dtype=jnp.float32) + 1.0) / H
    base = (tok_f[..., None, None] + 1.0) / V
    return base * ramp_h[:, None] * ramp_d[None, :]


def _readout(cache00, valid):
    """(…, H, M, D) x (…, M) -> (…, V): the position-weighted "attention"
    readout. Masking by the write position mirrors the span mask of the
    real decode step — scratch-page garbage beyond ``t`` must never leak
    into logits."""
    feat = jnp.einsum("...hmd,...m,m->...d", cache00.astype(jnp.float32),
                      valid.astype(jnp.float32), _POSW)
    return feat @ _W


def toy_step(tok, cache, t):
    """(B, 1) int32, (L, 2, B, H, M, D), (B,) int32 -> next tok + cache."""
    tok_d, c, td = tok._data, cache._data, t._data.astype(jnp.int32)
    kv = _kv_of(tok_d[:, 0].astype(jnp.float32))         # (B, H, D)

    def wr(cb, kvb, tb):                                 # cb (L, 2, H, M, D)
        page = jnp.broadcast_to(kvb[None, None, :, None, :],
                                (L, 2, H, 1, D)).astype(cb.dtype)
        return jax.lax.dynamic_update_slice(cb, page, (0, 0, 0, tb, 0))

    c2 = jax.vmap(wr, in_axes=(2, 0, 0), out_axes=2)(c, kv, td)
    valid = jnp.arange(M)[None, :] <= td[:, None]        # (B, M)
    logits = _readout(c2[0, 0], valid)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    return T(nxt), T(c2)


def toy_prefill(ids, cache):
    """(1, Lp) int32, zeroed (L, 2, 1, H, M, D) -> first tok + cache."""
    idsd, c = ids._data, cache._data
    lp = idsd.shape[1]
    kv = jnp.transpose(_kv_of(idsd[0].astype(jnp.float32)), (1, 0, 2))
    c = c.at[:, :, 0, :, :lp, :].set(
        jnp.broadcast_to(kv, (L, 2, H, lp, D)).astype(c.dtype))
    valid = (jnp.arange(M) < lp)[None, :]
    logits = _readout(c[0, 0], valid)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    return T(nxt), T(c)


def dense_reference(prompt, n_new):
    """The bs=1 dense loop — same callables, no paging. Greedy oracle."""
    cache = T(jnp.zeros((L, 2, 1, H, M, D), jnp.float32))
    tok, cache = toy_prefill(T(jnp.asarray(prompt[None, :], jnp.int32)),
                             cache)
    toks = [int(np.asarray(tok._data)[0, 0])]
    t = int(prompt.size)
    for _ in range(n_new - 1):
        tok, cache = toy_step(tok, cache, T(jnp.asarray([t], jnp.int32)))
        toks.append(int(np.asarray(tok._data)[0, 0]))
        t += 1
    return toks


def make_engine(max_batch=4, page_size=16, kv_dtype="native", **kw):
    cfg = serving.ServingConfig(
        num_layers=L, num_heads=H, head_dim=D, max_len=M,
        max_batch=max_batch,
        buckets=tuple(b for b in (1, 4, 16) if b <= max_batch) or (max_batch,),
        page_size=page_size, kv_dtype=kv_dtype, **kw)
    return serving.Engine(toy_prefill, toy_step, cfg)


_RNG = np.random.default_rng(0)
PROMPTS = [_RNG.integers(0, V, (n,), dtype=np.int32)
           for n in (8, 8, 8, 5, 11)]


@pytest.fixture()
def metrics():
    obs.enable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()


# ---------------------------------------------------------------------------
# kv_cache: page math + the int8 grid
# ---------------------------------------------------------------------------

class TestKVCache:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="multiple"):
            kvc.KVCacheConfig(num_layers=L, num_heads=H, head_dim=D,
                              max_len=60, page_size=16)
        with pytest.raises(ValueError, match="num_pages"):
            kvc.PagedKVCache(kvc.KVCacheConfig(
                num_layers=L, num_heads=H, head_dim=D, max_len=M,
                page_size=16))
        with pytest.raises(ValueError, match="scratch"):
            kvc.PagedKVCache(kvc.KVCacheConfig(
                num_layers=L, num_heads=H, head_dim=D, max_len=M,
                page_size=16, num_pages=1))

    def test_alloc_free_accounting(self):
        pool = kvc.PagedKVCache(kvc.KVCacheConfig(
            num_layers=L, num_heads=H, head_dim=D, max_len=M,
            page_size=16, num_pages=5))
        assert pool.free_pages == 4           # page 0 reserved
        ids = pool.alloc(3)
        assert len(ids) == 3 and 0 not in ids
        assert pool.alloc(2) is None          # partial claims never escape
        assert pool.free_pages == 1
        pool.free(ids)
        assert pool.free_pages == 4
        with pytest.raises(ValueError):
            pool.free(ids[:1])                # double free
        with pytest.raises(ValueError):
            pool.free([0])                    # scratch is not freeable

    def test_pages_for_rounding(self):
        pool = kvc.PagedKVCache(kvc.KVCacheConfig(
            num_layers=L, num_heads=H, head_dim=D, max_len=M,
            page_size=16, num_pages=5))
        assert pool.pages_for(1) == 1
        assert pool.pages_for(16) == 1
        assert pool.pages_for(17) == 2
        assert pool.pages_for(10_000) == 4    # capped at pages_per_slot

    def test_quantize_pages_absmax_grid(self):
        rng = np.random.default_rng(1)
        pages = jnp.asarray(rng.standard_normal(
            (3, L, 2, H, 16, D)).astype(np.float32)) * 4.0
        q, scale = kvc.quantize_pages(pages)
        assert q.dtype == jnp.int8 and scale.shape == (3, L, 2, H)
        absmax = np.max(np.abs(np.asarray(pages)), axis=(-2, -1))
        np.testing.assert_allclose(np.asarray(scale), absmax / 127.0,
                                   rtol=1e-6)
        # reconstruction error bounded by half a quantization step
        recon = np.asarray(q, np.float32) * np.asarray(scale)[..., None, None]
        err = np.abs(recon - np.asarray(pages))
        assert (err <= np.asarray(scale)[..., None, None] * 0.5 + 1e-6).all()
        # all-zero page quantizes with scale 1 (no 0/0)
        qz, sz = kvc.quantize_pages(jnp.zeros((1, L, 2, H, 16, D)))
        assert (np.asarray(sz) == 1.0).all() and (np.asarray(qz) == 0).all()

    def _roundtrip(self, kv_dtype):
        cfg = kvc.KVCacheConfig(num_layers=L, num_heads=H, head_dim=D,
                                max_len=M, page_size=16, num_pages=5,
                                kv_dtype=kv_dtype)
        pool = kvc.PagedKVCache(cfg)
        rng = np.random.default_rng(2)
        lp = 40                                # 3 pages, last partial
        dense = jnp.asarray(rng.standard_normal(
            (L, 2, 1, H, M, D)).astype(np.float32))
        dense = dense.at[:, :, :, :, lp:, :].set(0.0)
        page_ids = pool.alloc(pool.pages_for(lp))
        row = pool.table_row(page_ids)   # 3 real pages + 1 scratch entry;
        # the engine passes the FULL row — trailing scratch entries absorb
        # the masked-to-zero pages past the prompt
        p2, s2 = kvc.scatter_prefill_pages(
            dense, pool.pool, pool.scales, jnp.asarray(row),
            jnp.asarray(lp, jnp.int32), 16)
        back = kvc.gather_pages(p2, s2, jnp.asarray(row[None, :]),
                                jnp.float32)
        return np.asarray(dense[:, :, 0]), np.asarray(back[:, :, 0]), lp

    def test_gather_scatter_roundtrip_native(self):
        dense, back, lp = self._roundtrip("native")
        np.testing.assert_array_equal(back[..., :lp, :], dense[..., :lp, :])

    def test_int8_roundtrip_tolerance(self):
        dense, back, lp = self._roundtrip("int8")
        absmax = np.abs(dense).max()
        assert np.abs(back[..., :lp, :] - dense[..., :lp, :]).max() \
            <= absmax / 127.0 * 0.5 + 1e-6

    def test_int8_logits_tolerance_parity(self):
        """The ISSUE-named parity gate: logits computed off the paged-int8
        cache match the dense-cache logits within the absmax grid's error
        budget — and are NOT trivially identical."""
        dense, back, lp = self._roundtrip("int8")
        valid = (np.arange(M) < lp)[None, :]
        ref = np.asarray(_readout(jnp.asarray(dense[0, 0][None]),
                                  jnp.asarray(valid)))
        got = np.asarray(_readout(jnp.asarray(back[0, 0][None]),
                                  jnp.asarray(valid)))
        delta = np.abs(got - ref).max()
        assert 0.0 < delta <= 0.05 * np.abs(ref).max(), delta

    def test_scatter_token_masks_future_positions(self):
        """A freshly claimed page must not inherit stale pool bytes: the
        single-token write-back zeroes positions > t inside its page."""
        cfg = kvc.KVCacheConfig(num_layers=L, num_heads=H, head_dim=D,
                                max_len=M, page_size=16, num_pages=5)
        pool = jnp.full((5,) + cfg.page_shape(), 7.0, jnp.float32)  # stale
        tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        dense = jnp.asarray(np.random.default_rng(3).standard_normal(
            (L, 2, 1, H, M, D)).astype(np.float32))
        t = jnp.asarray([17], jnp.int32)       # page 1 of the slot
        p2, _ = kvc.scatter_token_page(dense, pool, None, tables, t, 16)
        page = np.asarray(p2)[2]               # pool page id 2
        np.testing.assert_array_equal(page[:, :, :, 2:, :], 0.0)
        np.testing.assert_array_equal(
            page[:, :, :, :2, :], np.asarray(dense)[:, :, 0, :, 16:18, :])


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_queue_overflow_rejects(self, metrics):
        s = serving.Scheduler(max_queue=2)
        s.submit(serving.GenerationRequest(PROMPTS[0]))
        s.submit(serving.GenerationRequest(PROMPTS[1]))
        with pytest.raises(serving.QueueFull):
            s.submit(serving.GenerationRequest(PROMPTS[2]))
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=rejected"] == 1
        assert s.queue_depth == 2

    def test_fifo_no_slip_ahead(self):
        s = serving.Scheduler()
        big = serving.GenerationRequest(PROMPTS[4])     # head
        small = serving.GenerationRequest(PROMPTS[3])
        s.submit(big), s.submit(small)
        # head does not fit -> nothing admitted, even though `small` would
        taken = s.next_admissions(
            2, lambda r: r.request_id != big.request_id)
        assert taken == [] and s.queue_depth == 2

    def test_budget_policy_bounds_prefill_tokens(self):
        s = serving.Scheduler(policy="budget", prefill_token_budget=12)
        for p in PROMPTS[:3]:                           # 8 + 8 + 8 tokens
            s.submit(serving.GenerationRequest(p))
        taken = s.next_admissions(3, lambda r: True)
        assert len(taken) == 1                          # 8 + 8 > 12
        taken = s.next_admissions(3, lambda r: True)
        assert len(taken) == 1
        # the first request always passes, even over budget: progress
        s2 = serving.Scheduler(policy="budget", prefill_token_budget=4)
        s2.submit(serving.GenerationRequest(PROMPTS[0]))
        assert len(s2.next_admissions(1, lambda r: True)) == 1

    def test_budget_policy_validation(self):
        with pytest.raises(ValueError):
            serving.Scheduler(policy="budget")
        with pytest.raises(ValueError):
            serving.Scheduler(policy="wrfq")

    def test_cancel_queued_resolves_future(self, metrics):
        s = serving.Scheduler()
        req = serving.GenerationRequest(PROMPTS[0])
        fut = s.submit(req)
        assert s.cancel(req.request_id) is True
        res = fut.result(timeout=1)
        assert res.finish_reason == "cancelled" and res.tokens == []
        assert s.queue_depth == 0

    def test_cancel_active_is_deferred_to_engine(self):
        s = serving.Scheduler()
        assert s.cancel(12345) is True                  # flagged, not lost
        assert s.take_cancelled_active() == {12345}
        assert s.take_cancelled_active() == set()       # drained

    def test_requeue_preserves_order(self):
        s = serving.Scheduler()
        reqs = [serving.GenerationRequest(p) for p in PROMPTS[:3]]
        for r in reqs:
            s.submit(r)
        taken = s.next_admissions(2, lambda r: True)
        s.requeue(taken)
        order = [p.request.request_id
                 for p in s.next_admissions(3, lambda r: True)]
        assert order == [r.request_id for r in reqs]


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------

class TestEngine:
    def test_batched_matches_sequential(self, metrics):
        """5 requests (> max_batch=4, mixed prompt lengths and budgets)
        through the continuously-batched engine decode the exact sequences
        of the dense bs=1 loop — the scan_greedy_parity gate, on CPU."""
        n_new = [6, 4, 6, 5, 3]
        eng = make_engine()
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=n))
                for p, n in zip(PROMPTS, n_new)]
        eng.run()
        for p, n, f in zip(PROMPTS, n_new, futs):
            res = f.result(timeout=5)
            assert res.finish_reason == "length"
            assert res.tokens == dense_reference(p, n)
            assert res.ttft_s is not None and res.tpot_s is not None
        # all pages returned to the pool
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=completed"] == 5
        assert snap["serving.tokens_total"] == sum(n_new)
        for hist in ("serving.ttft_seconds", "serving.tpot_seconds"):
            assert snap[hist]["count"] >= 1
        assert "serving.batch_utilization" in snap

    @pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
    def test_quantized_legs_match_reference(self, kv_dtype):
        """The storage-dtype legs keep greedy parity on the toy LM (logit
        gaps here dwarf the absmax grid error — the tolerance-level parity
        is pinned in test_int8_logits_tolerance_parity)."""
        eng = make_engine(kv_dtype=kv_dtype)
        assert eng.kv.pool.dtype == (jnp.int8 if kv_dtype == "int8"
                                     else jnp.bfloat16)
        assert (eng.kv.scales is not None) == (kv_dtype == "int8")
        futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=5))
                for p in PROMPTS[:3]]
        eng.run()
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 5)

    def test_env_knob_selects_kv_dtype(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "int8")
        eng = make_engine(kv_dtype="")          # defer to env
        assert eng.kv.config.quantized
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "bogus")
        with pytest.raises(ValueError, match="PADDLE_TPU_KV_DTYPE"):
            make_engine(kv_dtype="")

    def test_admission_at_full_batch(self):
        """max_batch=1: the second request waits queued, joins the moment
        the first evicts, and still decodes its exact reference sequence
        — continuous batching across an eviction boundary."""
        eng = make_engine(max_batch=1)
        f0 = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                  max_new_tokens=3))
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=3))
        eng.step()
        assert eng.active_requests == 1 and eng.queue_depth == 1
        eng.run()
        assert f0.result(timeout=5).tokens == dense_reference(PROMPTS[0], 3)
        assert f1.result(timeout=5).tokens == dense_reference(PROMPTS[1], 3)

    def test_page_pool_gating(self):
        """A pool sized for ONE resident request serializes two: the
        second is admitted only after the first's pages free."""
        eng = make_engine(max_batch=4, num_pages=5)   # 4 usable = 1 slot
        n = M // 16                                    # whole-lifetime claim
        futs = [eng.submit(serving.GenerationRequest(
            PROMPTS[i], max_new_tokens=M - PROMPTS[i].size))
            for i in range(2)]
        eng.step()
        assert eng.active_requests == 1 and eng.queue_depth == 1
        assert eng.kv.free_pages == 4 - n
        eng.run()
        for f in futs:
            assert f.result(timeout=5).finish_reason == "length"
        assert eng.kv.free_pages == 4

    def test_admission_batch_no_overcommit_no_slip_ahead(self):
        """Pages must be reserved WITHIN one boundary's admission batch:
        6 usable pages, A and B need 4 each, C needs 2. B must stay
        queued (pool can't cover it beside A) and C must NOT slip past B
        even though C alone would fit — strict FIFO survives admission."""
        eng = make_engine(max_batch=4, num_pages=7)    # 6 usable
        fa = eng.submit(serving.GenerationRequest(      # 8+56=64 -> 4 pages
            PROMPTS[0], max_new_tokens=56))
        fb = eng.submit(serving.GenerationRequest(
            PROMPTS[1], max_new_tokens=56))
        fc = eng.submit(serving.GenerationRequest(      # 8+24=32 -> 2 pages
            PROMPTS[2], max_new_tokens=24))
        eng.step()
        assert eng.active_requests == 1                 # A alone
        assert eng.queue_depth == 2                     # B then C, in order
        assert eng.kv.free_pages == 2                   # no over-commit
        eng.run()
        assert fa.result(timeout=5).tokens == \
            dense_reference(PROMPTS[0], 56)
        assert fb.result(timeout=5).tokens == \
            dense_reference(PROMPTS[1], 56)
        assert fc.result(timeout=5).tokens == \
            dense_reference(PROMPTS[2], 24)
        assert eng.kv.free_pages == 6

    def test_submit_validation(self):
        eng = make_engine(max_queue=1)
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(serving.GenerationRequest(
                np.zeros(M, np.int32), max_new_tokens=1))
        eng.submit(serving.GenerationRequest(PROMPTS[0], max_new_tokens=4))
        with pytest.raises(serving.QueueFull):
            eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                 max_new_tokens=4))

    def test_zero_active_idle_step(self, metrics):
        eng = make_engine()
        assert eng.step() is False              # no device touch
        snap = obs.snapshot()
        assert snap.get("serving.steps_total") is None
        assert snap["serving.active_slots"] == 0

    def test_eviction_on_eos(self):
        ref = dense_reference(PROMPTS[0], 6)
        eos = ref[2]
        k = ref.index(eos)              # first occurrence stops the decode
        eng = make_engine()
        fut = eng.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=6, eos_token_id=eos))
        eng.run()
        res = fut.result(timeout=5)
        assert res.finish_reason == "eos" and res.tokens == ref[:k + 1]
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_cancel_active_mid_flight(self):
        eng = make_engine()
        req0 = serving.GenerationRequest(PROMPTS[0], max_new_tokens=8)
        f0 = eng.submit(req0)
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=8))
        eng.step()                              # both admitted + 1 token
        eng.step()
        eng.cancel(req0.request_id)
        eng.run()
        res0 = f0.result(timeout=5)
        assert res0.finish_reason == "cancelled"
        assert 1 <= len(res0.tokens) < 8        # partial transcript kept
        assert res0.tokens == dense_reference(PROMPTS[0], 8)[:len(res0.tokens)]
        assert f1.result(timeout=5).tokens == dense_reference(PROMPTS[1], 8)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_streaming_callback(self):
        seen = []
        eng = make_engine()
        req = serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=4,
            stream=lambda rid, tok: seen.append((rid, tok)))
        fut = eng.submit(req)
        eng.run()
        assert [t for _, t in seen] == fut.result(timeout=5).tokens
        assert {rid for rid, _ in seen} == {req.request_id}

    def test_raising_stream_callback_fails_request_alone(self):
        """A raising callback is the REQUEST's failure: its Future gets
        the exception and its pages free; batchmates are untouched (the
        step loop — incl. the start() thread — must not unwind)."""
        class CbErr(RuntimeError):
            pass

        def bad(rid, tok):
            raise CbErr("user callback exploded")

        eng = make_engine()
        f0 = eng.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=4, stream=bad))
        f1 = eng.submit(serving.GenerationRequest(PROMPTS[1],
                                                  max_new_tokens=4))
        eng.run()
        with pytest.raises(CbErr):
            f0.result(timeout=5)
        assert f1.result(timeout=5).tokens == dense_reference(PROMPTS[1], 4)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1

    def test_background_thread_serving(self):
        eng = make_engine()
        eng.start()
        try:
            fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                       max_new_tokens=4))
            assert fut.result(timeout=30).tokens == \
                dense_reference(PROMPTS[0], 4)
        finally:
            eng.stop()

    def test_warmup_compiles_every_bucket(self):
        eng = make_engine().warmup(prompt_lens=[8])
        # warmup must leave the pool allocatable and the engine clean
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=3))
        eng.run()
        assert fut.result(timeout=5).tokens == dense_reference(PROMPTS[0], 3)


# ---------------------------------------------------------------------------
# fault injection: a faulted slot fails alone
# ---------------------------------------------------------------------------

class TestFaults:
    def _run_with_schedule(self, sched, n_new=5):
        eng = make_engine()
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=n_new)) for p in PROMPTS[:3]]
            eng.run()
        return eng, futs

    def test_faulted_slot_fails_alone(self, metrics):
        """serving.step fires once per (step, slot) in admission order:
        calls 2 and 5 target slot B at two consecutive boundaries — one
        retry, then failure. A and C must complete bit-identically."""
        sched = faults.FaultSchedule().error("serving.step", on=(2, 5))
        eng, (fa, fb, fc) = self._run_with_schedule(sched)
        with pytest.raises(faults.FaultInjected):
            fb.result(timeout=5)
        assert fa.result(timeout=5).tokens == dense_reference(PROMPTS[0], 5)
        assert fc.result(timeout=5).tokens == dense_reference(PROMPTS[2], 5)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1  # B freed
        snap = obs.snapshot()
        assert snap["serving.requests_total"]["status=failed"] == 1
        assert snap["serving.requests_total"]["status=completed"] == 2
        # determinism: same schedule => same (site, call, kind) trace
        trace = [t for t in sched.trace if t[0] == "serving.step"]
        assert trace == [("serving.step", 2, "error"),
                         ("serving.step", 5, "error")]

    def test_step_fault_retries_once_then_completes(self, metrics):
        """A single fault only delays its slot one boundary; the transcript
        is still exact (functional cache state — nothing half-written)."""
        sched = faults.FaultSchedule().error("serving.step", on=(2,))
        _, futs = self._run_with_schedule(sched)
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 5)
        assert obs.snapshot()["serving.step_retries_total"] == 1

    def test_admit_fault_retry_then_success(self, metrics):
        sched = faults.FaultSchedule().error("serving.admit", on=(1,))
        eng, futs = self._run_with_schedule(sched)
        for p, f in zip(PROMPTS, futs):
            assert f.result(timeout=5).tokens == dense_reference(p, 5)
        assert obs.snapshot()["serving.admit_retries_total"] == 1

    def test_admit_double_fault_fails_request_frees_pages(self, metrics):
        sched = faults.FaultSchedule().error("serving.admit", on=(1, 2))
        eng, (fa, fb, fc) = self._run_with_schedule(sched)
        with pytest.raises(faults.FaultInjected):
            fa.result(timeout=5)
        assert fb.result(timeout=5).tokens == dense_reference(PROMPTS[1], 5)
        assert fc.result(timeout=5).tokens == dense_reference(PROMPTS[2], 5)
        assert eng.kv.free_pages == eng.kv.config.num_pages - 1
