"""hapi (paddle.Model) + paddle.metric + callbacks tests."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.hapi.callbacks import Callback, EarlyStopping


class _TinyNet(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(8, 16)
        self.fc2 = paddle.nn.Linear(16, 3)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _toy_dataset(n=64, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, 8)).astype(np.float32)
    ys = (xs.sum(axis=1) > 0).astype(np.int64) % 3
    return paddle.io.TensorDataset(
        [paddle.to_tensor(xs), paddle.to_tensor(ys)])


def _prepared_model(lr=0.05):
    paddle.seed(0)
    net = _TinyNet()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=lr,
                                        parameters=net.parameters()),
        loss=paddle.nn.CrossEntropyLoss(),
        metrics=Accuracy())
    return model


def test_fit_decreases_loss_and_tracks_accuracy():
    model = _prepared_model()
    ds = _toy_dataset()
    hist = model.fit(ds, batch_size=16, epochs=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    res = model.evaluate(ds, batch_size=16, verbose=0)
    assert res["acc"] > 0.5
    assert res["loss"] < 1.5


def test_predict_shapes_and_stack():
    model = _prepared_model()
    ds = _toy_dataset(n=20)
    outs = model.predict(ds, batch_size=8, stack_outputs=True)
    assert len(outs) == 1
    assert outs[0].shape == (20, 3)
    outs2 = model.predict(ds, batch_size=8)
    assert len(outs2[0]) == 3  # 3 batches: 8+8+4


def test_train_eval_batch_api():
    model = _prepared_model()
    x = paddle.randn([16, 8])
    y = paddle.to_tensor(np.zeros(16, np.int64))
    l0, _ = model.train_batch([x], [y])
    for _ in range(10):
        l1, m = model.train_batch([x], [y])
    assert l1[0] < l0[0]
    le, me = model.eval_batch([x], [y])
    assert np.isfinite(le[0]) and len(me) == 1


def test_model_save_load_roundtrip(tmp_path):
    model = _prepared_model()
    ds = _toy_dataset(n=32)
    model.fit(ds, batch_size=16, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")

    model2 = _prepared_model()
    model2.load(path)
    x = paddle.randn([4, 8])
    np.testing.assert_allclose(model.predict_batch([x])[0],
                               model2.predict_batch([x])[0], rtol=1e-6)


def test_callbacks_fire_and_early_stopping():
    events = []

    class Recorder(Callback):
        def on_epoch_begin(self, epoch, logs=None):
            events.append(("epoch_begin", epoch))

        def on_train_batch_end(self, step, logs=None):
            events.append(("batch_end", step))

    model = _prepared_model(lr=0.0)  # frozen: eval loss never improves
    ds = _toy_dataset(n=32)
    es = EarlyStopping(monitor="loss", patience=1, verbose=0,
                       save_best_model=False)
    model.fit(ds, eval_data=ds, batch_size=16, epochs=10, verbose=0,
              callbacks=[Recorder(), es])
    epochs_run = len([e for e in events if e[0] == "epoch_begin"])
    assert 2 <= epochs_run < 10  # stopped early
    assert any(e[0] == "batch_end" for e in events)


def test_model_checkpoint_callback(tmp_path):
    # PR 10: ModelCheckpoint rides the verified writer — per-epoch
    # checkpoint DIRECTORIES with a committed CRC manifest and rotating
    # latest/latest.prev pointers, not bare .pdparams saves
    from paddle_tpu.core.tensor import Parameter

    Parameter._param_counter = 0  # deterministic optimizer-state keys
    model = _prepared_model()
    ds = _toy_dataset(n=32)
    save_dir = str(tmp_path / "ckpts")
    model.fit(ds, batch_size=16, epochs=2, verbose=0, save_dir=save_dir)
    for name in ("epoch-0", "epoch-1", "final"):
        assert os.path.exists(os.path.join(save_dir, name, "manifest.json"))
    with open(os.path.join(save_dir, "latest")) as f:
        assert f.read().strip() == "final"
    # CRC-verified round trip into a fresh model (fresh-process simulation:
    # same construction order => same state keys)
    w_before = np.asarray(model.network.fc1.weight._data).copy()
    Parameter._param_counter = 0
    fresh = _prepared_model(lr=0.05)
    fresh.load_verified(os.path.join(save_dir, "final"))
    np.testing.assert_array_equal(
        np.asarray(fresh.network.fc1.weight._data), w_before)


def test_model_checkpoint_callback_legacy(tmp_path):
    from paddle_tpu.hapi.callbacks import ModelCheckpoint

    model = _prepared_model()
    ds = _toy_dataset(n=32)
    save_dir = str(tmp_path / "ckpts")
    model.fit(ds, batch_size=16, epochs=1, verbose=0,
              callbacks=[ModelCheckpoint(1, save_dir, legacy=True)])
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))


def test_summary_counts_params(capsys):
    net = _TinyNet()
    info = paddle.summary(net)
    capsys.readouterr()
    # fc1: 8*16+16, fc2: 16*3+3
    assert info["total_params"] == 8 * 16 + 16 + 16 * 3 + 3
    assert info["trainable_params"] == info["total_params"]


# -- metrics ----------------------------------------------------------------

def test_accuracy_metric_topk():
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.8, 0.1, 0.1]])
    label = np.array([[1], [2]])
    correct = m.compute(pred, label)
    m.update(correct)
    top1, top2 = m.accumulate()
    assert top1 == pytest.approx(0.5)   # first correct, second wrong
    assert top2 == pytest.approx(0.5)   # label 2 not in top2 of row 2
    assert m.name() == ["acc_top1", "acc_top2"]


def test_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.7])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)  # TP=2, FP=1
    assert r.accumulate() == pytest.approx(2 / 3)  # TP=2, FN=1


def test_auc_matches_sklearn_style_reference():
    rng = np.random.default_rng(0)
    n = 500
    labels = rng.integers(0, 2, n)
    # informative scores: higher for positives
    preds = np.clip(labels * 0.3 + rng.normal(0.35, 0.25, n), 0, 1)
    m = Auc()
    m.update(preds, labels)
    got = m.accumulate()

    # exact AUC by rank statistic
    pos = preds[labels == 1]
    neg = preds[labels == 0]
    exact = np.mean([(pos[:, None] > neg[None, :]).mean()
                     + 0.5 * (pos[:, None] == neg[None, :]).mean()])
    assert got == pytest.approx(exact, abs=0.01)


def test_functional_accuracy_jittable():
    x = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    y = paddle.to_tensor(np.array([1, 1]))
    acc = paddle.metric.accuracy(x, y, k=1)
    assert float(acc) == pytest.approx(0.5)


def test_logwriter_and_visualdl_callback(tmp_path):
    """Scalar sink (VisualDL LogWriter parity) + hapi callback wiring."""
    import json

    from paddle_tpu.utils.logwriter import LogWriter

    with LogWriter(logdir=str(tmp_path)) as w:
        w.add_scalar("train/loss", 1.5, step=1)
        w.add_scalar("train/loss", 1.2, step=2)
        w.add_scalars("eval", {"acc": 0.9, "f1": 0.8}, step=2)
        w.add_text("note", "hello", step=2)
        w.add_histogram("grads", np.random.rand(100), step=2)
        path = w.file_name
    recs = [json.loads(l) for l in open(path)]
    scalars = [r for r in recs if r["type"] == "scalar"]
    assert {r["tag"] for r in scalars} == {"train/loss", "eval/acc",
                                           "eval/f1"}
    assert any(r["type"] == "histogram" for r in recs)

    # callback end-to-end through a tiny fit()
    from paddle_tpu.hapi.callbacks import VisualDL

    cb = VisualDL(log_dir=str(tmp_path / "fit"))
    cb.on_train_batch_end(0, {"loss": 0.7})
    cb.on_epoch_end(0, {"loss": 0.6})
    cb.on_eval_end({"acc": [0.5]})
    cb.on_train_end()
    files = list((tmp_path / "fit").iterdir())
    assert files
    recs = [json.loads(l) for l in open(files[0])]
    tags = {r["tag"] for r in recs}
    assert {"train/loss", "train_epoch/loss", "eval/acc"} <= tags
