"""Regressions for review findings: donation safety, param groups, resume
before first step, spectral-norm convergence."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_donation_does_not_kill_readonly_state():
    """A param that is read but never mutated must survive a compiled call
    (donated buffers for un-mutated state are carried through as aliases)."""
    w = paddle.to_tensor(np.ones((4, 4), np.float32))
    paddle.core_register = getattr(paddle, "core_register", None)
    from paddle_tpu.core.tensor import register_state_tensor
    w.name = "ro_w"
    register_state_tensor(w)
    other = paddle.to_tensor(np.zeros((4, 4), np.float32))
    other.name = "mut"
    register_state_tensor(other)

    @paddle.jit.to_static
    def f(x):
        other._set_data(other._data + 1.0)  # mutate one, read the other
        return paddle.matmul(x, w)

    y = f(paddle.ones([4, 4]))
    # both state tensors must still be alive and correct
    np.testing.assert_allclose(w.numpy(), np.ones((4, 4)))
    np.testing.assert_allclose(other.numpy(), np.ones((4, 4)))
    np.testing.assert_allclose(y.numpy(), np.full((4, 4), 4.0))
    y2 = f(paddle.ones([4, 4]))
    np.testing.assert_allclose(other.numpy(), np.full((4, 4), 2.0))
    np.testing.assert_allclose(w.numpy(), np.ones((4, 4)))


def test_param_groups_dict_form():
    m1 = nn.Linear(4, 4)
    m2 = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[
        {"params": m1.parameters(), "learning_rate": 0.1},
        {"params": m2.parameters()},
    ])
    w1 = m1.weight.numpy().copy()
    w2 = m2.weight.numpy().copy()
    for p in list(m1.parameters()) + list(m2.parameters()):
        p.grad = paddle.ones(p.shape)
    opt.step()
    np.testing.assert_allclose(m1.weight.numpy(), w1 - 0.1, rtol=1e-6)
    np.testing.assert_allclose(m2.weight.numpy(), w2 - 1.0, rtol=1e-6)
    # state_dict sees params in dict groups
    sd = opt.state_dict()
    assert "step" in sd
    opt.clear_grad()
    assert all(p.grad is None for p in m1.parameters())


def test_resume_before_first_step():
    """set_state_dict on a FRESH optimizer must materialize accumulators."""
    m = nn.Linear(3, 3)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    x = paddle.randn([4, 3])
    for _ in range(3):
        loss = (m(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()

    m2 = nn.Linear(3, 3)
    # rename params to match checkpoint keys
    for p2, p1 in zip(m2.parameters(), m.parameters()):
        p2.name = p1.name
    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=m2.parameters())
    opt2.set_state_dict(sd)  # BEFORE any step
    assert opt2._step_count == 3
    m1_acc = sorted((k for k in sd if k.endswith("_moment1")))
    assert m1_acc, "checkpoint must contain moment keys"
    # accumulators materialized with checkpoint values
    assert "moment1" in opt2._accumulators
    loaded = list(opt2._accumulators["moment1"].values())[0].numpy()
    orig = sd[m1_acc[0]].numpy()
    assert not np.allclose(loaded, 0), "loaded moments must not be zero"


def test_spectral_norm_buffers_advance():
    sn = nn.SpectralNorm((8, 8), power_iters=1)
    w = paddle.randn([8, 8])
    u0 = sn.weight_u.numpy().copy()
    sn(w)
    u1 = sn.weight_u.numpy().copy()
    assert not np.allclose(u0, u1), "power iteration must advance u buffer"
    for _ in range(50):
        sn(w)
    # after many iterations sigma should approximate the top singular value
    out = sn(w)
    top = np.linalg.svd(w.numpy(), compute_uv=False)[0]
    ratio = np.linalg.svd(out.numpy(), compute_uv=False)[0]
    np.testing.assert_allclose(ratio, 1.0, rtol=1e-2)


# -- round-1 session-2 review findings ---------------------------------------

@pytest.mark.slow
def test_flash_causal_alignment_lq_ne_lk():
    """Pallas, XLA, and chunked-backward paths must agree on bottom-right
    causal alignment for lq != lk (KV-cache decode / cross-window)."""
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.ops import flash_attention as fa

    rng = np.random.default_rng(0)
    for (lq, lk) in [(32, 64), (64, 32), (16, 128)]:
        q = jnp.asarray(rng.normal(size=(1, 2, lq, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, lk, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, lk, 16)).astype(np.float32))
        for causal in (True, False):
            out_p = fa._pallas_flash(q, k, v, causal, 0.25, 16, 16, True)
            out_x = fa._xla_attention(q, k, v, causal, 0.25)
            out_c = fa._chunked_attention(q, k, v, causal, 0.25, 16)
            assert float(jnp.abs(out_p - out_x).max()) < 1e-5
            assert float(jnp.abs(out_c - out_x).max()) < 1e-5


def test_flash_backward_matches_dense_grad():
    import jax
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.ops import flash_attention as fa

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 2, 32, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 2, 64, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 2, 64, 16)).astype(np.float32))
    g1 = jax.grad(lambda a, b, c: jnp.sum(
        fa._flash_core(a, b, c, True, 0.25) ** 2), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda a, b, c: jnp.sum(
        fa._xla_attention(a, b, c, True, 0.25) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 2e-4


def test_moe_gate_respects_top_k():
    from paddle_tpu.incubate.moe import GShardGate, SwitchGate

    assert GShardGate(8, 4, top_k=4).top_k == 4
    assert SwitchGate(8, 4).top_k == 1
    assert SwitchGate(8, 4, top_k=2).top_k == 2
