"""graft-lint engine tests + the tier-1 gate.

Three layers:

* fixture tests — one positive + one negative snippet per rule, run
  through the real engine against a tmp tree;
* machinery tests — suppression pragmas, baseline round-trip/staleness,
  CLI exit codes and JSON schema;
* the gate — ``run_lint()`` over the shipped tree must be clean against
  the checked-in baseline, every baseline entry must carry a real reason
  (no TODOs), and no entry may be stale.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import (  # noqa: E402
    RULES, default_baseline_path, load_baseline, match_baseline, run_lint,
    update_baseline,
)
from tools.lint.engine import save_baseline  # noqa: E402

EXPECTED_RULES = {"trace-impurity", "silent-swallow", "hot-path-import",
                  "unguarded-global", "host-sync",
                  # graft-lint 2.0 whole-program rules
                  "cross-trace-impurity", "cross-host-sync",
                  "lock-order", "import-layering",
                  # PR 5 (resilience): retry loops belong to the policies
                  "naked-retry",
                  # PR 6 (backend fallback): placement belongs to
                  # device.py / core/fallback.py
                  "device-access",
                  # ISSUE 12 (tracing): spans only via the span() context
                  # manager; guarded construction on the dispatch fast path
                  "span-discipline",
                  # ISSUE 14 (graft-lint 3.0): whole-program race detector —
                  # thread-root discovery + lock domination over shared state
                  "shared-state-race",
                  # ISSUE 18 (graft-lint 4.0): CFG-backed exception/resource
                  # flow — typed failure surfaces at declared entry roots,
                  # and all-paths release of configured acquire/release pairs
                  "exception-contract", "resource-discipline",
                  # ISSUE 19 (graft-lint 5.0): interprocedural blocking —
                  # lock-hold stalls, unbounded waits at serving roots, and
                  # stall classes reachable from the dispatch fast path
                  "blocking-under-lock", "unbounded-wait", "hot-path-stall"}


def _lint_snippet(tmp_path, code, rule, filename="snippet.py", config=None):
    f = tmp_path / filename
    f.write_text(textwrap.dedent(code))
    return run_lint(paths=[str(f)], rules=[rule], config=config,
                    root=str(tmp_path)).new


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

def test_all_eighteen_rules_registered():
    assert len(EXPECTED_RULES) == 18
    assert EXPECTED_RULES <= set(RULES)


# ---------------------------------------------------------------------------
# silent-swallow
# ---------------------------------------------------------------------------

def test_silent_swallow_positive(tmp_path):
    found = _lint_snippet(tmp_path, """\
        try:
            x = 1
        except Exception:
            pass
        """, "silent-swallow")
    assert len(found) == 1 and found[0].line == 3


def test_silent_swallow_negative(tmp_path):
    found = _lint_snippet(tmp_path, """\
        try:
            x = 1
        except Exception:
            pass  # why: probe failure means feature absent, default is fine
        """, "silent-swallow")
    assert found == []


# ---------------------------------------------------------------------------
# hot-path-import
# ---------------------------------------------------------------------------

HOT_CFG = {"hot_path_modules": ["hot.py"]}


def test_hot_path_import_positive(tmp_path):
    found = _lint_snippet(tmp_path, """\
        def dispatch(x):
            import numpy as np
            return np.asarray(x)
        """, "hot-path-import", filename="hot.py", config=HOT_CFG)
    assert len(found) == 1 and found[0].line == 2
    assert "dispatch" in found[0].message


def test_hot_path_import_negative_module_scope_and_unlisted(tmp_path):
    clean = """\
        import numpy as np

        def dispatch(x):
            return np.asarray(x)
        """
    assert _lint_snippet(tmp_path, clean, "hot-path-import",
                         filename="hot.py", config=HOT_CFG) == []
    # same function-level import in a module NOT in the hot-path set: ok
    dirty = """\
        def helper(x):
            import numpy as np
            return np.asarray(x)
        """
    assert _lint_snippet(tmp_path, dirty, "hot-path-import",
                         filename="cold.py", config=HOT_CFG) == []


# ---------------------------------------------------------------------------
# trace-impurity
# ---------------------------------------------------------------------------

def test_trace_impurity_positive_clock_and_mutable_global(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import time
        import jax

        SCALES = {"a": 2.0}

        def fwd(x):
            return x * time.time() * SCALES["a"]

        fwd_c = jax.jit(fwd)
        """, "trace-impurity")
    kinds = {(f.line, f.message.split(" ")[0]) for f in found}
    assert (7, "'time.time(...)'") in kinds
    assert any("SCALES" in f.message for f in found)


def test_trace_impurity_reaches_helpers_and_apply_roots(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import os

        def apply(name, fn, *xs):
            return fn(*xs)

        def _helper(x):
            return x if os.environ.get("FAST") else x * 2

        def op(x):
            return apply("op", lambda a: _helper(a), x)
        """, "trace-impurity")
    assert len(found) == 1 and found[0].line == 7
    assert "os.environ" in found[0].message


def test_trace_impurity_negative_keyed_rng_and_untraced(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import time
        import jax

        def fwd(x, key):
            return x + jax.random.normal(key, x.shape)

        fwd_c = jax.jit(fwd)

        def untraced_host_helper():
            return time.time()
        """, "trace-impurity")
    assert found == []


# ---------------------------------------------------------------------------
# unguarded-global
# ---------------------------------------------------------------------------

def test_unguarded_global_positive_including_alias(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import threading

        _LOCK = threading.Lock()
        _REG = {}

        def put(k, v):
            _REG[k] = v

        def bump(k):
            d = _REG
            d.setdefault(k, 0)
        """, "unguarded-global")
    assert [f.line for f in found] == [7, 11]


def test_unguarded_global_negative_lock_and_locked_suffix(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import threading

        _LOCK = threading.Lock()
        _REG = {}

        def put(k, v):
            with _LOCK:
                _REG[k] = v

        def _insert_locked(k, v):
            _REG[k] = v

        _REG["module-scope"] = "import runs single-threaded"
        """, "unguarded-global")
    assert found == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

def test_host_sync_positive(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import jax.numpy as jnp
        import numpy as np

        def norms(params):
            out = []
            for p in params:
                out.append(float(jnp.sum(p._data)))
            return out

        def items(xs):
            return [x.item() for x in xs]  # comprehension: not a loop stmt

        def drain(ts):
            while True:
                if bool(np.asarray(ts[0]._data).all()):
                    break
        """, "host-sync")
    assert [f.line for f in found] == [7, 15]


def test_host_sync_negative_metadata_and_outside_loop(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import numpy as np

        def shapes(params):
            return [int(np.prod(p._data.shape)) for p in params]

        def sizes(params):
            out = []
            for p in params:
                out.append(int(np.prod(p._data.shape)))
            return out

        def one_sync(t):
            return t.item()
        """, "host-sync")
    assert found == []


# ---------------------------------------------------------------------------
# naked-retry
# ---------------------------------------------------------------------------

def test_naked_retry_positive_alias_and_except_loop(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import time as _time

        def call_with_retry(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    _time.sleep(0.2)
        """, "naked-retry")
    assert len(found) == 1 and found[0].line == 8
    assert "call_with_retry" in found[0].message


def test_naked_retry_negative_plain_poll_and_allowed_path(tmp_path):
    # a sleep in a loop WITHOUT exception handling is a plain poll loop,
    # not a hand-rolled retry — out of scope for this rule
    clean = """\
        import time

        def wait_for(flag):
            while not flag():
                time.sleep(0.1)
        """
    assert _lint_snippet(tmp_path, clean, "naked-retry") == []
    # the same retry idiom inside the resilience package itself is the
    # implementation, not a violation
    dirty = """\
        import time

        def backoff(fn):
            while True:
                try:
                    return fn()
                except OSError:
                    time.sleep(0.2)
        """
    assert _lint_snippet(
        tmp_path, dirty, "naked-retry", filename="policy.py",
        config={"retry_allowed_paths": ["policy.py"]}) == []


# ---------------------------------------------------------------------------
# device-access
# ---------------------------------------------------------------------------

def test_device_access_positive_call_alias_and_from_import(tmp_path):
    found = _lint_snippet(tmp_path, """\
        import jax as j
        from jax import device_put

        def move(arr):
            dev = j.devices("cpu")[0]
            return device_put(arr, dev)
        """, "device-access")
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2
    assert "jax.devices" in msgs and "from jax import device_put" in msgs
    # `import jax.numpy` (no asname) also binds the top-level `jax` name
    found = _lint_snippet(tmp_path, """\
        import jax.numpy

        def move(arr, dev):
            return jax.device_put(arr, dev)
        """, "device-access")
    assert len(found) == 1 and "jax.device_put" in found[0].message


def test_device_access_negative_allowed_paths_and_unrelated_attrs(tmp_path):
    # the sanctioned owners are exempt (config default covers the real
    # tree; fixture passes its own allowed list)
    dirty = """\
        import jax

        def put(arr, dev):
            return jax.device_put(arr, dev)
        """
    assert _lint_snippet(
        tmp_path, dirty, "device-access", filename="fallback.py",
        config={"device_access_allowed_paths": ["fallback.py"]}) == []
    # an unrelated attr named devices on a non-jax object is not a finding
    clean = """\
        import jax

        def shapes(mesh):
            return mesh.devices()  # Mesh.devices, not jax.devices

        def grids(x):
            return jax.numpy.asarray(x)
        """
    assert _lint_snippet(tmp_path, clean, "device-access") == []


def test_naked_retry_strict_poll_loop_paths(tmp_path):
    # poll_loop_paths modules (serving) get the strict tier: a plain
    # poll-loop sleep WITHOUT try/except is a finding there — watchdog/
    # drain threads must ride resilience.jitter_sleep
    poll = """\
        import time

        def wait_for(flag):
            while not flag():
                time.sleep(0.1)
        """
    found = _lint_snippet(
        tmp_path, poll, "naked-retry", filename="watchdog.py",
        config={"poll_loop_paths": ["watchdog.py"]})
    assert len(found) == 1 and "jitter_sleep" in found[0].message
    # the same file outside poll_loop_paths stays clean (non-strict tier)
    assert _lint_snippet(tmp_path, poll, "naked-retry") == []
    # jitter_sleep-based polling in a strict module is the sanctioned form
    clean = """\
        from paddle_tpu.resilience import jitter_sleep

        def wait_for(flag):
            while not flag():
                jitter_sleep(0.1)
        """
    assert _lint_snippet(
        tmp_path, clean, "naked-retry", filename="watchdog.py",
        config={"poll_loop_paths": ["watchdog.py"]}) == []


def test_naked_retry_strict_outranks_retry_allowed(tmp_path):
    # ISSUE 10: the watchdog moved INTO paddle_tpu/resilience (which is
    # retry_allowed). Its poll loops must still ride jitter_sleep — a
    # module in poll_loop_paths keeps the strict tier even when it is
    # also under retry_allowed_paths.
    poll = """\
        import time

        def loop(flag):
            while not flag():
                time.sleep(0.1)
        """
    found = _lint_snippet(
        tmp_path, poll, "naked-retry", filename="watchdog.py",
        config={"retry_allowed_paths": ["watchdog.py"],
                "poll_loop_paths": ["watchdog.py"]})
    assert len(found) == 1 and "jitter_sleep" in found[0].message
    # the shipped config actually covers the extracted modules
    from tools.lint.engine import DEFAULT_CONFIG
    assert "paddle_tpu/resilience/watchdog.py" in \
        DEFAULT_CONFIG["poll_loop_paths"]
    assert "paddle_tpu/resilience/trainer.py" in \
        DEFAULT_CONFIG["poll_loop_paths"]


def test_naked_retry_nested_def_does_not_inherit_loop(tmp_path):
    # a function DEFINED inside a loop starts its own context: its sleep
    # is not "in" the enclosing loop
    found = _lint_snippet(tmp_path, """\
        import time

        def outer(items):
            for it in items:
                try:
                    it.go()
                except ValueError:
                    pass  # why: optional feature probe
                def helper():
                    time.sleep(0.1)
                helper()
        """, "naked-retry")
    assert found == []


# ---------------------------------------------------------------------------
# span-discipline (ISSUE 12)
# ---------------------------------------------------------------------------

def test_span_discipline_flags_manual_pairing(tmp_path):
    found = _lint_snippet(tmp_path, """\
        from paddle_tpu.observability import trace

        def f():
            s = trace.begin_span("x")
            trace.end_span(s)
        """, "span-discipline")
    assert len(found) == 2
    assert "manual span pairing" in found[0].message


def test_span_discipline_flags_span_outside_with(tmp_path):
    found = _lint_snippet(tmp_path, """\
        from paddle_tpu.observability import trace as _trace

        def f():
            s = _trace.span("x")
            s.__enter__()
        """, "span-discipline")
    assert len(found) == 1 and "outside a `with`" in found[0].message


def test_span_discipline_with_statement_is_clean(tmp_path):
    found = _lint_snippet(tmp_path, """\
        from paddle_tpu.observability import trace as _trace

        def f(ctx):
            with _trace.span("serving.prefill", parent=ctx, rid=1):
                _trace.instant("tick")
        """, "span-discipline")
    assert found == []


def test_span_discipline_hot_module_needs_enabled_guard(tmp_path):
    hot = """\
        from paddle_tpu.observability import trace as _trace

        def dispatch():
            with _trace.span("op"):
                pass
        """
    cfg = {"span_hot_modules": ["hot.py"]}
    found = _lint_snippet(tmp_path, hot, "span-discipline",
                          filename="hot.py", config=cfg)
    assert len(found) == 1 and "enabled() guard" in found[0].message
    # the same file NOT in span_hot_modules is fine
    assert _lint_snippet(tmp_path, hot, "span-discipline",
                         filename="warm.py", config=cfg) == []


def test_span_discipline_guarded_hot_module_is_clean(tmp_path):
    found = _lint_snippet(tmp_path, """\
        from paddle_tpu.observability import trace as _trace

        def dispatch():
            if _trace.enabled():
                with _trace.span("op"):
                    pass
            else:
                pass
        """, "span-discipline", filename="hot.py",
        config={"span_hot_modules": ["hot.py"]})
    assert found == []


def test_span_discipline_shipped_tree_is_clean():
    # the acceptance pin: 0 findings over paddle_tpu/ with no baseline
    # allowance — the step_capture fast-path span stays guarded
    result = run_lint(rules=["span-discipline"])
    assert [f.text() for f in result.new] == []


# ---------------------------------------------------------------------------
# suppression pragmas
# ---------------------------------------------------------------------------

def test_pragma_same_line_suppresses(tmp_path):
    found = _lint_snippet(tmp_path, """\
        def items(xs):
            out = []
            for x in xs:
                out.append(x.item())  # graft-lint: disable=host-sync
            return out
        """, "host-sync")
    assert found == []


def test_pragma_comment_line_above_suppresses(tmp_path):
    found = _lint_snippet(tmp_path, """\
        def items(xs):
            out = []
            for x in xs:
                # graft-lint: disable=host-sync
                out.append(x.item())
            return out
        """, "host-sync")
    assert found == []


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    found = _lint_snippet(tmp_path, """\
        def items(xs):
            out = []
            for x in xs:
                out.append(x.item())  # graft-lint: disable=silent-swallow
            return out
        """, "host-sync")
    assert len(found) == 1


def test_pragma_disable_file(tmp_path):
    found = _lint_snippet(tmp_path, """\
        # graft-lint: disable-file=host-sync
        def items(xs):
            out = []
            for x in xs:
                out.append(x.item())
            return out
        """, "host-sync")
    assert found == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

BAD = """\
try:
    x = 1
except Exception:
    pass
"""


def test_baseline_round_trip(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(BAD)
    first = run_lint(paths=[str(f)], rules=["silent-swallow"],
                     root=str(tmp_path))
    assert len(first.new) == 1
    entries = update_baseline(first.new, [])
    assert entries[0]["count"] == 1
    assert entries[0]["reason"].startswith("TODO")
    bl = tmp_path / "baseline.json"
    save_baseline(str(bl), entries)
    again = run_lint(paths=[str(f)], rules=["silent-swallow"],
                     baseline_entries=load_baseline(str(bl)),
                     root=str(tmp_path))
    assert again.clean and len(again.baselined) == 1 and again.stale == []


def test_baseline_reports_stale_after_fix(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(BAD)
    first = run_lint(paths=[str(f)], rules=["silent-swallow"],
                     root=str(tmp_path))
    entries = update_baseline(first.new, [])
    f.write_text(BAD.replace("pass", "pass  # why: benign"))
    fixed = run_lint(paths=[str(f)], rules=["silent-swallow"],
                     baseline_entries=entries, root=str(tmp_path))
    assert fixed.clean and len(fixed.stale) == 1
    # --update-baseline semantics prune it while keeping live reasons
    assert update_baseline(fixed.new, entries) == []


def test_update_baseline_preserves_reasons(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(BAD)
    first = run_lint(paths=[str(f)], rules=["silent-swallow"],
                     root=str(tmp_path))
    entries = update_baseline(first.new, [])
    entries[0]["reason"] = "teardown path, nothing to signal to"
    again = update_baseline(first.new, entries)
    assert again[0]["reason"] == "teardown path, nothing to signal to"


def test_baseline_count_absorbs_exactly(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(BAD + "\n" + BAD)
    findings = run_lint(paths=[str(f)], rules=["silent-swallow"],
                        root=str(tmp_path)).new
    assert len(findings) == 2
    one = update_baseline(findings[:1], [])
    new, baselined, stale = match_baseline(findings, one)
    assert len(new) == 1 and len(baselined) == 1 and stale == []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


def test_cli_list_rules():
    p = _cli("--list-rules")
    assert p.returncode == 0
    for r in EXPECTED_RULES:
        assert r in p.stdout


def test_cli_unknown_rule_is_usage_error():
    p = _cli("--rules=no-such-rule")
    assert p.returncode == 2


@pytest.mark.slow
def test_cli_json_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    p = _cli(str(bad), "--format=json", "--no-baseline")
    assert p.returncode == 1
    report = json.loads(p.stdout)
    assert report["clean"] is False
    assert report["counts_by_rule"] == {"silent-swallow": 1}
    assert report["findings"][0]["rule"] == "silent-swallow"

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = _cli(str(good), "--format=json", "--no-baseline")
    assert p.returncode == 0 and json.loads(p.stdout)["clean"] is True


def test_cli_nonexistent_path_is_usage_error(tmp_path, capsys):
    # a renamed/typo'd path must not silently report "ok: 0 files"
    from tools.lint.cli import main
    assert main([str(tmp_path / "no_such_dir")]) == 2
    assert "no python files" in capsys.readouterr().err


def test_cli_scoped_update_baseline_preserves_out_of_scope(tmp_path, capsys):
    # --update-baseline narrowed to one file/rule must NOT delete the
    # other files' entries (and their human-written reasons)
    from tools.lint.cli import main
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text(BAD)
    b.write_text(BAD)
    bl = tmp_path / "baseline.json"
    assert main([str(a), str(b), f"--baseline={bl}",
                 "--update-baseline"]) == 0
    entries = load_baseline(str(bl))
    assert len(entries) == 2
    for e in entries:
        e["reason"] = "reviewed: teardown path"
    save_baseline(str(bl), entries)
    # scoped regeneration over a.py only: b.py's entry + reason survive
    assert main([str(a), f"--baseline={bl}", "--update-baseline"]) == 0
    after = {e["path"]: e for e in load_baseline(str(bl))}
    assert len(after) == 2
    b_rel = os.path.relpath(str(b), REPO).replace(os.sep, "/")
    assert after[b_rel]["reason"] == "reviewed: teardown path"
    # scoping by rule keeps entries of other rules too
    assert main([str(a), str(b), f"--baseline={bl}",
                 "--rules=host-sync", "--update-baseline"]) == 0
    assert len(load_baseline(str(bl))) == 2
    capsys.readouterr()


@pytest.mark.slow
def test_cli_update_baseline_flow(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    bl = tmp_path / "baseline.json"
    p = _cli(str(bad), f"--baseline={bl}", "--update-baseline")
    assert p.returncode == 0 and bl.exists()
    assert "TODO" in p.stdout  # new grandfathering demands a reviewed reason
    # a TODO-stamped reason is a drafting state: shipping it fails the run
    p = _cli(str(bad), f"--baseline={bl}")
    assert p.returncode == 1 and "TODO" in p.stderr
    p = _cli(str(bad), f"--baseline={bl}", "--allow-todo")
    assert p.returncode == 0  # baselined + drafting escape hatch -> clean


def test_cli_prune_baseline_removes_only_dead_entries(tmp_path, capsys):
    # ISSUE 18: --prune-baseline deletes entries that no longer fire and
    # lowers over-counted ones, leaving live entries (and their reasons).
    # Doctor a copy of the SHIPPED baseline — it is exactly-firing (the
    # tier-1 gate asserts zero stale entries), so the one inflated count
    # and the one fabricated entry are the only prunable budget.
    from tools.lint.cli import main
    real = load_baseline(default_baseline_path())
    assert real
    doctored = [dict(e) for e in real]
    doctored[0]["count"] = int(doctored[0].get("count", 1)) + 2
    doctored.append({"path": "paddle_tpu/no_such_file.py",
                     "rule": "host-sync", "message": "never fires",
                     "count": 1, "reason": "reviewed: dead"})
    bl = tmp_path / "baseline.json"
    save_baseline(str(bl), doctored)
    assert main([f"--baseline={bl}", "--prune-baseline"]) == 0
    out = capsys.readouterr().out
    assert "pruned:" in out and "lowered:" in out
    after = load_baseline(str(bl))
    key = lambda e: (e["path"], e["rule"], e["message"])  # noqa: E731
    assert {key(e) for e in after} == {key(e) for e in real}
    by_key = {key(e): e for e in after}
    k0 = key(real[0])
    assert by_key[k0]["count"] == int(real[0].get("count", 1))
    assert by_key[k0].get("reason") == real[0].get("reason")


def test_cli_prune_baseline_requires_full_run(tmp_path, capsys):
    # a narrowed run cannot tell "fixed" from "not scanned": usage error
    from tools.lint.cli import main
    assert main(["--prune-baseline", str(tmp_path)]) == 2
    assert main(["--prune-baseline", "--changed-only"]) == 2
    assert main(["--prune-baseline", "--rules=host-sync"]) == 2
    assert main(["--prune-baseline", "--no-baseline"]) == 2
    assert main(["--prune-baseline", "--update-baseline"]) == 2
    assert "full default run" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the tier-1 gate: shipped tree is clean, baseline fully justified
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean_against_baseline():
    # all ten rules — the four whole-program rules (call graph, lock
    # order, layer DAG) run against the full tree right here in tier 1
    result = run_lint(baseline_entries=load_baseline(default_baseline_path()))
    assert result.errors == []
    assert [f.text() for f in result.new] == [], (
        "new graft-lint findings — fix them or (with a written reason) "
        "run `python -m tools.lint --update-baseline`")
    assert result.stale == [], (
        "stale baseline entries — the code improved, run "
        "`python -m tools.lint --update-baseline` to prune them")


def test_baseline_is_fully_justified():
    entries = load_baseline(default_baseline_path())
    assert entries, "expected grandfathered findings from the initial rollout"
    for e in entries:
        reason = str(e.get("reason", ""))
        assert reason and not reason.startswith("TODO"), (
            f"baseline entry without a real justification: {e}")


def test_every_rule_is_exercised_by_tree_or_baseline():
    # each rule must have teeth on THIS tree: either a baselined real
    # finding or (for rules whose findings were all fixed) a fixture;
    # assert the baseline covers the rules we grandfathered — including
    # the whole-program rules' deliberate findings (the fused/np-scalar
    # fast-path syncs, the two load-bearing package import cycles)
    rules_in_baseline = {e["rule"]
                        for e in load_baseline(default_baseline_path())}
    assert {"hot-path-import", "host-sync", "unguarded-global",
            "cross-host-sync", "import-layering", "naked-retry",
            # ISSUE 14: the race detector's reasoned survivors (lock-free
            # flight ring, GIL-atomic endpoint refresh, the engine's
            # single-consumer step state)
            "shared-state-race",
            # ISSUE 19: the blocking analysis' reasoned survivors (the
            # native-build lock, the by-design serialized push RPCs, the
            # cache-miss jit under the dispatch root, the resolved-by-
            # protocol future waits in http/router)
            "blocking-under-lock", "unbounded-wait",
            "hot-path-stall"} <= rules_in_baseline


# ---------------------------------------------------------------------------
# dogfood (ISSUE 19): the linter lints itself
# ---------------------------------------------------------------------------

def test_linter_tree_lints_itself_clean():
    # tools/lint under its own rules, no baseline allowance: no silent
    # except-pass, no unlocked module-global mutation, and no function-
    # level imports in the scan hot loop (the one reviewed cycle-break in
    # build_summary carries a pragma). Scoped to the three rules that are
    # meaningful for a stdlib-only single-threaded tool — thread/device
    # rules have nothing to bite on here.
    res = run_lint(paths=["tools/lint"],
                   rules=["silent-swallow", "unguarded-global",
                          "hot-path-import"],
                   config={"hot_path_modules": [
                       "tools/lint/wholeprogram/summary.py",
                       "tools/lint/wholeprogram/project.py",
                       "tools/lint/astutil.py"]},
                   baseline_entries=[])
    assert res.errors == []
    assert [f.text() for f in res.new] == []
    # a renamed tree must fail loudly, not lint zero files to green
    assert res.files_checked >= 20
