"""Reduced-footprint optimizer state (the ≥1.5B-on-chip enabler).

bf16 m/v accumulators and master-weight-free bf16 AdamW (stochastic
rounding) must track the fp32-state trajectory — the loss-parity contract
that converts "halve the optimizer memory" from a flag into a usable
training mode. Reference keeps fp32 m/v + masters unconditionally
(upstream python/paddle/optimizer/adam.py, python/paddle/amp/); the narrow
variants are the TPU-native extension SURVEY §6's north star needs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn

D = 16


def _data(steps=24, batch=16):
    rng = np.random.default_rng(0)
    w_true = rng.normal(0, 1, (D, 1)).astype(np.float32)
    xs = rng.normal(0, 1, (steps, batch, D)).astype(np.float32)
    ys = xs @ w_true + 0.01 * rng.normal(0, 1, (steps, batch, 1)).astype(np.float32)
    return xs, ys


def _train(moment_dtype="float32", master=None, sr=True, fused=False,
           cast_bf16=False, steps=24, seed=5):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(D, 32), nn.Tanh(), nn.Linear(32, 1))
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-2, parameters=model.parameters(),
        use_multi_tensor=fused, moment_dtype=moment_dtype,
        use_master_weights=master, stochastic_rounding=sr)
    if cast_bf16:
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16",
                                         master_weight=master)
    xs, ys = _data(steps)
    losses = []
    for i in range(steps):
        x, y = paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])
        if cast_bf16:
            with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
                out = model(x)
            loss = ((out.astype("float32") - y) ** 2).mean()
        else:
            loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return np.asarray(losses), opt


def test_bf16_moments_track_fp32_trajectory():
    ref, _ = _train(moment_dtype="float32")
    lo, opt = _train(moment_dtype="bfloat16")
    assert lo[-1] < 0.1 * lo[0], "bf16-moment training must converge"
    # trajectories stay in the same neighborhood throughout
    np.testing.assert_allclose(lo, ref, rtol=0.25, atol=0.02)
    # and the state really is narrow
    m = next(iter(opt._accumulators["moment1"].values()))
    assert m._data.dtype == jnp.bfloat16


def test_bf16_moments_track_fp32_trajectory_fused():
    ref, _ = _train(moment_dtype="float32", fused=True)
    lo, opt = _train(moment_dtype="bfloat16", fused=True)
    assert lo[-1] < 0.1 * lo[0]
    np.testing.assert_allclose(lo, ref, rtol=0.25, atol=0.02)
    assert opt._fused["m"]._data.dtype == jnp.bfloat16
    assert opt._fused["v"]._data.dtype == jnp.bfloat16


def test_master_free_bf16_matches_mastered_bf16():
    """The headline mode: bf16 params, NO fp32 masters, stochastic
    rounding. Must land in the same loss neighborhood as the master-weight
    run (the reference-equivalent baseline)."""
    ref, ref_opt = _train(cast_bf16=True, master=True)
    assert len(ref_opt._master_weights) > 0
    lo, opt = _train(cast_bf16=True, master=False, moment_dtype="bfloat16")
    assert len(opt._master_weights) == 0, "masters must not exist"
    assert lo[-1] < 0.15 * lo[0], "master-free bf16 training must converge"
    np.testing.assert_allclose(lo, ref, rtol=0.35, atol=0.05)


def test_master_free_fused_flat_buffer_is_bf16():
    lo, opt = _train(cast_bf16=True, master=False, moment_dtype="bfloat16",
                     fused=True)
    fs = opt._fused
    assert fs["master"]._data.dtype == jnp.bfloat16
    assert fs["m"]._data.dtype == jnp.bfloat16
    assert lo[-1] < 0.15 * lo[0]
    # total optimizer-state bytes: 3 bf16 buffers (flat, m, v) = 6 B/param
    per_param = sum(b._data.dtype.itemsize
                    for b in (fs["master"], fs["m"], fs["v"]))
    assert per_param == 6


@pytest.mark.slow
def test_master_free_without_sr_stalls_where_sr_learns():
    """Proof stochastic rounding is load-bearing: with a small LR the
    deterministic bf16 write-back loses sub-ulp updates and learns slower
    than SR over the same schedule."""
    paddle.seed(9)
    # single weight, tiny gradient updates relative to bf16 ulp at |w|~1
    w_sr = None
    outs = {}
    for sr in (True, False):
        paddle.seed(9)
        model = nn.Linear(1, 1, bias_attr=False)
        model.weight._set_data(jnp.asarray([[1.0]], jnp.bfloat16))
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=model.parameters())
        opt._use_master_weights = False
        opt._stochastic_rounding = sr
        # constant tiny gradient: 1e-4 ≈ ulp(1.0)/80 for bf16
        for _ in range(4000):
            model.weight._grad = None
            model.weight.grad  # ensure attribute exists

            g = jnp.asarray([[1e-4]], jnp.bfloat16)
            from paddle_tpu.core.tensor import Tensor
            model.weight._grad = Tensor(g, stop_gradient=True)
            opt.step()
        outs[sr] = float(np.asarray(model.weight._data.astype(jnp.float32)))
    # deterministic rounding: w + 1e-4 rounds back to w every step
    assert abs(outs[False] - 1.0) < 1e-6
    # SR: E[delta] = -lr*g per step -> ~0.4 drop over 4000 steps
    assert outs[True] < 0.8


def test_stochastic_round_exact_values_unchanged():
    from paddle_tpu.optimizer import _stochastic_round_bf16
    exact = jnp.asarray([1.0, -2.5, 0.0, 3.140625], jnp.bfloat16)
    x32 = exact.astype(jnp.float32)
    for s in range(5):
        out = _stochastic_round_bf16(x32, jax.random.PRNGKey(s))
        np.testing.assert_array_equal(np.asarray(out.astype(jnp.float32)),
                                      np.asarray(x32))


def test_stochastic_round_is_unbiased():
    from paddle_tpu.optimizer import _stochastic_round_bf16
    # bf16 ulp at 1.0 is 2^-7 (7 mantissa bits); x = 1 + ulp/4 must round
    # up a quarter of the time, keeping E[out] = x
    ulp = 2.0 ** -7
    x = jnp.full((1 << 16,), 1.0 + 0.25 * ulp, jnp.float32)
    out = _stochastic_round_bf16(x, jax.random.PRNGKey(0)).astype(jnp.float32)
    frac_up = float(np.mean(np.asarray(out) > 1.0))
    assert 0.22 < frac_up < 0.28, frac_up
    mean = float(np.mean(np.asarray(out)))
    np.testing.assert_allclose(mean, 1.0 + 0.25 * ulp, rtol=3e-4)


def test_reduced_state_survives_to_static():
    """Whole-step compiled training with bf16 moments + master-free bf16
    params — the exact bench configuration — must run and learn."""
    paddle.seed(4)
    model = nn.Sequential(nn.Linear(D, 32), nn.Tanh(), nn.Linear(32, 1))
    opt = paddle.optimizer.AdamW(learning_rate=3e-2,
                                 parameters=model.parameters(),
                                 moment_dtype="bfloat16",
                                 use_master_weights=False)
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16", master_weight=False)
    xs, ys = _data(20)

    @paddle.jit.to_static
    def step(x, y):
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            out = model(x)
        loss = ((out.astype("float32") - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = [float(step(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])))
              for i in range(20)]
    assert losses[-1] < 0.2 * losses[0], losses


def test_int8_moments_track_fp32_trajectory():
    """8-bit block-quantized m/v (the bitsandbytes layout): trajectory in
    the fp32 neighborhood, state physically int8."""
    ref, _ = _train(moment_dtype="float32")
    lo, opt = _train(moment_dtype="int8")
    assert lo[-1] < 0.15 * lo[0], "int8-moment training must converge"
    np.testing.assert_allclose(lo, ref, rtol=0.35, atol=0.05)
    m = next(iter(opt._accumulators["moment1"].values()))
    assert m._data.dtype == jnp.int8
    s = next(iter(opt._accumulators["moment1_scale"].values()))
    assert s._data.dtype == jnp.float32


def test_int8_moments_master_free_end_to_end():
    lo, opt = _train(cast_bf16=True, master=False, moment_dtype="int8")
    assert len(opt._master_weights) == 0
    assert lo[-1] < 0.2 * lo[0], lo


def test_int8_rejects_fused_path():
    paddle.seed(3)
    m = nn.Linear(4, 4)
    with pytest.raises(ValueError, match="int8"):
        paddle.optimizer.AdamW(parameters=m.parameters(),
                               use_multi_tensor=True, moment_dtype="int8")


def test_q8_quantize_roundtrip():
    from paddle_tpu.optimizer import _q8_dequantize, _q8_quantize
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 3, (1000,)).astype(np.float32) *
                    rng.uniform(0.001, 10, (1000,)).astype(np.float32))
    q, s = _q8_quantize(x)
    back = _q8_dequantize(q, s, (1000,))
    # per-block absmax: error bounded by absmax/254 per block
    err = np.abs(np.asarray(back - x))
    assert err.max() <= float(jnp.max(jnp.abs(x))) / 127.0 + 1e-6


def test_q8_chunked_update_matches_single_chunk():
    """Round-4: the int8 update runs per-chunk under lax.map (so fp32
    transients stay O(chunk) at the 2B single-chip ceiling). Multi-chunk
    (tiny _Q8_CHUNK_ELEMS) must match the single-chunk trajectory — the
    blockwise quantization math is chunk-shape invariant, pinned BITWISE
    on the int8 moment state below. The fp32 weights get a few-ulp
    allowance: XLA does not promise identical fusion/fma ordering between
    a lax.map body and the equivalent straight-line program, and some CPU
    backends (this container's jax 0.4.37 among them) produce 1-ulp
    differences in the weight-update arithmetic."""
    import paddle_tpu.optimizer as optim

    def run(chunk_elems):
        paddle.seed(11)
        model = nn.Linear(64, 96)  # 6144 weights -> 3 blocks of 2048
        opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters(),
                                     moment_dtype="int8",
                                     stochastic_rounding=False)
        old = optim.Adam._Q8_CHUNK_ELEMS
        optim.Adam._Q8_CHUNK_ELEMS = chunk_elems
        try:
            x = paddle.to_tensor(
                np.random.default_rng(5).normal(0, 1, (8, 64))
                .astype(np.float32))
            for _ in range(4):
                loss = (model(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
        finally:
            optim.Adam._Q8_CHUNK_ELEMS = old
        return (np.asarray(model.weight._data.astype(jnp.float32)),
                np.asarray(next(iter(
                    opt._accumulators["moment1"].values()))._data))

    w_multi, m_multi = run(2048)          # 1 block/chunk -> 3 chunks
    w_single, m_single = run(8 * 1024 * 1024)  # everything in one chunk
    np.testing.assert_allclose(w_multi, w_single, rtol=0, atol=6e-8)
    np.testing.assert_array_equal(m_multi, m_single)


def test_q8_legacy_linear_v_checkpoint_converts_on_load():
    """Round-3 int8 checkpoints stored moment2 as LINEAR v; the current
    layout stores sqrt(v) under the versioned key moment2_sqrt. Loading a
    legacy dict must convert (binding raw would shrink v ~1000x)."""
    paddle.seed(13)
    model = nn.Linear(64, 32)
    opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters(),
                                 moment_dtype="int8",
                                 stochastic_rounding=False)
    p = model.weight
    n = p.size
    nb = -(-n // 2048)
    rng = np.random.default_rng(0)
    v_true = (rng.uniform(0.001, 1.0, (nb * 2048,)) ** 2).astype(np.float32)
    blocks = v_true.reshape(nb, 2048)
    scale = np.abs(blocks).max(1) / 127.0
    q_lin = np.clip(np.round(blocks / scale[:, None]), -127, 127) \
        .astype(np.int8)
    legacy = {
        "step": 3,
        f"{p.name}_moment2": paddle.to_tensor(q_lin),
        f"{p.name}_moment2_scale": paddle.to_tensor(scale.astype(np.float32)),
    }
    with pytest.warns(UserWarning, match="sqrt-space"):
        opt.set_state_dict(legacy)
    assert "moment2" not in opt._accumulators
    q = opt._accumulators["moment2_sqrt"][id(p)]._data
    s = opt._accumulators["moment2_sqrt_scale"][id(p)]._data
    got_v = (np.asarray(q, np.float32) * np.asarray(s)[:, None]) ** 2
    # reconstruction error bounded by double quantization, relative scale
    np.testing.assert_allclose(got_v.reshape(-1), v_true, atol=2e-2)


def test_q8_pallas_kernel_matches_chunked_path():
    """Round 5: the fused Pallas int8-Adam kernel (interpret mode on CPU)
    must track the chunked XLA path — same blockwise quantization rule,
    same sqrt-space v, same update math. int8 codes may differ by 1 at
    quantization boundaries (different fp32 fusion), params stay within
    float tolerance."""
    import jax
    import paddle_tpu.optimizer as optim
    from paddle_tpu.ops.q8_adam_pallas import q8_adam_update

    rng = np.random.default_rng(7)
    nb, B = 4, 2048
    n = nb * B
    base = rng.normal(0, 0.1, (nb, B)).astype(np.float32)
    grad = rng.normal(0, 0.01, (nb, B)).astype(np.float32)
    m_q = np.zeros((nb, B), np.int8)
    m_s = np.ones((nb, 1), np.float32)
    v_q = np.zeros((nb, B), np.int8)
    v_s = np.ones((nb, 1), np.float32)
    lr, wd, eps, b1, b2 = 1e-2, 0.01, 1e-8, 0.9, 0.999
    c1, c2 = 1.0 - b1, 1.0 - b2  # t = 1
    scalars = jnp.array([lr, wd, c1, c2, eps, b1, b2], jnp.float32)
    seed = jnp.zeros((1,), jnp.int32)

    mq2, ms2, vq2, vs2, newb = q8_adam_update(
        jnp.asarray(m_q), jnp.asarray(m_s), jnp.asarray(v_q),
        jnp.asarray(v_s), jnp.asarray(base), jnp.asarray(grad),
        scalars, seed, use_sr=False, has_wd=True, interpret=True)

    # reference: the same math in numpy (the rule _q8_quantize pins)
    g32 = grad
    nm = b1 * (m_q.astype(np.float32) * m_s) + (1 - b1) * g32
    nv = b2 * (v_q.astype(np.float32) * v_s) ** 2 + (1 - b2) * g32 * g32
    msc = np.abs(nm).max(1, keepdims=True) / 127.0
    msc[msc == 0] = 1.0
    vsc = np.sqrt(nv).max(1, keepdims=True) / 127.0
    vsc[vsc == 0] = 1.0
    upd = base * (1 - lr * wd) - lr * (nm / c1) / (np.sqrt(nv / c2) + eps)

    # numpy promotes the python-float coefficients to float64 where the
    # kernel stays fp32 — a few-ulp gap on the tiny v scales is expected
    np.testing.assert_allclose(np.asarray(ms2), msc, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(vs2), vsc, rtol=2e-5)
    assert np.abs(np.asarray(mq2).astype(np.int32) -
                  np.clip(np.round(nm / msc), -127, 127)).max() <= 1
    np.testing.assert_allclose(np.asarray(newb), upd, rtol=1e-5, atol=1e-7)


def test_q8_pallas_routing_gate():
    """The Pallas route is TPU-only and block-multiple-only; CPU and
    ragged params stay on the chunked XLA path (this whole test file runs
    on CPU, so passing tests above already prove the fallback works)."""
    import jax
    assert jax.default_backend() == "cpu"  # test env contract
    paddle.seed(3)
    model = nn.Linear(64, 96)  # n=6144: block-multiple, but CPU -> XLA path
    opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters(),
                                 moment_dtype="int8",
                                 stochastic_rounding=False)
    x = paddle.to_tensor(np.ones((4, 64), np.float32))
    loss = (model(x) ** 2).mean()
    loss.backward()
    opt.step()  # must not raise (would, if Pallas ran on CPU)
