"""Compiled (shard_map+ppermute) pipeline schedule vs serial reference."""

import pytest

# the whole module drives the shard_map pipeline engine
pytestmark = pytest.mark.requires_shard_map

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet.tpu_pipeline import (pipelined_forward,
                                                       stack_stage_params)

S, M, B, D = 4, 8, 2, 16


def _setup():
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
    rng = np.random.default_rng(0)
    per_stage = [{"w": jnp.asarray(rng.normal(0, 0.3, (D, D)).astype(np.float32)),
                  "b": jnp.asarray(rng.normal(0, 0.1, (D,)).astype(np.float32))}
                 for _ in range(S)]
    micro = jnp.asarray(rng.normal(0, 1, (M, B, D)).astype(np.float32))
    return mesh, per_stage, micro


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def test_pipelined_forward_matches_serial():
    mesh, per_stage, micro = _setup()
    stacked = stack_stage_params(per_stage, mesh, "pp")
    out = pipelined_forward(_stage_fn, stacked, micro, mesh, "pp")
    ref = micro
    for p in per_stage:
        ref = _stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_pipelined_grad_matches_serial():
    mesh, per_stage, micro = _setup()
    stacked = stack_stage_params(per_stage, mesh, "pp")

    def loss_fn(params, mi):
        return jnp.sum(pipelined_forward(_stage_fn, params, mi, mesh, "pp") ** 2)

    g = jax.grad(loss_fn)(stacked, micro)

    def ref_loss(params_list, mi):
        y = mi
        for p in params_list:
            y = _stage_fn(p, y)
        return jnp.sum(y ** 2)

    gref = jax.grad(ref_loss)(per_stage, micro)
    for s in range(S):
        np.testing.assert_allclose(np.asarray(g["w"][s]),
                                   np.asarray(gref[s]["w"]), atol=1e-4)
        np.testing.assert_allclose(np.asarray(g["b"][s]),
                                   np.asarray(gref[s]["b"]), atol=1e-4)


# ---------------------------------------------------------------------------
# Fleet-wired pipeline: PipelineLayer -> PipelinedStack, loss parity vs serial
# ---------------------------------------------------------------------------

import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.pipeline_parallel import (LayerDesc,
                                                            PipelineLayer,
                                                            SharedLayerDesc)
from paddle_tpu.distributed.topology import (HybridCommunicateGroup,
                                             set_hybrid_communicate_group)

D, NBLK = 16, 8


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(D, D)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(D, 4)

    def forward(self, x):
        return self.fc(x)


class Emb(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(D, D)

    def forward(self, x):
        return self.fc(x)


def _mse(out, label):
    return ((out - label) ** 2).mean()


def _build_pipeline_layer():
    return PipelineLayer(
        layers=[LayerDesc(Emb)] + [LayerDesc(Block) for _ in range(NBLK)]
        + [LayerDesc(Head)],
        loss_fn=_mse)


def _train(model_like, params, data, labels, steps=4, lr=0.1):
    opt = paddle.optimizer.SGD(learning_rate=lr, parameters=params)
    losses = []
    for i in range(steps):
        if hasattr(model_like, "train_batch"):
            loss = model_like.train_batch(
                (data, labels), optimizer=opt)
        else:
            loss = _mse(model_like(data), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("dp,pp", [
    pytest.param(1, 2, marks=pytest.mark.slow),
    pytest.param(1, 4, marks=pytest.mark.slow),
    pytest.param(2, 4, marks=pytest.mark.slow),
])
def test_fleet_pipeline_parity_vs_serial(dp, pp):
    rng = np.random.default_rng(7)
    data_np = rng.normal(0, 1, (8, D)).astype(np.float32)
    label_np = rng.normal(0, 1, (8, 4)).astype(np.float32)

    # serial reference: same seed -> identical init
    paddle.seed(123)
    set_hybrid_communicate_group(None)
    serial = _build_pipeline_layer()
    s_losses = _train(serial, serial.parameters(),
                      paddle.to_tensor(data_np), paddle.to_tensor(label_np))

    # pipelined: rebuild with the same seed under a dp x pp mesh
    paddle.seed(123)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": dp, "pp_degree": pp}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = _build_pipeline_layer()
        wrapped = fleet.distributed_model(model)
        assert wrapped._engine is not None, "pipelined path not taken"
        p_losses = _train(wrapped, wrapped.parameters(),
                          paddle.to_tensor(data_np),
                          paddle.to_tensor(label_np))
    finally:
        set_hybrid_communicate_group(None)

    np.testing.assert_allclose(p_losses, s_losses, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_fleet_pipeline_shared_embedding_grads():
    """Tied embed/head (SharedLayerDesc): both uses hit one parameter and
    its gradient is the sum of both paths — no explicit allreduce needed."""

    class TiedEmb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter([D, D], dtype="float32")

        def forward(self, x):
            return paddle.matmul(x, self.weight)

    def head_fwd(layer, x):
        return paddle.matmul(x, layer.weight.t())

    def build():
        return PipelineLayer(
            layers=[SharedLayerDesc("emb", TiedEmb),
                    LayerDesc(Block), LayerDesc(Block),
                    LayerDesc(Block), LayerDesc(Block),
                    SharedLayerDesc("emb", TiedEmb, forward_func=head_fwd)],
            loss_fn=_mse)

    rng = np.random.default_rng(3)
    data_np = rng.normal(0, 1, (4, D)).astype(np.float32)
    label_np = rng.normal(0, 1, (4, D)).astype(np.float32)

    paddle.seed(77)
    set_hybrid_communicate_group(None)
    serial = build()
    s_losses = _train(serial, serial.parameters(), paddle.to_tensor(data_np),
                      paddle.to_tensor(label_np))

    paddle.seed(77)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = build()
        wrapped = fleet.distributed_model(model)
        assert wrapped._engine is not None
        # the tied weight must appear exactly ONCE in the engine's param
        # list (same object serves embed and head; duplication would break
        # the summed-gradient tying)
        params = wrapped.parameters()
        assert len({id(p) for p in params}) == len(params)
        tied_obj = model._shared["emb"].weight
        assert sum(1 for p in params if p is tied_obj) == 1
        p_losses = _train(wrapped, wrapped.parameters(),
                          paddle.to_tensor(data_np),
                          paddle.to_tensor(label_np))
    finally:
        set_hybrid_communicate_group(None)

    np.testing.assert_allclose(p_losses, s_losses, rtol=2e-4, atol=2e-5)


def test_non_uniform_stack_falls_back():
    """hetero_pipeline=False restores the documented grad-accumulation
    fallback for stacks the uniform engine cannot place."""
    paddle.seed(5)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"hetero_pipeline": False}
        fleet.init(is_collective=True, strategy=strategy)
        model = PipelineLayer(layers=[LayerDesc(Emb), LayerDesc(Head)],
                              loss_fn=_mse)
        with pytest.warns(UserWarning, match="grad-accumulation"):
            wrapped = fleet.distributed_model(model)
        assert wrapped._engine is None
    finally:
        set_hybrid_communicate_group(None)


def test_hetero_shape_varying_stack_dismantles_to_fallback():
    """Round 5: a shape-VARYING non-uniform stack gets the hetero engine at
    construction; the first call's boundary-shape validation DISMANTLES it
    (weights unpacked back into the original blocks) and training
    continues on the grad-accumulation fallback — the pre-round-5 UX for
    such stacks, with a warning instead of a silent engine."""
    from paddle_tpu.distributed.fleet.tpu_pipeline import HeteroPipelinedStack
    paddle.seed(5)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(9)
        model = PipelineLayer(layers=[LayerDesc(Emb), LayerDesc(Head)],
                              loss_fn=_mse)
        wrapped = fleet.distributed_model(model)
        assert isinstance(wrapped._engine, HeteroPipelinedStack)
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(0, 1, (4, D)).astype(np.float32))
        with pytest.warns(UserWarning, match="Dismantled"):
            out = wrapped(x)
        assert wrapped._engine is None
        assert out.shape == [4, 4]
        # the dismantled weights are the originals: the fallback output
        # matches a same-seed serial twin
        paddle.seed(9)
        set_hybrid_communicate_group(None)
        twin = PipelineLayer(layers=[LayerDesc(Emb), LayerDesc(Head)],
                             loss_fn=_mse)
        np.testing.assert_allclose(out.numpy(), twin(x).numpy(),
                                   rtol=1e-5, atol=1e-6)
    finally:
        set_hybrid_communicate_group(None)


class WideBlock(nn.Layer):
    def __init__(self):
        super().__init__()
        self.up = nn.Linear(D, 2 * D)
        self.down = nn.Linear(2 * D, D)

    def forward(self, x):
        return x + self.down(paddle.tanh(self.up(x)))


class NarrowBlock(nn.Layer):
    def __init__(self):
        super().__init__()
        self.up = nn.Linear(D, D // 2)
        self.down = nn.Linear(D // 2, D)

    def forward(self, x):
        return x + self.down(paddle.nn.functional.relu(self.up(x)))


class GatedBlock(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(D, D)
        self.gate = nn.Linear(D, D)

    def forward(self, x):
        return x + self.fc(x) * paddle.nn.functional.sigmoid(self.gate(x))


def _build_hetero_layer():
    # aperiodic mix: no stage-periodic run exists, but every block is
    # shape-preserving (B, D) -> (B, D)
    descs = [LayerDesc(Emb), LayerDesc(WideBlock), LayerDesc(NarrowBlock),
             LayerDesc(WideBlock), LayerDesc(GatedBlock),
             LayerDesc(NarrowBlock), LayerDesc(GatedBlock), LayerDesc(Head)]
    return PipelineLayer(layers=descs, loss_fn=_mse)


@pytest.mark.slow
def test_hetero_pipeline_parity_vs_serial():
    """Round 5 (VERDICT r4 #4): non-uniform stacks train with REAL stage
    placement — switch-branch stages in the ppermute scan — and match the
    serial model's loss trajectory."""
    from paddle_tpu.distributed.fleet.tpu_pipeline import HeteroPipelinedStack
    rng = np.random.default_rng(21)
    data_np = rng.normal(0, 1, (8, D)).astype(np.float32)
    label_np = rng.normal(0, 1, (8, 4)).astype(np.float32)

    paddle.seed(77)
    set_hybrid_communicate_group(None)
    serial = _build_hetero_layer()
    s_losses = _train(serial, serial.parameters(),
                      paddle.to_tensor(data_np), paddle.to_tensor(label_np))

    paddle.seed(77)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = _build_hetero_layer()
        wrapped = fleet.distributed_model(model)
        assert isinstance(wrapped._engine, HeteroPipelinedStack), \
            "hetero engine not selected"
        p_losses = _train(wrapped, wrapped.parameters(),
                          paddle.to_tensor(data_np),
                          paddle.to_tensor(label_np))
    finally:
        set_hybrid_communicate_group(None)

    np.testing.assert_allclose(p_losses, s_losses, rtol=2e-4, atol=2e-5)


def test_hetero_pipeline_stage_placement_physical():
    """Each device stores only its stage's (padded) fused weights, and the
    compiled schedule really hops activations (collective-permute in HLO)."""
    from paddle_tpu.distributed.fleet.tpu_pipeline import HeteroPipelinedStack
    paddle.seed(3)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 4}
        strategy.pipeline_configs = {"accumulate_steps": 4}
        fleet.init(is_collective=True, strategy=strategy)
        model = _build_hetero_layer()
        wrapped = fleet.distributed_model(model)
        eng = wrapped._engine
        assert isinstance(eng, HeteroPipelinedStack)
        buf = eng._buffers["float32"]._data
        S = 4
        assert buf.shape[0] == S
        shards = buf.addressable_shards
        assert len(shards) >= S
        per_dev = {sh.device for sh in shards}
        assert len(per_dev) >= S  # spread over the pp axis, 1 row each
        for sh in shards:
            assert sh.data.shape[0] == 1  # one stage row per device

        # HLO of the schedule carries the ppermute hop
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.tpu_pipeline import pipelined_forward
        mesh = eng._mesh
        rows = {dt: eng._buffers[dt]._data for dt in eng._dtypes}
        micro = jnp.zeros((4, 2, D), jnp.float32)

        def fn(rows, micro):
            def stage_fn(rows_local, h):
                stage = jax.lax.axis_index("pp")
                return jax.lax.switch(
                    stage, [lambda h, s=s: eng._branch(s)(rows_local, h)
                            for s in range(S)], h)
            return pipelined_forward(stage_fn, rows, micro, mesh, "pp")

        hlo = jax.jit(fn).lower(rows, micro).compile().as_text()
        assert "collective-permute" in hlo
    finally:
        set_hybrid_communicate_group(None)


@pytest.mark.slow
def test_interleaved_vpp_parity_vs_serial():
    """virtual_pp_degree=2 (interleaved placement, upstream VPP parity):
    same numerics as serial; the option exists for schedule parity even
    though RESULTS.md documents the compiled-scan slowdown."""
    rng = np.random.default_rng(31)
    data_np = rng.normal(0, 1, (8, D)).astype(np.float32)
    label_np = rng.normal(0, 1, (8, 4)).astype(np.float32)

    paddle.seed(55)
    set_hybrid_communicate_group(None)
    serial = _build_pipeline_layer()
    s_losses = _train(serial, serial.parameters(),
                      paddle.to_tensor(data_np), paddle.to_tensor(label_np))

    paddle.seed(55)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 2,
                                     "virtual_pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = _build_pipeline_layer()
        wrapped = fleet.distributed_model(model)
        assert wrapped._engine is not None and wrapped._engine._V == 2
        p_losses = _train(wrapped, wrapped.parameters(),
                          paddle.to_tensor(data_np),
                          paddle.to_tensor(label_np))
    finally:
        set_hybrid_communicate_group(None)

    np.testing.assert_allclose(p_losses, s_losses, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_engine_state_dict_roundtrip_and_eval():
    """Review regression: after engine construction, state_dict/forward on
    the wrapper must reflect the TRAINED stacked params (not the stale
    truncated PipelineLayer), and eval_batch must not inherit the training
    microbatch split."""
    paddle.seed(11)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 4}
        fleet.init(is_collective=True, strategy=strategy)
        model = _build_pipeline_layer()
        wrapped = fleet.distributed_model(model)
        assert wrapped._engine is not None
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(0, 1, (8, D)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(0, 1, (8, 4)).astype(np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=wrapped.parameters())
        before = {k: np.asarray(v._data).copy()
                  for k, v in wrapped.state_dict().items()}
        wrapped.train_batch((x, y), optimizer=opt)
        after = wrapped.state_dict()
        changed = any(not np.allclose(before[k], np.asarray(v._data))
                      for k, v in after.items())
        assert changed, "state_dict does not reflect trained params"
        # roundtrip
        wrapped.set_state_dict(after)
        # eval on a batch size (6) NOT divisible by accumulate_steps (4)
        x6 = paddle.to_tensor(rng.normal(0, 1, (6, D)).astype(np.float32))
        y6 = paddle.to_tensor(rng.normal(0, 1, (6, 4)).astype(np.float32))
        loss = wrapped.eval_batch((x6, y6))
        assert np.isfinite(float(loss))
        # direct use of the consumed PipelineLayer is an error, not silence
        import pytest as _pytest
        with _pytest.raises(RuntimeError):
            model(x)
    finally:
        set_hybrid_communicate_group(None)


@pytest.mark.slow
def test_fleet_pipeline_parity_compiled_fast():
    """Fast-subset guard for the pipelined engine: pp=2 under to_static,
    2 steps, loss parity vs serial (full matrix in the slow-marked tests)."""
    rng = np.random.default_rng(9)
    data_np = rng.normal(0, 1, (8, D)).astype(np.float32)
    label_np = rng.normal(0, 1, (8, 4)).astype(np.float32)

    def compiled_losses(model_like, params, is_pp):
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=params)

        @paddle.jit.to_static
        def step(x, y):
            if is_pp:
                return model_like.train_batch((x, y), optimizer=opt)
            loss = _mse(model_like(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        x, y = paddle.to_tensor(data_np), paddle.to_tensor(label_np)
        return [float(step(x, y)) for _ in range(2)]

    paddle.seed(321)
    set_hybrid_communicate_group(None)
    serial = _build_pipeline_layer()
    ref = compiled_losses(serial, serial.parameters(), False)

    paddle.seed(321)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = _build_pipeline_layer()
        wrapped = fleet.distributed_model(model)
        assert wrapped._engine is not None
        got = compiled_losses(wrapped, wrapped.parameters(), True)
        # eager train_batch path too (one step): first-loss must equal the
        # serial first loss (same init, same data)
        paddle.seed(321)
        set_hybrid_communicate_group(None)
        strategy2 = fleet.DistributedStrategy()
        strategy2.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
        strategy2.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy2)
        model2 = _build_pipeline_layer()
        wrapped2 = fleet.distributed_model(model2)
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=wrapped2.parameters())
        eager_loss = float(wrapped2.train_batch(
            (paddle.to_tensor(data_np), paddle.to_tensor(label_np)),
            optimizer=opt2))
        np.testing.assert_allclose(eager_loss, ref[0], rtol=2e-4)
    finally:
        set_hybrid_communicate_group(None)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# Heterogeneous (periodic) stacks: BERT-shaped alternating entries pipeline
# ---------------------------------------------------------------------------

class Attnish(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(D, D)

    def forward(self, x):
        return x + paddle.tanh(self.fc(x))


class MLPish(nn.Layer):
    def __init__(self):
        super().__init__()
        self.up = nn.Linear(D, 2 * D)
        self.down = nn.Linear(2 * D, D)

    def forward(self, x):
        return x + self.down(paddle.nn.functional.gelu(self.up(x)))


def test_find_uniform_run_periodic():
    from paddle_tpu.distributed.fleet.tpu_pipeline import find_uniform_run

    # (Attn, MLP) x 8 over 4 stages: period 2, 16 entries usable
    entries = []
    for _ in range(8):
        entries.append((Attnish(), None))
        entries.append((MLPish(), None))
    assert find_uniform_run(entries, 4) == (0, 16)
    # with edges around it
    bounded = [(Emb(), None)] + entries + [(Head(), None)]
    start, used = find_uniform_run(bounded, 4)
    assert (start, used) == (1, 16)
    # 6 repeats over 4 stages: only 4 repeats (8 entries) usable
    short = entries[:12]
    start, used = find_uniform_run(short, 4)
    assert used == 8


@pytest.mark.parametrize("dp,pp", [
    pytest.param(1, 2, marks=pytest.mark.slow),
    pytest.param(2, 4, marks=pytest.mark.slow),
])
def test_fleet_pipeline_periodic_stack_parity(dp, pp):
    """BERT-shaped PipelineLayer (alternating attention/MLP entries) takes
    the truly pipelined path and matches the serial trajectory."""
    def build():
        layers = [LayerDesc(Emb)]
        for _ in range(4):
            layers.append(LayerDesc(Attnish))
            layers.append(LayerDesc(MLPish))
        layers.append(LayerDesc(Head))
        return PipelineLayer(layers=layers, loss_fn=_mse)

    rng = np.random.default_rng(11)
    data_np = rng.normal(0, 1, (8, D)).astype(np.float32)
    label_np = rng.normal(0, 1, (8, 4)).astype(np.float32)

    paddle.seed(321)
    set_hybrid_communicate_group(None)
    serial = build()
    s_losses = _train(serial, serial.parameters(),
                      paddle.to_tensor(data_np), paddle.to_tensor(label_np))

    paddle.seed(321)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": dp, "pp_degree": pp}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = build()
        wrapped = fleet.distributed_model(model)
        assert wrapped._engine is not None, "periodic stack must pipeline"
        assert wrapped._engine._k == (4 // pp) * 2  # repeats/stage x period
        p_losses = _train(wrapped, wrapped.parameters(),
                          paddle.to_tensor(data_np),
                          paddle.to_tensor(label_np))
    finally:
        set_hybrid_communicate_group(None)

    np.testing.assert_allclose(p_losses, s_losses, rtol=2e-4, atol=2e-5)
