"""Compiled (shard_map+ppermute) pipeline schedule vs serial reference."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet.tpu_pipeline import (pipelined_forward,
                                                       stack_stage_params)

S, M, B, D = 4, 8, 2, 16


def _setup():
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
    rng = np.random.default_rng(0)
    per_stage = [{"w": jnp.asarray(rng.normal(0, 0.3, (D, D)).astype(np.float32)),
                  "b": jnp.asarray(rng.normal(0, 0.1, (D,)).astype(np.float32))}
                 for _ in range(S)]
    micro = jnp.asarray(rng.normal(0, 1, (M, B, D)).astype(np.float32))
    return mesh, per_stage, micro


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def test_pipelined_forward_matches_serial():
    mesh, per_stage, micro = _setup()
    stacked = stack_stage_params(per_stage, mesh, "pp")
    out = pipelined_forward(_stage_fn, stacked, micro, mesh, "pp")
    ref = micro
    for p in per_stage:
        ref = _stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipelined_grad_matches_serial():
    mesh, per_stage, micro = _setup()
    stacked = stack_stage_params(per_stage, mesh, "pp")

    def loss_fn(params, mi):
        return jnp.sum(pipelined_forward(_stage_fn, params, mi, mesh, "pp") ** 2)

    g = jax.grad(loss_fn)(stacked, micro)

    def ref_loss(params_list, mi):
        y = mi
        for p in params_list:
            y = _stage_fn(p, y)
        return jnp.sum(y ** 2)

    gref = jax.grad(ref_loss)(per_stage, micro)
    for s in range(S):
        np.testing.assert_allclose(np.asarray(g["w"][s]),
                                   np.asarray(gref[s]["w"]), atol=1e-4)
        np.testing.assert_allclose(np.asarray(g["b"][s]),
                                   np.asarray(gref[s]["b"]), atol=1e-4)
