"""Reshard coverage matrix (VERDICT r2 item 8; upstream
paddle/phi/core/distributed/auto_parallel/reshard/ transition functions).

Two layers of guarantees:

* the full placement-transition matrix (Replicate / Shard(0) / Shard(1) /
  Partial -> each other) on 1D and 2D meshes preserves the logical value
  and the placement metadata — ``reshard`` is ``device_put`` to the target
  layout; Partial at the eager boundary is metadata (the reduction is
  materialized — partial values exist INSIDE compiled programs where XLA
  tracks them);
* the compiled-program layer really emits the minimal collective per
  transition: r->s lowers to a local slice (no collective), s->r to an
  all-gather, s0->s1 to an all-to-all (never gather+scatter through a
  replicated intermediate), and partial-consumption to
  reduce-scatter/all-reduce — asserted on HLO text.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import Partial, Replicate, Shard

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs the 8-device CPU mesh")


def _mesh_1d():
    return dist.ProcessMesh(np.arange(8), dim_names=["x"])


def _mesh_2d():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])


_PLACEMENTS_1D = [
    [Replicate()], [Shard(0)], [Shard(1)], [Partial()],
]
_PLACEMENTS_2D = [
    [Replicate(), Replicate()], [Shard(0), Replicate()],
    [Replicate(), Shard(1)], [Shard(0), Shard(1)], [Shard(1), Shard(0)],
    [Partial(), Replicate()], [Partial(), Shard(0)],
]


@pytest.mark.parametrize("src", range(len(_PLACEMENTS_1D)))
@pytest.mark.parametrize("dst", range(len(_PLACEMENTS_1D)))
def test_reshard_matrix_1d(src, dst):
    mesh = _mesh_1d()
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    t = dist.shard_tensor(x, mesh, _PLACEMENTS_1D[src])
    out = dist.reshard(t, mesh, _PLACEMENTS_1D[dst])
    assert out.placements == _PLACEMENTS_1D[dst] or \
        all(type(a) == type(b) for a, b in
            zip(out.placements, _PLACEMENTS_1D[dst]))
    got = np.asarray(dist.unshard_dtensor(out)._data)
    np.testing.assert_allclose(got, x)
    # physical layout sanity: a Shard(k) destination leaves 1/8 of the
    # rows/cols per device
    pl = _PLACEMENTS_1D[dst][0]
    if isinstance(pl, Shard):
        shard_shapes = {s.data.shape for s in out._data.addressable_shards}
        want = list(x.shape)
        want[pl.dim] //= 8
        assert shard_shapes == {tuple(want)}


@pytest.mark.parametrize("src", range(len(_PLACEMENTS_2D)))
@pytest.mark.parametrize("dst", range(len(_PLACEMENTS_2D)))
def test_reshard_matrix_2d(src, dst):
    mesh = _mesh_2d()
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    t = dist.shard_tensor(x, mesh, _PLACEMENTS_2D[src])
    out = dist.reshard(t, mesh, _PLACEMENTS_2D[dst])
    got = np.asarray(dist.unshard_dtensor(out)._data)
    np.testing.assert_allclose(got, x)
    for mesh_dim, pl in enumerate(_PLACEMENTS_2D[dst]):
        if isinstance(pl, Shard):
            sizes = {s.data.shape[pl.dim] for s in out._data.addressable_shards}
            assert sizes == {x.shape[pl.dim] // mesh.shape[mesh_dim]}


# ---------------------------------------------------------------------------
# compiled-layer: the minimal collective per transition (HLO text)
# ---------------------------------------------------------------------------

def _jmesh():
    return Mesh(np.array(jax.devices()[:8]), ("x",))


def _relayout_hlo(src_spec, dst_spec):
    mesh = _jmesh()
    src = NamedSharding(mesh, src_spec)
    dst = NamedSharding(mesh, dst_spec)
    fn = jax.jit(lambda a: jax.lax.with_sharding_constraint(a, dst),
                 in_shardings=src, out_shardings=dst)
    return fn.lower(
        jax.ShapeDtypeStruct((8, 16), jnp.float32)).compile().as_text()


def test_hlo_replicate_to_shard_is_local_slice():
    txt = _relayout_hlo(P(), P("x"))
    assert "all-gather" not in txt and "all-to-all" not in txt
    assert "dynamic-slice" in txt or "slice" in txt


def test_hlo_shard_to_replicate_is_all_gather():
    txt = _relayout_hlo(P("x"), P())
    assert "all-gather" in txt


def test_hlo_shard0_to_shard1_is_all_to_all():
    txt = _relayout_hlo(P("x", None), P(None, "x"))
    assert "all-to-all" in txt
    assert "all-gather" not in txt, \
        "relayout must not gather through a replicated intermediate"


@pytest.mark.requires_shard_map
def test_hlo_partial_consumption_reduce_scatter():
    """Partial inside a program: psum_scatter consumes partial values with
    ONE reduce-scatter (not all-reduce + slice)."""
    mesh = _jmesh()

    def body(a):
        part = a * 2.0  # stand-in partial term per device
        return jax.lax.psum_scatter(part, "x", scatter_dimension=0,
                                    tiled=True)

    fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(),
                               out_specs=P("x")))
    txt = fn.lower(
        jax.ShapeDtypeStruct((8, 16), jnp.float32)).compile().as_text()
    assert "reduce-scatter" in txt and "all-reduce" not in txt


@pytest.mark.requires_shard_map
def test_hlo_partial_to_replicate_all_reduce():
    mesh = _jmesh()

    def body(a):
        return jax.lax.psum(a, "x")

    fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("x"),
                               out_specs=P()))
    txt = fn.lower(
        jax.ShapeDtypeStruct((8, 16), jnp.float32)).compile().as_text()
    assert "all-reduce" in txt
