"""GPT decoder family: training convergence, cached generation matches
uncached argmax decode, to_static step."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM


def _tiny():
    paddle.seed(0)
    return GPTForCausalLM(GPTConfig.tiny(vocab=97, hidden=48, layers=2,
                                         heads=4, inter=96, max_pos=64))


class TestGPT:
    def test_forward_shapes(self):
        model = _tiny()
        ids = paddle.to_tensor(np.random.randint(0, 97, (2, 16)))
        logits = model(ids)
        assert logits.shape == [2, 16, 97]
        loss, _ = model(ids, labels=ids)
        assert np.isfinite(float(loss))

    def test_trains_under_to_static(self):
        model = _tiny()
        opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=model.parameters())

        @paddle.jit.to_static
        def step(ids):
            loss, _ = model(ids, labels=ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        data = paddle.to_tensor(np.tile(np.arange(16), (4, 1)))
        losses = [float(step(data)) for _ in range(12)]
        assert losses[-1] < losses[0] * 0.8, losses

    @pytest.mark.slow
    def test_cached_generate_matches_uncached(self):
        model = _tiny()
        model.eval()
        prompt = paddle.to_tensor(np.random.randint(0, 97, (1, 8)))
        out = model.generate(prompt, max_new_tokens=6)
        assert out.shape == [1, 14]
        # uncached greedy reference
        ids = np.asarray(prompt.numpy())
        for _ in range(6):
            logits = model(paddle.to_tensor(ids))
            nxt = np.asarray(logits.numpy())[:, -1].argmax(-1)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out.numpy()), ids)

    def test_tied_embeddings_single_weight(self):
        model = _tiny()
        names = [n for n, _ in model.named_parameters()]
        assert not any("lm_head" in n for n in names)


class TestGenerationSemantics:
    def test_eos_freezes_finished_rows(self):
        from paddle_tpu.models.generation import kv_cache_generate
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor

        # toy step: always emits logits preferring token (step count + 1),
        # so row outputs are deterministic and hit eos=2 at step 2
        state = {"t": 0}

        def step(x, caches):
            state["t"] += 1
            return Tensor(jnp.zeros((2, 1, 4))), caches

        def logits_fn(h):
            v = jnp.full((2, 5), -10.0)
            tok = min(state["t"], 4)
            return Tensor(v.at[:, tok].set(10.0))

        prompt = paddle.to_tensor(np.zeros((2, 1), "int32"))
        out = kv_cache_generate(step, logits_fn, prompt, None,
                                max_new_tokens=5, eos_token_id=2)
        arr = np.asarray(out.numpy())
        # emits 1, then 2 (eos) -> loop stops with all rows finished
        assert arr.shape[1] == 3 and arr[0, -1] == 2

    def test_max_new_tokens_zero(self):
        model = _tiny()
        model.eval()
        prompt = paddle.to_tensor(np.random.randint(0, 97, (1, 5)))
        out = model.generate(prompt, max_new_tokens=0)
        assert out.shape == [1, 5]

    def test_position_overflow_raises(self):
        model = _tiny()  # max_pos = 64
        prompt = paddle.to_tensor(np.random.randint(0, 97, (1, 60)))
        with pytest.raises(ValueError, match="max_position_embeddings"):
            model.generate(prompt, max_new_tokens=10)

    def test_llama_generate_still_works(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny(vocab=64, hidden=32, layers=2,
                                              heads=4, kv_heads=2, inter=64,
                                              max_pos=64))
        m.eval()
        out = m.generate(paddle.to_tensor(np.random.randint(0, 64, (2, 4))),
                         max_new_tokens=4, eos_token_id=0)
        assert out.shape[0] == 2 and out.shape[1] <= 8


class TestScanLayers:
    """LlamaConfig.scan_layers: stacked-layer lax.scan trainer structure."""

    def test_scan_layers_matches_loop(self):
        ids_np = np.random.default_rng(0).integers(0, 128, (2, 32),
                                                   dtype=np.int32)

        def losses(scan):
            paddle.seed(0)
            from paddle_tpu.models.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
            cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=3, heads=4,
                                   kv_heads=4, inter=128, max_pos=64)
            cfg.scan_layers = scan
            cfg.recompute = scan  # checkpointed scan body
            m = LlamaForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(
                learning_rate=1e-3,
                parameters=[p for p in m.parameters() if p.trainable])

            @paddle.jit.to_static
            def step(ids):
                loss, _ = m(ids, labels=ids)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss

            ids = paddle.to_tensor(ids_np)
            return [float(step(ids)) for _ in range(4)]

        ref = losses(False)
        got = losses(True)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)

    def test_template_params_not_trainable(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        paddle.seed(0)
        cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2,
                               kv_heads=2, inter=64, max_pos=32)
        cfg.scan_layers = True
        m = LlamaForCausalLM(cfg)
        # template placeholders excluded; stacked params present
        trainable = [p for p in m.parameters() if p.trainable]
        assert any((p.name or "").startswith("llama_scan_")
                   for p in trainable)
        for layer in m.model.layers:
            for p in layer.parameters():
                assert not p.trainable


class TestScanLayoutConversion:
    """scan_layers checkpoints convert to the per-layer layout (and back)
    so cached generation is reachable from a scan-trained model."""

    def test_scan_to_layered_roundtrip_and_generate(self):
        from paddle_tpu.models.llama import (
            LlamaConfig, LlamaForCausalLM, layered_to_scan_state_dict,
            scan_to_layered_state_dict)

        paddle.seed(5)
        cfg_s = LlamaConfig.tiny(vocab=64, hidden=32, layers=3, heads=4,
                                 kv_heads=2, inter=64, max_pos=32)
        cfg_s.scan_layers = True
        m_scan = LlamaForCausalLM(cfg_s)
        ids = paddle.to_tensor(np.random.default_rng(0).integers(
            0, 64, (2, 8), dtype=np.int32))
        logits_scan = m_scan(ids).numpy()

        m_layer = LlamaForCausalLM(LlamaConfig.tiny(
            vocab=64, hidden=32, layers=3, heads=4, kv_heads=2, inter=64,
            max_pos=32))
        converted = scan_to_layered_state_dict(m_scan.state_dict())
        missing, unexpected = m_layer.set_state_dict(converted)
        assert not missing and not unexpected
        np.testing.assert_allclose(m_layer(ids).numpy(), logits_scan,
                                   rtol=2e-4, atol=2e-5)
        out = m_layer.generate(ids, max_new_tokens=3)
        assert out.shape == [2, 11]

        back = layered_to_scan_state_dict(m_layer.state_dict(), 3)
        for k, v in m_scan.state_dict().items():
            got = back[k]._data if hasattr(back[k], "_data") else back[k]
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(v._data), rtol=1e-6)
