"""Sparse COO/CSR tensors and ops — numerics vs dense NumPy references
(SURVEY.md §4 op-test pattern), plus autograd through sparse values."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _rand_coo(shape=(4, 5), nnz=6, seed=0, dense_dims=0):
    rng = np.random.default_rng(seed)
    sp_shape = shape[:len(shape) - dense_dims]
    lin = rng.choice(int(np.prod(sp_shape)), size=nnz, replace=False)
    idx = np.stack(np.unravel_index(lin, sp_shape))
    vals = rng.normal(size=(nnz,) + shape[len(sp_shape):]).astype(np.float32)
    return idx, vals


def test_coo_construct_and_to_dense():
    idx, vals = _rand_coo()
    x = sparse.sparse_coo_tensor(idx, vals, (4, 5))
    assert x.is_sparse() and x.is_sparse_coo() and not x.is_sparse_csr()
    assert x.nnz() == 6 and x.shape == [4, 5]
    dense = np.zeros((4, 5), np.float32)
    dense[tuple(idx)] = vals
    np.testing.assert_allclose(x.to_dense().numpy(), dense)
    # infer shape when omitted
    y = sparse.sparse_coo_tensor(idx, vals)
    assert y.shape[0] >= idx[0].max() + 1


def test_coalesce_merges_duplicates():
    idx = np.array([[0, 0, 1], [2, 2, 3]])
    vals = np.array([1.0, 2.0, 5.0], np.float32)
    x = sparse.sparse_coo_tensor(idx, vals, (2, 4))
    c = sparse.coalesce(x)
    assert c.nnz() == 2
    dense = np.zeros((2, 4), np.float32)
    np.add.at(dense, tuple(idx), vals)
    np.testing.assert_allclose(c.to_dense().numpy(), dense)


def test_csr_roundtrip():
    idx, vals = _rand_coo((4, 5), 7, seed=1)
    coo = sparse.sparse_coo_tensor(idx, vals, (4, 5))
    csr = coo.to_sparse_csr()
    assert csr.is_sparse_csr() and csr.nnz() == 7
    np.testing.assert_allclose(csr.to_dense().numpy(), coo.to_dense().numpy())
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(back.to_dense().numpy(), coo.to_dense().numpy())
    # direct csr construction
    csr2 = sparse.sparse_csr_tensor(csr.crows(), csr.cols(), csr.values(),
                                    (4, 5))
    np.testing.assert_allclose(csr2.to_dense().numpy(), coo.to_dense().numpy())


def test_arithmetic():
    idx, vals = _rand_coo((4, 5), 6, seed=2)
    a = sparse.sparse_coo_tensor(idx, vals, (4, 5))
    b = sparse.sparse_coo_tensor(idx, vals * 2, (4, 5))
    da, db = a.to_dense().numpy(), b.to_dense().numpy()
    np.testing.assert_allclose(sparse.add(a, b).to_dense().numpy(), da + db,
                               rtol=1e-6)
    np.testing.assert_allclose(sparse.subtract(a, b).to_dense().numpy(),
                               da - db, rtol=1e-6)
    np.testing.assert_allclose(sparse.multiply(a, b).to_dense().numpy(),
                               da * db, rtol=1e-6)
    np.testing.assert_allclose((a * 3.0).to_dense().numpy(), da * 3, rtol=1e-6)
    # different patterns: add works via union, multiply raises
    idx2, vals2 = _rand_coo((4, 5), 5, seed=3)
    c = sparse.sparse_coo_tensor(idx2, vals2, (4, 5))
    np.testing.assert_allclose(sparse.add(a, c).to_dense().numpy(),
                               da + c.to_dense().numpy(), rtol=1e-6)
    with pytest.raises(ValueError):
        sparse.multiply(a, c)
    # sparse * dense
    d = paddle.to_tensor(np.arange(20).reshape(4, 5).astype(np.float32))
    np.testing.assert_allclose(sparse.multiply(a, d).to_dense().numpy(),
                               da * d.numpy(), rtol=1e-6)
    # sparse + dense would densify silently — must raise
    with pytest.raises(TypeError):
        sparse.add(a, d)
    with pytest.raises(TypeError):
        sparse.subtract(a, d)


def test_matmul_and_masked_matmul():
    rng = np.random.default_rng(0)
    idx, vals = _rand_coo((4, 6), 8, seed=4)
    a = sparse.sparse_coo_tensor(idx, vals, (4, 6))
    dense = paddle.to_tensor(rng.normal(size=(6, 3)).astype(np.float32))
    out = sparse.matmul(a, dense)
    np.testing.assert_allclose(out.numpy(),
                               a.to_dense().numpy() @ dense.numpy(),
                               rtol=1e-5, atol=1e-5)
    # csr operand
    out2 = sparse.matmul(a.to_sparse_csr(), dense)
    np.testing.assert_allclose(out2.numpy(), out.numpy(), rtol=1e-6)
    # SDDMM: (x @ y) sampled at mask
    x = paddle.to_tensor(rng.normal(size=(4, 5)).astype(np.float32))
    y = paddle.to_tensor(rng.normal(size=(5, 6)).astype(np.float32))
    mask = sparse.sparse_coo_tensor(idx, np.ones(8, np.float32), (4, 6))
    got = sparse.masked_matmul(x, y, mask)
    want = (x.numpy() @ y.numpy()) * (mask.to_dense().numpy() != 0)
    np.testing.assert_allclose(got.to_dense().numpy(), want, rtol=1e-5,
                               atol=1e-5)


def test_unary_ops_and_cast():
    idx, vals = _rand_coo((4, 5), 6, seed=5)
    x = sparse.sparse_coo_tensor(idx, vals, (4, 5))
    np.testing.assert_allclose(sparse.relu(x).values().numpy(),
                               np.maximum(vals, 0), rtol=1e-6)
    np.testing.assert_allclose(sparse.tanh(x).values().numpy(),
                               np.tanh(vals), rtol=1e-6)
    np.testing.assert_allclose(sparse.pow(x, 2).values().numpy(), vals ** 2,
                               rtol=1e-6)
    assert str(sparse.cast(x, value_dtype="float16").dtype).endswith("float16")
    t = sparse.transpose(x, [1, 0])
    np.testing.assert_allclose(t.to_dense().numpy(),
                               x.to_dense().numpy().T, rtol=1e-6)
    r = sparse.reshape(x, [2, 10])
    np.testing.assert_allclose(r.to_dense().numpy(),
                               x.to_dense().numpy().reshape(2, 10), rtol=1e-6)
    s = sparse.sum(x)
    np.testing.assert_allclose(float(s), vals.sum(), rtol=1e-5)


def test_autograd_through_sparse():
    idx, vals = _rand_coo((4, 6), 8, seed=6)
    a = sparse.sparse_coo_tensor(idx, vals, (4, 6), stop_gradient=False)
    dense = paddle.to_tensor(np.ones((6, 2), np.float32))
    out = sparse.matmul(a, dense)
    out.sum().backward()
    g = a.grad
    assert g is not None
    # d(sum(A@1))/dA_ij = sum_k 1 = 2 for every stored element
    np.testing.assert_allclose(g.numpy(), np.full(8, 2.0), rtol=1e-6)


def test_sparse_softmax():
    idx, vals = _rand_coo((4, 5), 9, seed=7)
    x = sparse.sparse_coo_tensor(idx, vals, (4, 5))
    sm = sparse.nn.Softmax()
    y = sm(x)
    d = y.to_dense().numpy()
    rows_with = np.unique(idx[0])
    for r in rows_with:
        np.testing.assert_allclose(d[r][d[r] != 0].sum(), 1.0, rtol=1e-5)


def test_sparse_conv3d_and_subm():
    rng = np.random.default_rng(0)
    N, D, H, W, C = 1, 4, 4, 4, 2
    idx, _ = _rand_coo((N, D, H, W), 5, seed=8)
    vals = rng.normal(size=(5, C)).astype(np.float32)
    x = sparse.sparse_coo_tensor(idx, vals, (N, D, H, W, C))

    conv = sparse.nn.Conv3D(C, 3, kernel_size=3, padding=1)
    conv.bias.set_value(np.full(3, 0.25, np.float32))
    y = conv(x)
    assert y.shape == [N, D, H, W, 3]
    # dense reference: bias lands only at retained (conv-active) sites —
    # a nonzero bias must NOT densify the output
    dense_in = x.to_dense().numpy()
    import jax
    import jax.numpy as jnp
    ref = np.array(jax.lax.conv_general_dilated(
        jnp.asarray(dense_in), conv.weight._data, (1, 1, 1),
        [(1, 1)] * 3, dimension_numbers=("NDHWC", "DHWIO", "NDHWC")))
    active = np.any(ref != 0, axis=-1)
    ref[active] += 0.25
    assert y.nnz() == int(active.sum()) < N * D * H * W
    np.testing.assert_allclose(y.to_dense().numpy(), ref, rtol=1e-4, atol=1e-4)
    # submanifold without size-preserving padding is rejected
    with pytest.raises(ValueError):
        sparse.nn.SubmConv3D(C, 3, kernel_size=3, padding=0)

    subm = sparse.nn.SubmConv3D(C, 3, kernel_size=3, padding=1)
    ys = subm(x)
    assert ys.nnz() == x.nnz()  # submanifold preserves active sites
    out_d = ys.to_dense().numpy()
    inactive = np.ones((N, D, H, W), bool)
    inactive[tuple(idx)] = False
    assert np.all(out_d[inactive] == 0)


def test_sparse_batchnorm():
    idx, _ = _rand_coo((2, 3, 3, 3), 10, seed=9)
    vals = np.random.default_rng(1).normal(size=(10, 4)).astype(np.float32)
    x = sparse.sparse_coo_tensor(idx, vals, (2, 3, 3, 3, 4))
    bn = sparse.nn.BatchNorm(4)
    y = bn(x)
    got = y.values().numpy()
    assert got.shape == (10, 4)
    np.testing.assert_allclose(got.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(got.std(axis=0), 1.0, atol=1e-2)
    bn.eval()
    y2 = bn(x)
    assert y2.values().numpy().shape == (10, 4)


# ---------------------------------------------------------------------------
# round-3 surface wave: mv/addmm/slice/unary tail, hybrid to_sparse_coo,
# 2-D sparse convs, LeakyReLU/ReLU6
# ---------------------------------------------------------------------------

def test_mv_and_addmm():
    dense = np.array([[0, 2, 0], [3, 0, 4.0]], np.float32)
    idx = np.stack(np.nonzero(dense))
    coo = sparse.sparse_coo_tensor(idx, dense[tuple(idx)], dense.shape)
    v = paddle.to_tensor(np.array([1.0, 2, 3], np.float32))
    np.testing.assert_allclose(sparse.mv(coo, v).numpy(), dense @ [1, 2, 3])
    y = paddle.to_tensor(np.ones((3, 2), np.float32))
    inp = paddle.to_tensor(np.ones((2, 2), np.float32))
    np.testing.assert_allclose(
        sparse.addmm(inp, coo, y, beta=0.5, alpha=2.0).numpy(),
        0.5 + 2 * (dense @ np.ones((3, 2), np.float32)), rtol=1e-6)


def test_slice_and_unary_tail():
    dense = np.array([[0, 2, 0, 1], [3, 0, 4.0, 0]], np.float32)
    idx = np.stack(np.nonzero(dense))
    coo = sparse.sparse_coo_tensor(idx, dense[tuple(idx)], dense.shape)
    sl = sparse.slice(coo, [1], [1], [3])
    np.testing.assert_allclose(sl.to_dense().numpy(), dense[:, 1:3])
    sl2 = sparse.slice(coo, [0, 1], [1, 0], [2, 4])
    np.testing.assert_allclose(sl2.to_dense().numpy(), dense[1:2, :])
    assert not bool(np.any(sparse.isnan(coo).values().numpy()))
    np.testing.assert_allclose(sparse.rad2deg(coo).values().numpy(),
                               np.rad2deg(dense[tuple(idx)]), rtol=1e-6)


def test_to_sparse_coo_hybrid_dims():
    dense = np.zeros((1, 4, 4, 3), np.float32)
    dense[0, 1, 2] = [1, 2, 3]
    x = paddle.to_tensor(dense).to_sparse_coo(3)
    assert x.sparse_dim == 3 and x.dense_dim == 1
    np.testing.assert_allclose(x.to_dense().numpy(), dense)
    full = paddle.to_tensor(dense).to_sparse_coo(4)
    assert full.sparse_dim == 4 and full.dense_dim == 0
    np.testing.assert_allclose(full.to_dense().numpy(), dense)


def test_sparse_conv2d_and_subm():
    paddle.seed(0)
    dense = np.zeros((1, 8, 8, 3), np.float32)
    dense[0, 2, 3] = [1, 2, 3]
    dense[0, 5, 5] = [4, 5, 6]
    x = paddle.to_tensor(dense).to_sparse_coo(3)

    subm = sparse.nn.SubmConv2D(3, 4, kernel_size=3, padding=1)
    out = subm(x)
    assert out.shape == [1, 8, 8, 4]
    np.testing.assert_array_equal(np.asarray(out.indices().numpy()),
                                  np.asarray(x.indices().numpy()))

    conv = sparse.nn.Conv2D(3, 4, kernel_size=3, stride=2, padding=1)
    out2 = conv(x)
    assert out2.shape == [1, 4, 4, 4]
    # dense reference at the retained sites
    import jax
    import jax.numpy as jnp
    ref = jax.lax.conv_general_dilated(
        jnp.asarray(dense), conv.weight._data, (2, 2), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    got = np.asarray(out2.to_dense().numpy())
    want = np.asarray(ref)
    sites = np.any(got != 0, axis=-1)
    np.testing.assert_allclose(
        got[sites], (want + np.asarray(conv.bias._data))[sites], rtol=1e-4)


def test_sparse_activations():
    dense = np.array([[-2.0, 0, 8.0]], np.float32)
    idx = np.stack(np.nonzero(dense))
    coo = sparse.sparse_coo_tensor(idx, dense[tuple(idx)], dense.shape)
    lr = sparse.nn.LeakyReLU(0.1)(coo)
    np.testing.assert_allclose(lr.values().numpy(), [-0.2, 8.0], rtol=1e-6)
    r6 = sparse.nn.ReLU6()(coo)
    np.testing.assert_allclose(r6.values().numpy(), [0.0, 6.0])


class TestSparseFunctional:
    """paddle.sparse.nn.functional (round-4): conv/pool/attention
    functionals vs dense references."""

    def _voxels(self, rng, shape=(1, 6, 6, 6, 4), n=30):
        pts = np.unique(
            rng.integers(0, shape[1], (n, 3)), axis=0)
        idx = np.concatenate(
            [np.zeros((pts.shape[0], 1), np.int64), pts], axis=1).T
        vals = rng.normal(0, 1, (idx.shape[1], shape[-1])).astype(np.float32)
        return paddle.sparse.sparse_coo_tensor(idx, vals, shape), idx, vals

    def test_functional_activations(self):
        rng = np.random.default_rng(0)
        x, idx, vals = self._voxels(rng)
        F = paddle.sparse.nn.functional
        np.testing.assert_allclose(
            F.relu(x).values().numpy(), np.maximum(vals, 0))
        np.testing.assert_allclose(
            F.relu6(x).values().numpy(), np.clip(vals, 0, 6))
        np.testing.assert_allclose(
            F.leaky_relu(x, 0.1).values().numpy(),
            np.where(vals >= 0, vals, 0.1 * vals), rtol=1e-6)

    def test_functional_subm_conv3d_matches_layer(self):
        rng = np.random.default_rng(1)
        x, idx, vals = self._voxels(rng)
        F = paddle.sparse.nn.functional
        paddle.seed(3)
        layer = paddle.sparse.nn.SubmConv3D(4, 8, kernel_size=3, padding=1)
        want = layer(x)
        got = F.subm_conv3d(x, layer.weight, layer.bias, padding=1)
        np.testing.assert_allclose(got.values().numpy(),
                                   want.values().numpy(), rtol=1e-5,
                                   atol=1e-6)
        assert got.shape == want.shape

    def test_functional_max_pool3d(self):
        rng = np.random.default_rng(2)
        x, idx, vals = self._voxels(rng)
        F = paddle.sparse.nn.functional
        out = F.max_pool3d(x, kernel_size=2, stride=2)
        # dense reference over active sites (-inf background)
        dense = np.full((1, 6, 6, 6, 4), -np.inf, np.float32)
        dense[tuple(idx)] = vals
        ref = dense.reshape(1, 3, 2, 3, 2, 3, 2, 4).max((2, 4, 6))
        got = out.to_dense().numpy()
        active = np.isfinite(ref).any(-1)
        ref_vals = np.where(np.isfinite(ref), ref, 0.0)
        np.testing.assert_allclose(got[active], ref_vals[active], rtol=1e-6)
        assert np.allclose(got[~active], 0.0)
        # the layer form agrees
        got2 = paddle.sparse.nn.MaxPool3D(2, 2)(x).to_dense().numpy()
        np.testing.assert_allclose(got2, got)

    def test_csr_masked_attention_matches_dense(self):
        rng = np.random.default_rng(3)
        B, H, L, D = 2, 2, 8, 4
        q = rng.normal(0, 1, (B, H, L, D)).astype(np.float32)
        k = rng.normal(0, 1, (B, H, L, D)).astype(np.float32)
        v = rng.normal(0, 1, (B, H, L, D)).astype(np.float32)
        # banded causal-ish layout as the CSR pattern
        mask = np.tril(np.ones((L, L), bool)) & \
            ~np.tril(np.ones((L, L), bool), -4)
        crows = np.concatenate([[0], np.cumsum(mask.sum(1))]).astype(np.int32)
        cols = np.concatenate([np.nonzero(mask[i])[0] for i in range(L)]) \
            .astype(np.int32)
        sm = paddle.sparse.sparse_csr_tensor(
            crows, cols, np.ones(cols.shape[0], np.float32), (L, L))
        kp = np.zeros((B, L), np.float32)
        kp[:, -2:] = -1e30  # pad out the last two keys
        out = paddle.sparse.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            sm, key_padding_mask=paddle.to_tensor(kp)).numpy()
        # dense reference
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = np.where(mask, s, -np.inf) + kp[:, None, None, :]
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-5)
