"""Optimizers, grad clip, LR schedulers, weight decay semantics."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _quad_problem():
    w = paddle.to_tensor(np.array([3.0, -2.0], np.float32), stop_gradient=False)
    w.name = "w"

    def loss_fn():
        return ((w - paddle.to_tensor([1.0, 1.0])) ** 2).sum()

    return w, loss_fn


@pytest.mark.parametrize("opt_name,kwargs", [
    ("SGD", {"learning_rate": 0.1}),
    ("Momentum", {"learning_rate": 0.05, "momentum": 0.9}),
    ("Adam", {"learning_rate": 0.1}),
    ("AdamW", {"learning_rate": 0.1, "weight_decay": 0.01}),
    ("Adagrad", {"learning_rate": 0.5}),
    ("RMSProp", {"learning_rate": 0.05}),
    ("Adamax", {"learning_rate": 0.1}),
    ("Adadelta", {"learning_rate": 50.0}),  # adadelta's effective step starts ~lr*sqrt(eps): slow by design
    ("Lamb", {"learning_rate": 0.1}),
])
def test_optimizer_converges(opt_name, kwargs):
    w, loss_fn = _quad_problem()
    opt = getattr(paddle.optimizer, opt_name)(parameters=[w], **kwargs)
    first = float(loss_fn())
    for _ in range(60):
        loss = loss_fn()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss_fn()) < first * 0.1, f"{opt_name} failed to converge"


def test_sgd_exact_update():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "w0"
    opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
    (w * 3).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.5 * 3.0])


def test_adamw_decoupled_decay():
    # with zero grad, AdamW still shrinks weights; Adam(weight_decay) couples
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "w1"
    opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5,
                                 parameters=[w])
    w.grad = paddle.zeros([1])
    opt.step()
    assert float(w) < 1.0  # decayed despite zero grad


def test_global_norm_clip():
    from paddle_tpu.optimizer import ClipGradByGlobalNorm
    w = paddle.to_tensor([10.0, 0.0], stop_gradient=False)
    w.name = "w2"
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w],
                               grad_clip=ClipGradByGlobalNorm(1.0))
    (w * paddle.to_tensor([3.0, 4.0])).sum().backward()
    opt.step()
    # grad (3,4) norm 5 -> clipped to (0.6, 0.8)
    np.testing.assert_allclose(w.numpy(), [10.0 - 0.6, -0.8], rtol=1e-5)


def test_lr_scheduler_basic():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "w3"
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
    lrs = []
    for _ in range(6):
        lrs.append(opt.get_lr())
        sched.step()
    assert lrs == [1.0, 1.0, 0.5, 0.5, 0.25, 0.25]


def test_cosine_and_warmup():
    import math
    c = paddle.optimizer.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    assert abs(c() - 1.0) < 1e-6
    for _ in range(10):
        c.step()
    assert c() < 1e-6
    w = paddle.optimizer.lr.LinearWarmup(learning_rate=0.1, warmup_steps=5,
                                         start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(6):
        vals.append(round(w(), 4))
        w.step()
    assert vals[0] == 0.0 and abs(vals[-1] - 0.1) < 1e-6


def test_optimizer_state_dict_roundtrip():
    w, loss_fn = _quad_problem()
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
    for _ in range(3):
        loss_fn().backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()
    assert sd["step"] == 3
    w2, loss_fn2 = _quad_problem()
    w2.name = "w"
    opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
    loss_fn2().backward()
    opt2.step()  # create accumulators
    opt2.clear_grad()
    opt2.set_state_dict(sd)
    assert opt2._step_count == 3
    m1 = list(opt._accumulators["moment1"].values())[0].numpy()
    m2 = list(opt2._accumulators["moment1"].values())[0].numpy()
    np.testing.assert_allclose(m1, m2)
