"""ISSUE 20 kill-storm soak: seeded fault storms + a real SIGKILL.

Slow tier (``-m slow``): the acceptance proof for the fleet tier's
at-most-once contract under composed chaos —

* **Part A (deterministic storm):** a scripted ``fleet.rpc``
  ``FaultSchedule`` drives transport faults into a fixed sequence of
  sequential submits. The SAME storm replayed against the SAME fleet
  (fresh schedule, counters restart) yields an identical rid-normalized
  outcome map and an identical fault trace — the determinism witness.
  Replica NAMES are normalized away: the pick RNG and heartbeat-cached
  scores may place work differently between runs, but outcomes (which
  requests complete, with which tokens, which fail with which type)
  may not differ.
* **Part B (real SIGKILL):** the fleet is loaded past one worker's
  batch capacity, then a live worker is SIGKILLed mid-flight. Every
  submitted request resolves with exactly one typed outcome: completed
  requests are bit-identical to the dense reference (zero-token victims
  of the dead worker failed over — never-admitted proof), mid-stream
  victims raise ``RpcTransportError`` (admitted: a silent re-send is
  forbidden). Afterwards no survivor leaks pages
  (``outstanding_pages == 0`` over the heartbeat), and the respawned
  worker rejoins rotation and serves.

Process budget: one module-scoped 2-worker fleet + exactly one respawn —
3 worker boots total (the 1-core CI host pays a fresh jax import + toy
compile per boot).
"""

import os
import signal
import time

import pytest

import paddle_tpu  # noqa: F401  (backend pin via conftest)
from paddle_tpu.distributed.rpc import RpcTransportError
from paddle_tpu.resilience import faults
from paddle_tpu.serving.router import RouterConfig

from test_fleet import (N_NEW, PROMPTS, _make_fleet, _submit,
                        _wait_rotation, dense_reference)

pytestmark = pytest.mark.slow

_REFS = None


def _refs():
    global _REFS
    if _REFS is None:
        _REFS = [dense_reference(p, N_NEW) for p in PROMPTS]
    return _REFS


@pytest.fixture(scope="module")
def chaos_fleet():
    sup = _make_fleet(
        ["c0", "c1"],
        # high threshold: the storm's scripted faults must exercise the
        # failover path, not collapse into breaker fast-fails whose
        # placement depends on which replica absorbed the faults
        router_config=RouterConfig(breaker_threshold=10, seed=0),
        max_respawns=3)
    sup.start()
    yield sup
    faults.uninstall()
    sup.stop(drain=True, timeout=60)


# the scripted storm: 10 sequential submits; fleet.rpc call indices run
# 1,2,... with one call per placement attempt. on=[2,5,6,9] makes
# submit #2 fault once and fail over, submit #4 fault on BOTH replicas
# (typed ConnectionError rejection), submit #7 fault once and fail over.
_STORM_ON = [2, 5, 6, 9]
_STORM_SUBMITS = 10
_EXPECTED_FAULT_TRACE = [("fleet.rpc", i, "error") for i in _STORM_ON]


def _run_storm(sup):
    """One storm pass: fresh scripted schedule, sequential submits,
    rid-normalized outcomes (submission index -> typed outcome)."""
    sched = faults.FaultSchedule(seed=0).error("fleet.rpc", on=_STORM_ON)
    faults.install(sched)
    outcomes = {}
    try:
        for i in range(_STORM_SUBMITS):
            prompt = PROMPTS[i % len(PROMPTS)]
            try:
                fut, toks = _submit(sup, prompt)
                res = fut.result(timeout=120)
                outcomes[i] = ("ok", tuple(res.tokens))
            except Exception as exc:
                outcomes[i] = ("err", type(exc).__name__)
    finally:
        faults.uninstall()
    return outcomes, list(sched.trace)


class TestSeededStorm:
    def test_storm_is_deterministic_and_typed(self, chaos_fleet):
        refs = _refs()
        out1, trace1 = _run_storm(chaos_fleet)
        out2, trace2 = _run_storm(chaos_fleet)

        # the determinism witness: same storm, same normalized outcomes
        assert out1 == out2
        assert trace1 == trace2 == _EXPECTED_FAULT_TRACE

        # every outcome is the TYPED one the script predicts: submit #3
        # (0-based) burns both replicas -> typed transport rejection;
        # everything else completes bit-identical to the dense oracle
        for i, outcome in out1.items():
            if i == 3:
                assert outcome == ("err", "FaultInjected"), outcome
            else:
                assert outcome == ("ok", tuple(refs[i % len(PROMPTS)])), i

    def test_storm_left_no_pages_behind(self, chaos_fleet):
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            stats = [chaos_fleet.worker_stats(n) for n in ("c0", "c1")]
            if all(s.get("outstanding_pages") == 0 and
                   s.get("active_requests") == 0 for s in stats):
                return
            time.sleep(0.2)
        raise AssertionError(f"pages leaked after the storm: {stats}")


class TestRealSigkill:
    def test_sigkill_under_load_every_future_typed(self, chaos_fleet):
        """Load past one worker's batch capacity, SIGKILL it live, and
        hold the acceptance invariants over EVERY submitted request."""
        refs = _refs()
        n_req = 6
        streams = {i: [] for i in range(n_req)}
        futs = {}
        for i in range(n_req):
            fut, toks = _submit(chaos_fleet, PROMPTS[i % len(PROMPTS)])
            futs[i] = fut
            streams[i] = toks
        victim = "c0"
        os.kill(chaos_fleet.worker_pids()[victim], signal.SIGKILL)

        outcomes = {}
        for i, fut in futs.items():
            try:
                # exactly one typed outcome per request — a timeout here
                # is a stranded future, the cardinal failure
                res = fut.result(timeout=180)
                outcomes[i] = ("ok", tuple(res.tokens))
            except RpcTransportError:
                outcomes[i] = ("err", "RpcTransportError")
            except Exception as exc:
                outcomes[i] = ("err", type(exc).__name__)

        for i, outcome in outcomes.items():
            ref = tuple(refs[i % len(PROMPTS)])
            if outcome[0] == "ok":
                # completed work — including zero-token victims failed
                # over off the corpse — is bit-identical to the oracle
                assert outcome[1] == ref, (i, outcome)
            else:
                # the only allowed typed failure is the admitted-victim
                # classification: tokens already streamed, so a silent
                # re-send is forbidden (at-most-once)
                assert outcome == ("err", "RpcTransportError"), (i, outcome)
                assert len(streams[i]) > 0, \
                    f"request {i}: zero-token death must fail over, " \
                    f"not surface transport error"

        # the supervisor noticed, classified, and respawned
        _wait_rotation(chaos_fleet, ["c0", "c1"], timeout=120)

        # no survivor leaks pages once the dust settles
        deadline = time.monotonic() + 60.0
        stats = {}
        while time.monotonic() < deadline:
            stats = {n: chaos_fleet.worker_stats(n) for n in ("c0", "c1")}
            if all(s.get("outstanding_pages") == 0 and
                   s.get("active_requests") == 0 for s in stats.values()):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"pages leaked after the kill: {stats}")

        # ... and the fresh incarnation serves, bit-identically
        fut, toks = _submit(chaos_fleet, PROMPTS[0])
        assert list(fut.result(timeout=120).tokens) == refs[0]
        assert toks == refs[0]
