"""Tests for the second distribution wave (scipy as the numeric reference),
wave-4 datasets, anomaly detection, and the tensor-protocol tail."""

import warnings

import numpy as np
import pytest
from scipy import stats

import paddle_tpu as paddle
import paddle_tpu.vision as vision
from paddle_tpu import distribution as D


class TestDistributionsWave2:
    def test_gamma_matches_scipy(self):
        v = np.array([0.5, 1.5, 3.0], "float32")
        g = D.Gamma(2.0, 0.5)
        np.testing.assert_allclose(
            np.asarray(g.log_prob(paddle.to_tensor(v)).numpy()),
            stats.gamma.logpdf(v, 2.0, scale=2.0), rtol=1e-5)
        np.testing.assert_allclose(float(g.entropy().numpy()),
                                   stats.gamma.entropy(2.0, scale=2.0),
                                   rtol=1e-5)
        assert abs(float(g.mean.numpy()) - 4.0) < 1e-6
        s = g.rsample((2000,))
        assert abs(float(s.numpy().mean()) - 4.0) < 0.5

    def test_poisson_matches_scipy(self):
        p = D.Poisson(3.0)
        np.testing.assert_allclose(
            np.asarray(p.log_prob(paddle.to_tensor(
                np.array([0.0, 2.0, 5.0], "float32"))).numpy()),
            stats.poisson.logpmf([0, 2, 5], 3.0), rtol=1e-5)
        s = p.sample((2000,))
        assert abs(float(s.numpy().mean()) - 3.0) < 0.3

    def test_binomial_matches_scipy(self):
        b = D.Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.3))
        np.testing.assert_allclose(
            float(b.log_prob(paddle.to_tensor(4.0)).numpy()),
            stats.binom.logpmf(4, 10, 0.3), rtol=1e-5)
        assert abs(float(b.mean.numpy()) - 3.0) < 1e-6

    def test_cauchy_student_match_scipy(self):
        v = np.array([0.5, 1.5, 3.0], "float32")
        c = D.Cauchy(1.0, 2.0)
        np.testing.assert_allclose(
            np.asarray(c.log_prob(paddle.to_tensor(v)).numpy()),
            stats.cauchy.logpdf(v, 1.0, 2.0), rtol=1e-5)
        t = D.StudentT(5.0, 1.0, 2.0)
        np.testing.assert_allclose(
            np.asarray(t.log_prob(paddle.to_tensor(v)).numpy()),
            stats.t.logpdf(v, 5.0, 1.0, 2.0), rtol=1e-5)

    def test_mvn_matches_scipy(self):
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], "float32")
        mvn = D.MultivariateNormal(
            paddle.to_tensor(np.array([1.0, -1.0], "float32")),
            covariance_matrix=paddle.to_tensor(cov))
        x = np.array([0.3, 0.7], "float32")
        np.testing.assert_allclose(
            float(mvn.log_prob(paddle.to_tensor(x)).numpy()),
            stats.multivariate_normal.logpdf(x, [1.0, -1.0], cov), rtol=1e-4)
        np.testing.assert_allclose(
            float(mvn.entropy().numpy()),
            stats.multivariate_normal([1.0, -1.0], cov).entropy(), rtol=1e-4)
        s = np.asarray(mvn.rsample((4000,)).numpy())
        np.testing.assert_allclose(s.mean(0), [1.0, -1.0], atol=0.15)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.2)

    def test_independent_sums_event_dims(self):
        base = D.Normal(np.zeros(3, "float32"), np.ones(3, "float32"))
        ind = D.Independent(base, 1)
        v = np.array([0.5, 1.5, 3.0], "float32")
        np.testing.assert_allclose(
            float(ind.log_prob(paddle.to_tensor(v)).numpy()),
            stats.norm.logpdf(v).sum(), rtol=1e-5)
        assert ind.event_shape == (3,)

    def test_gamma_kl(self):
        kl = D.kl_divergence(D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0))
        assert float(kl.numpy()) > 0
        self_kl = D.kl_divergence(D.Gamma(2.0, 1.0), D.Gamma(2.0, 1.0))
        assert abs(float(self_kl.numpy())) < 1e-6

    def test_rsample_grads_flow(self):
        conc = paddle.to_tensor(np.array([2.0], "float32"),
                                stop_gradient=False)
        g = D.Gamma(conc, paddle.to_tensor(np.array([1.0], "float32")))
        g.rsample((8,)).sum().backward()
        assert conc.grad is not None


class TestWave4Datasets:
    def test_flowers_voc(self):
        f = vision.datasets.Flowers(mode="train")
        img, lab = f[0]
        assert img.shape == (3, 224, 224) and 0 <= int(lab) < 102
        voc = vision.datasets.VOC2012()
        img, mask = voc[0]
        assert mask.shape == (224, 224) and mask.max() >= 1

    def test_image_folder(self, tmp_path):
        for i in range(3):
            np.save(tmp_path / f"img{i}.npy",
                    np.random.rand(3, 4, 4).astype("float32"))
        ds = vision.datasets.ImageFolder(str(tmp_path))
        assert len(ds) == 3
        (img,) = ds[0]
        assert img.shape == (3, 4, 4)

    def test_concat_dataset(self):
        d1 = vision.datasets.MNIST(mode="test")
        cd = paddle.io.ConcatDataset([d1, d1])
        assert len(cd) == 2 * len(d1)
        a, _ = cd[len(d1) + 5]
        b, _ = d1[5]
        np.testing.assert_allclose(a, b)
        with pytest.raises(ValueError):
            paddle.io.ConcatDataset([])


class TestAnomalyAndHooks:
    def test_detect_anomaly_flags_nonfinite(self):
        paddle.autograd.set_detect_anomaly(True)
        try:
            x = paddle.to_tensor(np.array([0.0], "float32"),
                                 stop_gradient=False)
            with pytest.raises(RuntimeError, match="anomaly"):
                paddle.log(x).backward()
        finally:
            paddle.autograd.set_detect_anomaly(False)
        x2 = paddle.to_tensor(np.array([2.0], "float32"),
                              stop_gradient=False)
        paddle.log(x2).backward()
        np.testing.assert_allclose(np.asarray(x2.grad.numpy()), [0.5])

    def test_saved_tensors_hooks_warns(self):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with paddle.autograd.saved_tensors_hooks(lambda t: t,
                                                     lambda t: t):
                pass
        assert any("recompute" in str(x.message) for x in w)

    def test_tensor_checker_config(self):
        cfg = paddle.amp.debugging.TensorCheckerConfig(enable=True)
        paddle.amp.debugging.enable_tensor_checker(cfg)
        paddle.amp.debugging.disable_tensor_checker()


class TestTensorProtocolTail:
    def test_dlpack_protocol(self):
        t = paddle.to_tensor(np.random.rand(2, 2).astype("float32"))
        assert t.__dlpack_device__() is not None
        np.testing.assert_allclose(np.from_dlpack(t), t.numpy())

    def test_sigmoid_(self):
        t = paddle.to_tensor(np.array([0.0], "float32"))
        t.sigmoid_()
        np.testing.assert_allclose(np.asarray(t.numpy()), [0.5])


class TestReviewFixes8:
    def test_mvn_batched_covariance(self):
        cov = np.stack([np.eye(2, dtype="float32") * (i + 1)
                        for i in range(5)])
        mvn = D.MultivariateNormal(
            paddle.to_tensor(np.zeros(2, "float32")),
            covariance_matrix=paddle.to_tensor(cov))
        assert mvn.batch_shape == (5,)
        s = mvn.rsample((3,))
        assert s.shape == [3, 5, 2]
        lp = mvn.log_prob(paddle.to_tensor(np.zeros((5, 2), "float32")))
        assert lp.shape == [5]

    def test_concat_out_of_range_raises(self):
        d1 = vision.datasets.MNIST(mode="test")
        cd = paddle.io.ConcatDataset([d1])
        with pytest.raises(IndexError):
            cd[len(d1)]
        with pytest.raises(IndexError):
            cd[-len(d1) - 1]

    def test_image_folder_full_path_predicate(self, tmp_path):
        sub = tmp_path / "keep"
        sub.mkdir()
        np.save(sub / "a.npy", np.zeros((1,), "float32"))
        np.save(tmp_path / "b.npy", np.zeros((1,), "float32"))
        import os
        ds = vision.datasets.ImageFolder(
            str(tmp_path), is_valid_file=lambda p: "keep" in p and
            os.path.exists(p))
        assert len(ds) == 1

    def test_tensor_checker_old_signature_still_works(self):
        paddle.amp.debugging.enable_tensor_checker()  # no-arg form
        paddle.amp.debugging.disable_tensor_checker()
        cfg = paddle.amp.debugging.TensorCheckerConfig(enable=True)
        paddle.amp.debugging.enable_tensor_checker(cfg)
        paddle.amp.debugging.disable_tensor_checker()

    def test_anomaly_flag_single_source(self):
        paddle.autograd.set_detect_anomaly(True)
        try:
            assert paddle.autograd.is_anomaly_enabled()
            from paddle_tpu.core import autograd as core_ad
            assert core_ad._detect_anomaly
        finally:
            paddle.autograd.set_detect_anomaly(False)
        assert not paddle.autograd.is_anomaly_enabled()
