"""graft-lint 2.0 whole-program tests.

Fixture mini-packages per rule (positive + negative), the alias-resolution
matrix (from-import, module alias, re-export), lock-order cycle detection
vs ``*_locked`` suppression, the content-hash cache (warm runs parse
nothing, edits invalidate exactly, format-version pin self-invalidates),
``--changed-only`` git narrowing, and the ``--allow-todo`` baseline gate.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import ProjectRule, RULES, run_lint  # noqa: E402
from tools.lint.engine import save_baseline  # noqa: E402
from tools.lint.wholeprogram import (  # noqa: E402
    CACHE_FORMAT_VERSION, Project, build_summary, module_name_for)
from tools.lint.wholeprogram.summary import SUMMARY_FORMAT  # noqa: E402

WHOLEPROGRAM_RULES = {"cross-trace-impurity", "cross-host-sync",
                      "lock-order", "import-layering",
                      "shared-state-race",
                      # ISSUE 18 (graft-lint 4.0)
                      "exception-contract", "resource-discipline",
                      # ISSUE 19 (graft-lint 5.0): interprocedural blocking
                      "blocking-under-lock", "unbounded-wait",
                      "hot-path-stall"}


def write_pkg(tmp_path, files):
    """Write {relpath: source} under tmp_path/; add __init__.py to every
    package directory that doesn't define one."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    for rel in list(files):
        d = (tmp_path / rel).parent
        while d != tmp_path:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("")
            d = d.parent


def lint_pkg(tmp_path, rule, files=None, config=None, **kw):
    if files:
        write_pkg(tmp_path, files)
    return run_lint(paths=["."], rules=[rule], config=config,
                    root=str(tmp_path), **kw)


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

def test_wholeprogram_rules_registered_as_project_rules():
    assert WHOLEPROGRAM_RULES <= set(RULES)
    for name in WHOLEPROGRAM_RULES:
        assert isinstance(RULES[name], ProjectRule)


def test_module_name_for():
    assert module_name_for("pkg/core/tensor.py") == "pkg.core.tensor"
    assert module_name_for("pkg/core/__init__.py") == "pkg.core"


# ---------------------------------------------------------------------------
# cross-trace-impurity: positive + negative + the alias matrix
# ---------------------------------------------------------------------------

TRACED_A = """\
    import jax
    from .util import helper

    def fwd(x):
        return helper(x)

    fwd_c = jax.jit(fwd)
    """


def test_cross_trace_impurity_from_import(tmp_path):
    res = lint_pkg(tmp_path, "cross-trace-impurity", {
        "pkg/a.py": TRACED_A,
        "pkg/util.py": """\
            import time

            def helper(x):
                return x * time.time()
            """,
    })
    assert len(res.new) == 1
    f = res.new[0]
    assert f.path == "pkg/util.py" and "time.time" in f.message
    assert "pkg.a.fwd" in f.message  # attributed to the reaching root


def test_cross_trace_impurity_module_alias(tmp_path):
    res = lint_pkg(tmp_path, "cross-trace-impurity", {
        "pkg/a.py": """\
            import jax
            from . import util as u

            def fwd(x):
                return u.helper(x)

            fwd_c = jax.jit(fwd)
            """,
        "pkg/util.py": """\
            import os

            def helper(x):
                return x if os.getenv("FAST") else x * 2
            """,
    })
    assert len(res.new) == 1 and res.new[0].path == "pkg/util.py"
    assert "os.getenv" in res.new[0].message


def test_cross_trace_impurity_reexport(tmp_path):
    # a.py pulls `helper` from the package __init__, which re-exports it
    # from util: resolution follows the __init__ binding one more hop
    res = lint_pkg(tmp_path, "cross-trace-impurity", {
        "pkg/__init__.py": """\
            from .util import helper
            """,
        "pkg/a.py": """\
            import jax
            from . import helper

            def fwd(x):
                return helper(x)

            fwd_c = jax.jit(fwd)
            """,
        "pkg/util.py": """\
            import random

            def helper(x):
                return x * random.random()
            """,
    })
    assert len(res.new) == 1 and res.new[0].path == "pkg/util.py"
    assert "random.random" in res.new[0].message


def test_cross_trace_impurity_mutable_global_of_other_module(tmp_path):
    # the READ lives in the root's own module but the global lives
    # elsewhere — invisible to any per-file scan
    res = lint_pkg(tmp_path, "cross-trace-impurity", {
        "pkg/a.py": """\
            import jax
            from . import cfg

            def fwd(x):
                return x * cfg.SCALES["a"]

            fwd_c = jax.jit(fwd)
            """,
        "pkg/cfg.py": """\
            SCALES = {"a": 2.0}
            """,
    })
    assert len(res.new) == 1 and res.new[0].path == "pkg/a.py"
    assert "pkg.cfg.SCALES" in res.new[0].message


def test_cross_trace_impurity_defers_to_intra_rule_on_shared_reach(tmp_path):
    # helper in b is reachable from b's OWN root (per-file rule's domain)
    # and from a root in a (which sorts first, so the BFS labels it with
    # the cross root): the per-file rule owns it — no cross finding, no
    # double reporting
    files = {
        "pkg/a.py": """\
            import jax
            from .b import helper

            def fwda(x):
                return helper(x)

            fwda_c = jax.jit(fwda)
            """,
        "pkg/b.py": """\
            import jax
            import time

            def helper(x):
                return x * time.time()

            def fwdb(x):
                return helper(x)

            fwdb_c = jax.jit(fwdb)
            """,
    }
    assert lint_pkg(tmp_path, "cross-trace-impurity", files).new == []
    # and the per-file rule does flag it there
    res = run_lint(paths=["."], rules=["trace-impurity"],
                   root=str(tmp_path))
    assert len(res.new) == 1 and res.new[0].path == "pkg/b.py"


def test_cross_trace_impurity_negative(tmp_path):
    # pure helper + impure-but-unreachable helper: clean; and a
    # same-module impure read is the per-file rule's business, not ours
    res = lint_pkg(tmp_path, "cross-trace-impurity", {
        "pkg/a.py": """\
            import jax
            import time
            from .util import helper

            def fwd(x):
                return helper(x)

            def untraced():
                return time.time()

            fwd_c = jax.jit(fwd)
            """,
        "pkg/util.py": """\
            import time

            def helper(x):
                return x + 1

            def impure_but_unreached():
                return time.time()
            """,
    })
    assert res.new == []


# ---------------------------------------------------------------------------
# cross-host-sync
# ---------------------------------------------------------------------------

FAST_CFG = {"fast_path_roots": ["pkg/fast.py::dispatch"]}


def test_cross_host_sync_positive_through_chain(tmp_path):
    res = lint_pkg(tmp_path, "cross-host-sync", {
        "pkg/fast.py": """\
            from .helpers import log_scalar

            def dispatch(x):
                log_scalar(x)
                return x
            """,
        "pkg/helpers.py": """\
            def log_scalar(t):
                return t.item()
            """,
    }, config=FAST_CFG)
    assert len(res.new) == 1
    assert res.new[0].path == "pkg/helpers.py"
    assert "t.item()" in res.new[0].message
    assert "pkg.fast.dispatch" in res.new[0].message


def test_cross_host_sync_negative_unreachable(tmp_path):
    res = lint_pkg(tmp_path, "cross-host-sync", {
        "pkg/fast.py": """\
            def dispatch(x):
                return x
            """,
        "pkg/helpers.py": """\
            def log_scalar(t):
                return t.item()
            """,
    }, config=FAST_CFG)
    assert res.new == []


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

def test_lock_order_detects_two_module_cycle(tmp_path):
    # the acceptance-criteria fixture: A takes LA then calls into b which
    # takes LB; B takes LB then calls into a which takes LA
    res = lint_pkg(tmp_path, "lock-order", {
        "pkg/a.py": """\
            import threading
            from . import b

            LA = threading.Lock()

            def fa():
                with LA:
                    b.acquire_b()

            def acquire_a():
                with LA:
                    pass
            """,
        "pkg/b.py": """\
            import threading
            from . import a

            LB = threading.Lock()

            def fb():
                with LB:
                    a.acquire_a()

            def acquire_b():
                with LB:
                    pass
            """,
    })
    assert len(res.new) == 1
    msg = res.new[0].message
    assert "lock-order cycle" in msg
    assert "pkg.a.LA" in msg and "pkg.b.LB" in msg


def test_lock_order_locked_suffix_suppresses_and_plain_call_flags(tmp_path):
    files = {
        "pkg/c.py": """\
            import threading

            LC = threading.Lock()

            def get():
                with LC:
                    return _refresh_locked()

            def _refresh_locked():
                return 1
            """,
    }
    assert lint_pkg(tmp_path, "lock-order", files).new == []
    # same shape WITHOUT the convention suffix, callee re-acquires: a
    # genuine self-deadlock on a non-reentrant Lock
    tmp2 = tmp_path / "flagged"
    tmp2.mkdir()
    res = lint_pkg(tmp2, "lock-order", {
        "pkg/c.py": """\
            import threading

            LC = threading.Lock()

            def get():
                with LC:
                    return _refresh()

            def _refresh():
                with LC:
                    return 1
            """,
    })
    assert len(res.new) == 1
    assert "self-deadlock" in res.new[0].message


def test_lock_order_rlock_self_reacquire_ok(tmp_path):
    res = lint_pkg(tmp_path, "lock-order", {
        "pkg/c.py": """\
            import threading

            LC = threading.RLock()

            def get():
                with LC:
                    return _refresh()

            def _refresh():
                with LC:
                    return 1
            """,
    })
    assert res.new == []


def test_lock_order_lexical_nesting_one_direction_ok(tmp_path):
    # consistent order A-then-B everywhere: no cycle, no finding
    res = lint_pkg(tmp_path, "lock-order", {
        "pkg/c.py": """\
            import threading

            LA = threading.Lock()
            LB = threading.Lock()

            def f():
                with LA:
                    with LB:
                        pass

            def g():
                with LA:
                    with LB:
                        pass
            """,
    })
    assert res.new == []


# ---------------------------------------------------------------------------
# import-layering
# ---------------------------------------------------------------------------

LAYER_CFG = {"import_layers": [
    {"name": "core", "prefixes": ["pkg.core"]},
    {"name": "api", "prefixes": ["pkg.api"]},
]}


def test_import_layering_back_edge(tmp_path):
    res = lint_pkg(tmp_path, "import-layering", {
        "pkg/core/x.py": """\
            from ..api import y

            def f():
                return y
            """,
        "pkg/api/y.py": """\
            y = 1
            """,
    }, config=LAYER_CFG)
    assert len(res.new) == 1
    assert res.new[0].path == "pkg/core/x.py"
    assert "layering violation" in res.new[0].message


def test_import_layering_forward_edge_and_deferred_ok(tmp_path):
    res = lint_pkg(tmp_path, "import-layering", {
        "pkg/core/x.py": """\
            def f():
                from ..api import y  # deferred: sanctioned cycle-breaker
                return y
            """,
        "pkg/api/y.py": """\
            from ..core import x
            y = 1
            """,
    }, config=LAYER_CFG)
    assert res.new == []


def test_import_layering_cycle(tmp_path):
    res = lint_pkg(tmp_path, "import-layering", {
        "pkg/m1.py": """\
            from . import m2
            """,
        "pkg/m2.py": """\
            from . import m1
            """,
    }, config={"import_layers": []})
    assert len(res.new) == 1
    assert "import cycle" in res.new[0].message
    assert "pkg.m1 -> pkg.m2 -> pkg.m1" in res.new[0].message


# ---------------------------------------------------------------------------
# shared-state-race (graft-lint 3.0)
# ---------------------------------------------------------------------------

RACE_HEAD = """\
    import threading

    class Worker:
        def __init__(self):
            self.items = []
            self._lock = threading.Lock()

        def start(self):
            threading.Thread(target=self._loop, daemon=True).start()
            threading.Thread(target=self._drain, daemon=True).start()

    """


def test_race_two_thread_write_write(tmp_path):
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": RACE_HEAD + """\
    def _loop(self):
        self.items.append(1)

    def _drain(self):
        self.items.pop()
    """.replace("\n    ", "\n        "),
    })
    assert len(res.new) == 1
    msg = res.new[0].message
    assert "'self.items'" in msg and "written in" in msg
    # both witness paths name their thread roots
    assert "Worker._loop" in msg and "Worker._drain" in msg
    # structured witness chain rides the finding for the SARIF exporter
    assert res.new[0].related and all(
        r["path"] == "pkg/w.py" for r in res.new[0].related)


def test_race_write_read(tmp_path):
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": RACE_HEAD + """\
    def _loop(self):
        self.items.append(1)

    def _drain(self):
        return len(self.items)
    """.replace("\n    ", "\n        "),
    })
    assert len(res.new) == 1
    assert "read in" in res.new[0].message


def test_race_common_lock_negative_through_call_edge(tmp_path):
    # the write side holds the lock around a CALL into the helper: lock
    # domination must propagate through the call edge, not just lexically
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": RACE_HEAD + """\
    def _loop(self):
        with self._lock:
            self._put()

    def _put(self):
        self.items.append(1)

    def _drain(self):
        with self._lock:
            self.items.pop()
    """.replace("\n    ", "\n        "),
    })
    assert res.new == []


def test_race_unlocked_second_path_defeats_domination(tmp_path):
    # the same helper ALSO called outside the lock: the meet over paths
    # is empty and the conflict comes back
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": RACE_HEAD + """\
    def _loop(self):
        with self._lock:
            self._put()
        self._put()

    def _put(self):
        self.items.append(1)

    def _drain(self):
        with self._lock:
            self.items.pop()
    """.replace("\n    ", "\n        "),
    })
    assert len(res.new) == 1


def test_race_locked_suffix_caller_holds_negative(tmp_path):
    # accesses inside *_locked helpers are the caller-holds convention —
    # trusted, same as unguarded-global/lock-order
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": RACE_HEAD + """\
    def _loop(self):
        with self._lock:
            self._put_locked()

    def _put_locked(self):
        self.items.append(1)

    def _drain(self):
        with self._lock:
            self.items.pop()
    """.replace("\n    ", "\n        "),
    })
    assert res.new == []


def test_race_config_thread_roots_seam(tmp_path):
    # no Thread() anywhere: the config escape names the callback seams
    # (caller-thread entry points) and a module global conflicts
    files = {
        "pkg/s.py": """\
            _REG = {}

            def produce(k, v):
                _REG[k] = v

            def consume(k):
                return _REG.get(k)
            """,
    }
    cfg = {"thread_roots": {"pkg/s.py": ["produce", "consume"]}}
    res = lint_pkg(tmp_path, "shared-state-race", files, config=cfg)
    assert len(res.new) == 1
    assert "module global '_REG'" in res.new[0].message
    # without the config roots the same tree is silent (< 2 roots)
    tmp2 = tmp_path / "quiet"
    tmp2.mkdir()
    assert lint_pkg(tmp2, "shared-state-race", files).new == []


def test_race_global_rebind_via_global_stmt_is_a_write(tmp_path):
    # the classic global-swap race: `global X; X = {...}` on one thread
    # vs `X[k] = v` on another — a plain-Name rebind must count as a
    # write (review regression: only Attribute/Subscript targets did)
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/g.py": """\
            import threading

            _CACHE = {}

            def _swap():
                global _CACHE
                _CACHE = {}

            def _fill():
                _CACHE["k"] = 1

            def start():
                threading.Thread(target=_swap, daemon=True).start()
                threading.Thread(target=_fill, daemon=True).start()
            """,
    })
    assert len(res.new) == 1
    assert "module global '_CACHE'" in res.new[0].message


def test_race_init_and_safe_primitives_excluded(tmp_path):
    # __init__ writes happen-before the spawns; Event/Queue fields are
    # internally synchronized — neither may conflict
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": """\
            import threading

            class Worker:
                def __init__(self):
                    self.items = []
                    self._wake = threading.Event()

                def start(self):
                    threading.Thread(target=self._a, daemon=True).start()
                    threading.Thread(target=self._b, daemon=True).start()

                def _a(self):
                    self._wake.set()

                def _b(self):
                    self._wake.clear()
                    return len(self.items)
            """,
    })
    assert res.new == []


def test_race_httpd_handler_methods_are_roots(tmp_path):
    # ThreadingHTTPServer handler do_* methods run on server threads
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/h.py": """\
            import threading
            from http.server import (BaseHTTPRequestHandler,
                                     ThreadingHTTPServer)

            _CACHE = {}

            class H(BaseHTTPRequestHandler):
                def do_GET(self):
                    _CACHE["last"] = self.path

            def refresh():
                _CACHE.clear()

            def serve():
                httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
                threading.Thread(target=refresh, daemon=True).start()
                return httpd
            """,
    })
    assert len(res.new) == 1
    assert "http handler" in res.new[0].message


def test_race_httpd_handler_in_another_module_still_roots(tmp_path):
    # review regression: the handler class moved out of the spawning
    # module must still contribute its do_* thread roots (resolution
    # follows the import binding, like every other cross-module seam)
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/handlers.py": """\
            from http.server import BaseHTTPRequestHandler

            CACHE = {}

            class ScrapeHandler(BaseHTTPRequestHandler):
                def do_GET(self):
                    CACHE["last"] = self.path

            def refresh():
                CACHE.clear()
            """,
        "pkg/server.py": """\
            import threading
            from http.server import ThreadingHTTPServer

            from . import handlers

            def serve():
                httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                            handlers.ScrapeHandler)
                threading.Thread(target=handlers.refresh,
                                 daemon=True).start()
                return httpd
            """,
    })
    assert len(res.new) == 1
    assert "http handler" in res.new[0].message
    assert "do_GET" in res.new[0].message


def test_race_ann_assign_write_and_safe_field(tmp_path):
    # review regression: annotated assignments are writes too — both for
    # the conflict itself and for the Event-field safety exemption
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": """\
            import threading

            class Worker:
                def __init__(self):
                    self._wake: threading.Event = threading.Event()

                def start(self):
                    threading.Thread(target=self._a, daemon=True).start()
                    threading.Thread(target=self._b, daemon=True).start()

                def _a(self):
                    self.count: int = 0
                    self._wake.set()

                def _b(self):
                    self.count: int = 1
                    self._wake.clear()
            """,
    })
    assert len(res.new) == 1
    assert "'self.count'" in res.new[0].message  # _wake stays exempt


def test_race_pragma_on_one_write_does_not_silence_the_target(tmp_path):
    # review regression: a pragma acknowledges ITS write only — the
    # finding re-anchors on the next unacknowledged conflicting write
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": """\
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._a, daemon=True).start()
                    threading.Thread(target=self._b, daemon=True).start()

                def _a(self):
                    self.n = 1  # graft-lint: disable=shared-state-race

                def _b(self):
                    self.n = 2
            """,
    })
    assert len(res.new) == 1
    assert "written in 'Worker._b'" in res.new[0].message


def test_race_pragma_suppresses(tmp_path):
    res = lint_pkg(tmp_path, "shared-state-race", {
        "pkg/w.py": """\
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._a, daemon=True).start()
                    threading.Thread(target=self._b, daemon=True).start()

                def _a(self):
                    self.n = 1  # graft-lint: disable=shared-state-race

                def _b(self):
                    self.n = 2  # graft-lint: disable=shared-state-race
            """,
    })
    assert res.new == []


def test_race_cache_warm_and_edit_invalidate(tmp_path):
    # the new summary fields ride the same content-hash cache: a warm run
    # parses nothing and still reports, an edit re-parses exactly one file
    files = {
        "pkg/w.py": RACE_HEAD + """\
    def _loop(self):
        self.items.append(1)

    def _drain(self):
        self.items.pop()
    """.replace("\n    ", "\n        "),
    }
    write_pkg(tmp_path, files)
    cache = tmp_path / "cache.json"
    cold = lint_pkg(tmp_path, "shared-state-race", cache_path=str(cache))
    assert len(cold.new) == 1 and cold.parsed_files == cold.total_files
    warm = lint_pkg(tmp_path, "shared-state-race", cache_path=str(cache))
    assert warm.parsed_files == 0
    assert warm.summary_cache_hits == warm.total_files
    assert [f.as_dict() for f in warm.new] == [f.as_dict() for f in cold.new]
    # fix the race: one file re-parses, the finding disappears
    src = (tmp_path / "pkg" / "w.py").read_text()
    (tmp_path / "pkg" / "w.py").write_text(src.replace(
        "        self.items.append(1)",
        "        with self._lock:\n            self.items.append(1)")
        .replace("        self.items.pop()",
                 "        with self._lock:\n            self.items.pop()"))
    fixed = lint_pkg(tmp_path, "shared-state-race", cache_path=str(cache))
    assert fixed.parsed_files == 1 and fixed.new == []


def test_race_shipped_tree_fixed_sites_stay_clean():
    # the ISSUE 14 production fixes must hold: the engine's in-transit
    # counter and the watchdog's thread handle are lock-dominated now, so
    # no NEW finding may name them (the reasoned survivors are baselined)
    from tools.lint import default_baseline_path, load_baseline
    res = run_lint(rules=["shared-state-race"],
                   baseline_entries=load_baseline(default_baseline_path()))
    assert [f.text() for f in res.new] == []
    assert not any("'self._in_transit'" in f.message or
                   "'self._thread' of class 'StepWatchdog'" in f.message
                   for f in res.baselined)
    assert not any(f.path == "paddle_tpu/resilience/watchdog.py"
                   for f in res.baselined)


# ---------------------------------------------------------------------------
# pragmas still apply to project-rule findings
# ---------------------------------------------------------------------------

def test_pragma_suppresses_project_finding(tmp_path):
    res = lint_pkg(tmp_path, "cross-host-sync", {
        "pkg/fast.py": """\
            from .helpers import log_scalar

            def dispatch(x):
                log_scalar(x)
                return x
            """,
        "pkg/helpers.py": """\
            def log_scalar(t):
                return t.item()  # graft-lint: disable=cross-host-sync
            """,
    }, config=FAST_CFG)
    assert res.new == []


# ---------------------------------------------------------------------------
# cache: warm runs parse nothing, edits invalidate, version pin
# ---------------------------------------------------------------------------

CACHE_FILES = {
    "pkg/a.py": TRACED_A,
    "pkg/util.py": """\
        def helper(x):
            return x + 1
        """,
}


def test_cache_warm_run_parses_nothing_and_edit_invalidates(tmp_path):
    write_pkg(tmp_path, CACHE_FILES)
    cache = tmp_path / "cache.json"
    cold = lint_pkg(tmp_path, "cross-trace-impurity",
                    cache_path=str(cache))
    assert cold.parsed_files == cold.total_files > 0
    assert cold.new == []

    warm = lint_pkg(tmp_path, "cross-trace-impurity",
                    cache_path=str(cache))
    assert warm.parsed_files == 0
    assert warm.summary_cache_hits == warm.total_files
    assert warm.new == []

    # edit util.py to become impure: exactly one file re-parses and the
    # finding appears (graphs rebuilt from the fresh summary)
    (tmp_path / "pkg" / "util.py").write_text(textwrap.dedent("""\
        import time

        def helper(x):
            return x * time.time()
        """))
    edited = lint_pkg(tmp_path, "cross-trace-impurity",
                      cache_path=str(cache))
    assert edited.parsed_files == 1
    assert len(edited.new) == 1 and edited.new[0].path == "pkg/util.py"


def test_cache_format_version_pin_self_invalidates(tmp_path):
    write_pkg(tmp_path, CACHE_FILES)
    cache = tmp_path / "cache.json"
    lint_pkg(tmp_path, "cross-trace-impurity", cache_path=str(cache))
    data = json.loads(cache.read_text())
    assert data["format"] == CACHE_FORMAT_VERSION
    # a cache written by a different format version is discarded whole
    data["format"] = CACHE_FORMAT_VERSION + 1
    cache.write_text(json.dumps(data))
    res = lint_pkg(tmp_path, "cross-trace-impurity", cache_path=str(cache))
    assert res.parsed_files == res.total_files > 0
    # and the rewrite restored the pinned version
    assert json.loads(cache.read_text())["format"] == CACHE_FORMAT_VERSION


def test_cache_per_file_findings_served_without_parse(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    cache = tmp_path / "cache.json"
    cold = run_lint(paths=[str(f)], rules=["silent-swallow"],
                    root=str(tmp_path), cache_path=str(cache))
    assert len(cold.new) == 1 and cold.parsed_files == 1
    warm = run_lint(paths=[str(f)], rules=["silent-swallow"],
                    root=str(tmp_path), cache_path=str(cache))
    assert warm.parsed_files == 0 and warm.findings_cache_hits == 1
    assert [x.as_dict() for x in warm.new] == [x.as_dict() for x in cold.new]


def test_summary_format_constant_is_pinned():
    # bump CACHE_FORMAT_VERSION whenever SUMMARY_FORMAT changes; this pin
    # forces the bump to be a conscious, reviewed edit (4: graft-lint 5.0
    # — per-function may-block events, kind + boundedness + held locks)
    assert (SUMMARY_FORMAT, CACHE_FORMAT_VERSION) == (4, 4)


def test_stale_v2_cache_is_resummarized_not_crashed(tmp_path):
    # ISSUE 18: a cache written by the graft-lint 3.0 layout (format 2 —
    # no raise-sets/resource events) must be discarded whole and rebuilt,
    # never half-read into the new summary shape
    write_pkg(tmp_path, CACHE_FILES)
    cache = tmp_path / "cache.json"
    first = lint_pkg(tmp_path, "cross-trace-impurity",
                     cache_path=str(cache))
    data = json.loads(cache.read_text())
    data["format"] = 2
    cache.write_text(json.dumps(data))
    res = lint_pkg(tmp_path, "cross-trace-impurity", cache_path=str(cache))
    assert res.errors == []
    assert res.parsed_files == res.total_files > 0  # full re-summarize
    assert [f.as_dict() for f in res.new] == \
        [f.as_dict() for f in first.new]
    assert json.loads(cache.read_text())["format"] == CACHE_FORMAT_VERSION


def test_stale_v3_cache_is_resummarized_not_crashed(tmp_path):
    # ISSUE 19: a cache written by the graft-lint 4.0 layout (format 3 —
    # no may-block events) must be discarded whole and rebuilt; reading
    # its summaries into the v4 shape would KeyError on "blk"
    write_pkg(tmp_path, CACHE_FILES)
    cache = tmp_path / "cache.json"
    first = lint_pkg(tmp_path, "cross-trace-impurity",
                     cache_path=str(cache))
    data = json.loads(cache.read_text())
    data["format"] = 3
    cache.write_text(json.dumps(data))
    res = lint_pkg(tmp_path, "cross-trace-impurity", cache_path=str(cache))
    assert res.errors == []
    assert res.parsed_files == res.total_files > 0  # full re-summarize
    assert [f.as_dict() for f in res.new] == \
        [f.as_dict() for f in first.new]
    assert json.loads(cache.read_text())["format"] == CACHE_FORMAT_VERSION


# ---------------------------------------------------------------------------
# --changed-only
# ---------------------------------------------------------------------------

needs_git = pytest.mark.skipif(shutil.which("git") is None,
                               reason="git not available")


def _git(tmp_path, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=str(tmp_path), capture_output=True, text=True, check=True)


@needs_git
def test_changed_only_narrows_to_edited_files(tmp_path):
    write_pkg(tmp_path, {
        "pkg/a.py": "x = 1\n",
        "pkg/b.py": "y = 1\n",
    })
    _git(tmp_path, "init", "-b", "main")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-m", "seed", "--no-gpg-sign")
    (tmp_path / "pkg" / "a.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n")
    res = run_lint(paths=["."], rules=["silent-swallow"],
                   root=str(tmp_path), changed_only=True)
    assert res.changed_only is True
    assert res.scanned == ["pkg/a.py"]
    assert len(res.new) == 1 and res.new[0].path == "pkg/a.py"


@needs_git
def test_changed_only_sees_untracked_files(tmp_path):
    write_pkg(tmp_path, {"pkg/a.py": "x = 1\n"})
    _git(tmp_path, "init", "-b", "main")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-m", "seed", "--no-gpg-sign")
    (tmp_path / "pkg" / "new.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n")
    res = run_lint(paths=["."], rules=["silent-swallow"],
                   root=str(tmp_path), changed_only=True)
    assert res.changed_only is True and res.scanned == ["pkg/new.py"]
    assert len(res.new) == 1


def test_changed_only_outside_git_falls_back_to_full_run(tmp_path):
    write_pkg(tmp_path, {
        "pkg/a.py": "try:\n    x = 1\nexcept Exception:\n    pass\n",
        "pkg/b.py": "y = 1\n",
    })
    res = run_lint(paths=["."], rules=["silent-swallow"],
                   root=str(tmp_path), changed_only=True)
    assert res.changed_only is False
    assert sorted(res.scanned) == ["pkg/__init__.py", "pkg/a.py", "pkg/b.py"]
    assert len(res.new) == 1


@needs_git
def test_changed_only_project_rules_cover_unchanged_files(tmp_path):
    # the edit is in fast.py; the finding it creates lives in the
    # UNCHANGED helpers.py — changed-only must still surface it
    write_pkg(tmp_path, {
        "pkg/fast.py": """\
            def dispatch(x):
                return x
            """,
        "pkg/helpers.py": """\
            def log_scalar(t):
                return t.item()
            """,
    })
    _git(tmp_path, "init", "-b", "main")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-m", "seed", "--no-gpg-sign")
    (tmp_path / "pkg" / "fast.py").write_text(textwrap.dedent("""\
        from .helpers import log_scalar

        def dispatch(x):
            log_scalar(x)
            return x
        """))
    res = run_lint(paths=["."], rules=["cross-host-sync"],
                   root=str(tmp_path), changed_only=True, config=FAST_CFG)
    assert res.changed_only is True and res.scanned == ["pkg/fast.py"]
    assert len(res.new) == 1 and res.new[0].path == "pkg/helpers.py"


# ---------------------------------------------------------------------------
# the TODO-reason gate (--allow-todo)
# ---------------------------------------------------------------------------

def test_cli_fails_on_todo_baseline_reason(tmp_path, capsys):
    from tools.lint.cli import main
    f = tmp_path / "mod.py"
    f.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    bl = tmp_path / "baseline.json"
    cache = tmp_path / "cache.json"
    assert main([str(f), f"--baseline={bl}", f"--cache-file={cache}",
                 "--update-baseline"]) == 0
    # the freshly stamped TODO reason must FAIL a normal run…
    assert main([str(f), f"--baseline={bl}",
                 f"--cache-file={cache}"]) == 1
    err = capsys.readouterr().err
    assert "TODO" in err and "--allow-todo" in err
    # …pass with the drafting escape hatch…
    assert main([str(f), f"--baseline={bl}", f"--cache-file={cache}",
                 "--allow-todo"]) == 0
    # …and pass once a real reason is written
    entries = json.loads(bl.read_text())["entries"]
    entries[0]["reason"] = "reviewed: teardown path, nothing to signal to"
    save_baseline(str(bl), entries)
    assert main([str(f), f"--baseline={bl}",
                 f"--cache-file={cache}"]) == 0
    capsys.readouterr()


def test_cli_json_report_still_emitted_on_todo_gate(tmp_path, capsys):
    # the TODO gate fails the run AFTER reporting: a --format=json
    # consumer must always get the report (plus the offending entries)
    from tools.lint.cli import main
    f = tmp_path / "mod.py"
    f.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    bl = tmp_path / "baseline.json"
    cache = tmp_path / "cache.json"
    assert main([str(f), f"--baseline={bl}", f"--cache-file={cache}",
                 "--update-baseline"]) == 0
    capsys.readouterr()
    assert main([str(f), f"--baseline={bl}", f"--cache-file={cache}",
                 "--format=json"]) == 1
    out = capsys.readouterr()
    report = json.loads(out.out)  # valid JSON despite the failure
    assert report["clean"] is False
    assert len(report["todo_baseline_entries"]) == 1
    assert report["findings"] == []  # the finding itself is absorbed
    assert "TODO" in out.err


def test_scoped_update_baseline_preserves_project_entries(tmp_path, capsys):
    # a path-narrowed --update-baseline builds a PARTIAL project graph
    # (missing roots make project findings vanish spuriously): project-
    # rule entries must pass through untouched — neither pruned nor
    # duplicated by partial-graph findings
    from tools.lint.cli import main
    shipped = os.path.join(REPO, "tools", "lint", "baseline.json")
    bl = tmp_path / "baseline.json"
    bl.write_text(open(shipped).read())
    before = json.loads(bl.read_text())["entries"]
    # dispatch_cache.py holds a justified cross-host-sync entry whose
    # finding needs tensor.py's roots to regenerate
    assert main(["paddle_tpu/core/dispatch_cache.py", f"--baseline={bl}",
                 "--no-cache", "--update-baseline"]) == 0
    after = json.loads(bl.read_text())["entries"]
    keys = [(e["path"], e["rule"], e["message"]) for e in after]
    assert len(keys) == len(set(keys)), "duplicate baseline keys"
    assert len(after) == len(before)
    assert any(e["rule"] == "cross-host-sync"
               and e["path"] == "paddle_tpu/core/dispatch_cache.py"
               and not str(e["reason"]).startswith("TODO")
               for e in after), "justified project entry was pruned"
    capsys.readouterr()


def test_update_baseline_keeps_entries_of_unparseable_files(tmp_path,
                                                            capsys):
    # a file that fails to parse produced no findings — regeneration must
    # not mistake that for "the code improved" and prune its entries
    from tools.lint.cli import main
    good = tmp_path / "good.py"
    bad = tmp_path / "bad.py"
    good.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    bad.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    bl = tmp_path / "baseline.json"
    assert main([str(good), str(bad), f"--baseline={bl}", "--no-cache",
                 "--update-baseline"]) == 0
    entries = json.loads(bl.read_text())["entries"]
    assert len(entries) == 2
    for e in entries:
        e["reason"] = "reviewed: fixture"
    save_baseline(str(bl), entries)
    bad.write_text("def broken(:\n")  # syntax error
    assert main([str(good), str(bad), f"--baseline={bl}", "--no-cache",
                 "--update-baseline"]) == 0
    after = json.loads(bl.read_text())["entries"]
    assert len(after) == 2, "entry of unparseable file was pruned"
    capsys.readouterr()


def test_cache_save_failure_keeps_dirty_and_leaves_no_temp(tmp_path,
                                                           monkeypatch):
    from tools.lint.wholeprogram.cache import SummaryCache
    c = SummaryCache(str(tmp_path / "cache.json"), "fp")
    c.put_summary("a.py", "sha", {"x": 1})
    def boom(*a, **k):
        raise OSError("disk full")
    monkeypatch.setattr(os, "replace", boom)
    c.save()
    assert c.dirty is True  # a retry in-process still wants to save
    leftovers = [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
    assert leftovers == []
    monkeypatch.undo()
    c.save()
    assert c.dirty is False and (tmp_path / "cache.json").exists()


@needs_git
@pytest.mark.slow
def test_changed_only_update_baseline_keeps_project_entries(tmp_path):
    # project rules scan the full tree even under --changed-only; their
    # justified entries for UNCHANGED files must survive a narrowed
    # --update-baseline (no TODO-stamped twins, no duplicate keys).
    # Regression: the in_scope filter used the per-file scan set for
    # project-rule entries too, duplicating all four deliberate project
    # findings with TODO reasons on every incremental regeneration.
    shipped = os.path.join(REPO, "tools", "lint", "baseline.json")
    bl = tmp_path / "baseline.json"
    bl.write_text(open(shipped).read())
    before = json.loads(bl.read_text())["entries"]
    p = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--changed-only",
         f"--baseline={bl}", f"--cache-file={tmp_path / 'cache.json'}",
         "--update-baseline"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert p.returncode == 0, p.stderr
    after = json.loads(bl.read_text())["entries"]
    keys = [(e["path"], e["rule"], e["message"]) for e in after]
    assert len(keys) == len(set(keys)), "duplicate baseline keys"
    assert len(after) == len(before)
    assert not any(str(e.get("reason", "")).startswith("TODO")
                   for e in after), "justified entries replaced by TODOs"


@pytest.mark.slow
def test_real_tree_warm_changed_only_parses_nothing(tmp_path):
    # the acceptance pin: a warm --changed-only run over the unchanged
    # shipped tree serves every summary from the cache (cache-hit line
    # in the JSON report shows 0 parsed)
    cache = tmp_path / "cache.json"

    def cli_json(*extra):
        p = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--format=json",
             f"--cache-file={cache}", "--no-baseline", *extra],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        return json.loads(p.stdout)

    cold = cli_json()
    assert cold["cache"]["parsed_files"] == cold["cache"]["total_files"]
    warm = cli_json("--changed-only")
    assert warm["cache"]["parsed_files"] == 0
    assert warm["cache"]["summary_hits"] == warm["cache"]["total_files"]
    assert warm["run_seconds"] < cold["run_seconds"]


# ---------------------------------------------------------------------------
# the shipped layer DAG matches reality (cheap sanity on real summaries)
# ---------------------------------------------------------------------------

def test_shipped_tree_layer_dag_has_no_back_edges():
    from tools.lint.engine import DEFAULT_CONFIG
    res = run_lint(rules=["import-layering"], baseline_entries=[])
    msgs = [f.message for f in res.new]
    assert not any("layering violation" in m for m in msgs), msgs
    # the two known load-bearing package cycles are the only cycles
    cycles = [m for m in msgs if "import cycle" in m]
    assert len(cycles) == 2
    assert any("paddle_tpu.sparse" in m for m in cycles)
    assert any("paddle_tpu.distribution" in m for m in cycles)
    assert DEFAULT_CONFIG["import_layers"][0]["name"] == "foundation"


# ---------------------------------------------------------------------------
# ISSUE 15: the HTTP serving tier's lint-config membership is pinned — the
# front door and the router must stay in the strict poll tier, in the api
# import layer, and in the race detector's thread-root table
# ---------------------------------------------------------------------------

def test_http_serving_tier_lint_config_membership():
    from tools.lint.engine import DEFAULT_CONFIG

    # naked-retry strict tier: any in-loop time.sleep in the HTTP tier is
    # a finding (serving-side threads poll via resilience.jitter_sleep)
    poll = DEFAULT_CONFIG["poll_loop_paths"]
    assert "paddle_tpu/serving" in poll
    assert "paddle_tpu/serving/http.py" in poll
    assert "paddle_tpu/serving/router.py" in poll

    # import layering: the serving tier (front door included) is api-layer
    api = next(layer for layer in DEFAULT_CONFIG["import_layers"]
               if layer["name"] == "api")
    assert "paddle_tpu.serving" in api["prefixes"]

    # shared-state-race roots: the router's caller-thread surface, its
    # health-poll thread, and the Future-resolution seam are registered
    roots = DEFAULT_CONFIG["thread_roots"]
    router_roots = roots["paddle_tpu/serving/router.py"]
    for entry in ("Router.submit", "Router.stop", "Router.drain_replica",
                  "Router._poll_loop", "Router._on_replica_done"):
        assert entry in router_roots, entry
    # the shared scaffolding's shutdown path covers BOTH endpoints
    assert "ServerHost.close" in roots["paddle_tpu/observability/http.py"]


def test_fleet_tier_lint_config_membership():
    # ISSUE 20: the fleet tier joins every strict lint tier the rest of
    # serving lives in — a package split or rename breaks THIS test, not
    # silently the analyses
    from tools.lint.engine import DEFAULT_CONFIG

    # naked-retry strict tier + unbounded-wait strict tier
    for key in ("poll_loop_paths", "bounded_wait_paths"):
        tier = DEFAULT_CONFIG[key]
        assert "paddle_tpu/serving/fleet.py" in tier, key
        assert "paddle_tpu/serving/fleet_worker.py" in tier, key

    # the long-lived loops are unbounded-wait roots
    bw_roots = DEFAULT_CONFIG["bounded_wait_roots"]
    assert "FleetSupervisor._monitor_loop" in \
        bw_roots["paddle_tpu/serving/fleet.py"]
    assert "main" in bw_roots["paddle_tpu/serving/fleet_worker.py"]

    # import layering: the rpc transport submodule is carved into the
    # api layer (most-specific prefix wins) so serving/fleet may import
    # it at module scope; the REST of distributed stays a higher layer
    layers = DEFAULT_CONFIG["import_layers"]
    api = next(layer for layer in layers if layer["name"] == "api")
    assert "paddle_tpu.distributed.rpc" in api["prefixes"]
    dist = next(layer for layer in layers if layer["name"] == "distributed")
    assert "paddle_tpu.distributed" in dist["prefixes"]

    # shared-state-race roots: supervisor caller surface + monitor
    # thread + reader threads, and the worker-side handler surface
    roots = DEFAULT_CONFIG["thread_roots"]
    fleet_roots = roots["paddle_tpu/serving/fleet.py"]
    for entry in ("FleetSupervisor.start", "FleetSupervisor.stop",
                  "FleetSupervisor._monitor_loop", "RemoteEngine.submit",
                  "RemoteEngine._read_stream"):
        assert entry in fleet_roots, entry
    worker_roots = roots["paddle_tpu/serving/fleet_worker.py"]
    for entry in ("_Handler.handle", "_srv_submit", "main"):
        assert entry in worker_roots, entry

    # exception contracts: the worker-side handlers mirror the PS
    # service convention; the supervisor's spawn-failure surface is typed
    contracts = DEFAULT_CONFIG["exception_contracts"]
    fw = contracts["paddle_tpu/serving/fleet_worker.py"]
    assert {"_srv_submit", "_srv_cancel", "_srv_withdraw", "_srv_drain",
            "_srv_prefix_summary", "_srv_beat"} <= set(fw)
    assert "QueueFull" in fw["_srv_submit"]
    assert "DrainTimeout" in fw["_srv_drain"]
    assert "FleetWorkerLost" in contracts[
        "paddle_tpu/serving/fleet.py"]["FleetSupervisor.start"]


def test_fleet_tier_thread_roots_resolve_on_shipped_tree():
    """Every registered fleet thread root resolves to a real function on
    the shipped tree — a rename breaks THIS test, not silently the race
    analysis."""
    import ast
    import os

    from tools.lint.engine import (DEFAULT_CONFIG, REPO_ROOT,
                                   iter_python_files)
    from tools.lint.wholeprogram.project import Project
    from tools.lint.wholeprogram.summary import build_summary

    summaries = {}
    for abspath in iter_python_files(["paddle_tpu/serving"]):
        rel = os.path.relpath(abspath, REPO_ROOT).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as fh:
            src = fh.read()
        summaries[rel] = build_summary(
            rel, ast.parse(src), src.splitlines(), DEFAULT_CONFIG)
    project = Project(summaries, DEFAULT_CONFIG)
    labels = {label for _m, _fi, label in project.thread_roots()}
    for needle in ("FleetSupervisor.start", "FleetSupervisor.stop",
                   "FleetSupervisor._monitor_loop", "RemoteEngine.submit",
                   "RemoteEngine._read_stream", "_Handler.handle",
                   "_srv_submit"):
        assert any(needle in lab for lab in labels), (needle, labels)


def test_http_serving_tier_thread_roots_resolve_on_shipped_tree():
    """The registered router roots and the front door's discovered do_*
    handler methods all resolve to real functions on the shipped tree —
    a rename breaks THIS test, not silently the race analysis."""
    import ast
    import os

    from tools.lint.engine import (DEFAULT_CONFIG, REPO_ROOT,
                                   iter_python_files)
    from tools.lint.wholeprogram.project import Project
    from tools.lint.wholeprogram.summary import build_summary

    summaries = {}
    for abspath in iter_python_files(["paddle_tpu/serving",
                                      "paddle_tpu/observability"]):
        rel = os.path.relpath(abspath, REPO_ROOT).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as fh:
            src = fh.read()
        summaries[rel] = build_summary(
            rel, ast.parse(src), src.splitlines(), DEFAULT_CONFIG)
    project = Project(summaries, DEFAULT_CONFIG)
    labels = {label for _m, _fi, label in project.thread_roots()}
    for needle in ("Router.submit", "Router._poll_loop",
                   "Router._on_replica_done", "ServerHost.close"):
        assert any(needle in lab for lab in labels), (needle, labels)
    # the front door's handler threads are discovered via the literal
    # ThreadingHTTPServer ctor (the ServerHost refactor must not hide it)
    assert any("do_POST" in lab for lab in labels), labels
    assert any("do_GET" in lab for lab in labels), labels


def test_prefix_sharing_kv_pool_thread_roots(tmp_path):
    """ISSUE 17: the prefix index + refcount table stay under the race
    detector's locked domains — the pool's public sharing surface is
    registered as thread roots and every entry resolves to a real method
    on the shipped tree (a rename breaks THIS test, not silently the
    analysis)."""
    import ast
    import os

    from tools.lint.engine import (DEFAULT_CONFIG, REPO_ROOT,
                                   iter_python_files)
    from tools.lint.wholeprogram.project import Project
    from tools.lint.wholeprogram.summary import build_summary

    kv_roots = DEFAULT_CONFIG["thread_roots"]["paddle_tpu/serving/kv_cache.py"]
    for entry in ("PagedKVCache.acquire_prefix", "PagedKVCache.publish",
                  "PagedKVCache.prefix_summary", "PagedKVCache.free",
                  "PagedKVCache.alloc"):
        assert entry in kv_roots, entry

    summaries = {}
    for abspath in iter_python_files(["paddle_tpu/serving"]):
        rel = os.path.relpath(abspath, REPO_ROOT).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as fh:
            src = fh.read()
        summaries[rel] = build_summary(
            rel, ast.parse(src), src.splitlines(), DEFAULT_CONFIG)
    project = Project(summaries, DEFAULT_CONFIG)
    labels = {label for _m, _fi, label in project.thread_roots()}
    for needle in kv_roots:
        assert any(needle in lab for lab in labels), (needle, labels)


# ---------------------------------------------------------------------------
# exception-contract (ISSUE 18, graft-lint 4.0)
# ---------------------------------------------------------------------------

EC_CONFIG = {"exception_contracts": {
    "pkg/serving/entry.py": {"Door.do_call": ["ValueError"]}}}

EC_INNER = """
    class Boom(RuntimeError):
        pass

    def work():
        raise Boom("kaboom")
"""


def test_exception_contract_flags_escaping_type(tmp_path):
    res = lint_pkg(tmp_path, "exception-contract", files={
        "pkg/serving/entry.py": """
            from pkg.inner import work

            class Door:
                def do_call(self):
                    return work()
        """,
        "pkg/inner.py": EC_INNER,
    }, config=EC_CONFIG)
    assert len(res.new) == 1
    f = res.new[0]
    assert f.path == "pkg/serving/entry.py"
    assert "'pkg.inner.Boom'" in f.message or "'Boom'" in f.message
    assert "Door.do_call" in f.message
    # the witness chain walks root -> callee -> raise site
    quals = [r["message"] for r in f.related]
    assert any("Door.do_call" in q for q in quals)
    assert any("work" in q for q in quals)
    assert f.related[-1]["path"] == "pkg/inner.py"


def test_exception_contract_allows_declared_and_subclasses(tmp_path):
    # the contract names the BASE; the raised subclass is admitted via
    # the project class-base table
    res = lint_pkg(tmp_path, "exception-contract", files={
        "pkg/serving/entry.py": """
            from pkg.inner import work

            class Door:
                def do_call(self):
                    return work()
        """,
        "pkg/inner.py": EC_INNER,
    }, config={"exception_contracts": {
        "pkg/serving/entry.py": {"Door.do_call": ["RuntimeError"]}}})
    assert res.new == []


def test_exception_contract_subtracts_caught_along_chain(tmp_path):
    res = lint_pkg(tmp_path, "exception-contract", files={
        "pkg/serving/entry.py": """
            from pkg.inner import work

            class Door:
                def do_call(self):
                    try:
                        return work()
                    except RuntimeError:
                        return None
        """,
        "pkg/inner.py": EC_INNER,
    }, config=EC_CONFIG)
    assert res.new == []


def test_exception_contract_transparent_handler_ordering(tmp_path):
    # CPython handler order: the FIRST matching arm decides — here it
    # re-raises, and the later catch-all arm of the SAME try never runs
    res = lint_pkg(tmp_path, "exception-contract", files={
        "pkg/serving/entry.py": """
            from pkg.inner import work, Boom

            class Door:
                def do_call(self):
                    try:
                        return work()
                    except Boom:
                        raise
                    except Exception:
                        return None
        """,
        "pkg/inner.py": EC_INNER,
    }, config=EC_CONFIG)
    assert len(res.new) == 1


def test_exception_contract_pragma_at_raise_site(tmp_path):
    res = lint_pkg(tmp_path, "exception-contract", files={
        "pkg/serving/entry.py": """
            from pkg.inner import work

            class Door:
                def do_call(self):
                    return work()
        """,
        "pkg/inner.py": """
            class Boom(RuntimeError):
                pass

            def work():
                raise Boom("x")  # graft-lint: disable=exception-contract
        """,
    }, config=EC_CONFIG)
    assert res.new == []


def test_exception_contract_assertion_error_always_allowed(tmp_path):
    # invariant violations should crash loudly, not be status-mapped
    res = lint_pkg(tmp_path, "exception-contract", files={
        "pkg/serving/entry.py": """
            class Door:
                def do_call(self):
                    raise AssertionError("unreachable")
        """,
    }, config=EC_CONFIG)
    assert res.new == []


# ---------------------------------------------------------------------------
# resource-discipline (ISSUE 18, graft-lint 4.0)
# ---------------------------------------------------------------------------

RD_CONFIG = {"resource_pairs": [
    {"name": "pages", "acquire": ["Pool.alloc"],
     "release": ["Pool.free"], "transfer": ["publish"]}]}


def test_resource_discipline_flags_exception_path_leak(tmp_path):
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def leaky(pool, work, n):
                h = pool.alloc(n)
                work(h)
                pool.free(h)
        """,
    }, config=RD_CONFIG)
    assert len(res.new) == 1
    f = res.new[0]
    assert "'pages'" in f.message and "an exception path" in f.message
    assert any("acquired here" in r["message"] for r in f.related)


def test_resource_discipline_discarded_result_always_leaks(tmp_path):
    # calling the acquirer without binding the handle leaks on the
    # normal path too
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def drop(pool, n):
                pool.alloc(n)
                return n
        """,
    }, config=RD_CONFIG)
    assert len(res.new) == 1
    assert "a normal path" in res.new[0].message


def test_resource_discipline_finally_and_with_are_all_paths(tmp_path):
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def fin(pool, work, n):
                h = pool.alloc(n)
                try:
                    work(h)
                finally:
                    pool.free(h)

            def ctx(pool, work, n):
                with pool.alloc(n) as h:
                    work(h)
        """,
    }, config=RD_CONFIG)
    assert res.new == []


def test_resource_discipline_handler_release_covers_raise(tmp_path):
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def guarded(pool, work, n):
                h = pool.alloc(n)
                try:
                    work(h)
                except Exception:
                    pool.free(h)
                    raise
                pool.free(h)
        """,
    }, config=RD_CONFIG)
    assert res.new == []


def test_resource_discipline_ownership_escape_negatives(tmp_path):
    # return, attribute store, transfer callee and constructor capture
    # all hand the obligation to someone else
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            class Slot:
                def __init__(self, pages):
                    self.pages = pages

            def ret(pool, n):
                h = pool.alloc(n)
                return h

            def store(obj, pool, n):
                obj.h = pool.alloc(n)

            def share(pool, cache, key, n):
                h = pool.alloc(n)
                cache.publish(key, h)

            def wrap(pool, n):
                h = pool.alloc(n)
                return Slot(h)
        """,
    }, config=RD_CONFIG)
    assert res.new == []


def test_resource_discipline_none_guard_refines_branch(tmp_path):
    # alloc refusing returns None: the proven-empty branch owes nothing
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def maybe(pool, work, n):
                h = pool.alloc(n)
                if h is None:
                    return "noroom"
                try:
                    work(h)
                finally:
                    pool.free(h)
        """,
    }, config=RD_CONFIG)
    assert res.new == []


def test_resource_discipline_caller_owns_suffix_exempt(tmp_path):
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def grab_locked(pool, work, n):
                h = pool.alloc(n)
                work(h)
                return None
        """,
    }, config=dict(RD_CONFIG,
                   resource_caller_owns_suffixes=["_locked"]))
    assert res.new == []


def test_resource_discipline_loop_dispenses_collection(tmp_path):
    # iterating the acquired collection hands each element to the loop
    # body (checked per element); loop exit owes nothing
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def drain(pool, n):
                for h in pool.alloc(n):
                    pool.free(h)
        """,
    }, config=RD_CONFIG)
    assert res.new == []


def test_resource_discipline_fork_transfer_owns_on_success_only(tmp_path):
    files = {
        "pkg/a.py": """
            def feed(pool, sink, n):
                h = pool.alloc(n)
                sink.push(h)
        """,
    }
    cfg = {"resource_pairs": [
        {"name": "pages", "acquire": ["Pool.alloc"],
         "release": ["Pool.free"], "fork_transfers": ["push"]}]}
    res = lint_pkg(tmp_path, "resource-discipline", files=files, config=cfg)
    assert len(res.new) == 1  # push raising leaves the handle held
    write_pkg(tmp_path, {"pkg/a.py": """
        def feed(pool, sink, n):
            h = pool.alloc(n)
            try:
                sink.push(h)
            except BaseException:
                pool.free(h)
                raise
    """})
    res = lint_pkg(tmp_path, "resource-discipline", config=cfg)
    assert res.new == []


def test_resource_discipline_acquire_raises_handler_infeasible(tmp_path):
    # handleless pair (breaker-probe shape): before_call raises INSTEAD
    # of taking the probe, so the except arm for that type can never be
    # entered with the probe held
    files = {
        "pkg/a.py": """
            def probe(gate, work):
                gate.enter()
                try:
                    work()
                except GateClosed:
                    return None
                except BaseException:
                    gate.leave()
                    raise
                gate.leave()
        """,
    }
    base = {"name": "probe", "acquire": ["Gate.enter"],
            "release": ["Gate.leave"], "handleless": True}
    res = lint_pkg(
        tmp_path, "resource-discipline", files=files,
        config={"resource_pairs": [
            dict(base, acquire_raises=["GateClosed"])]})
    assert res.new == []
    res = lint_pkg(
        tmp_path, "resource-discipline",
        config={"resource_pairs": [dict(base)]})
    assert len(res.new) == 1  # without the declaration the arm leaks


def test_resource_discipline_pragma_on_acquire_line(tmp_path):
    res = lint_pkg(tmp_path, "resource-discipline", files={
        "pkg/a.py": """
            def leaky(pool, work, n):
                h = pool.alloc(n)  # graft-lint: disable=resource-discipline
                work(h)
                pool.free(h)
        """,
    }, config=RD_CONFIG)
    assert res.new == []


# ---------------------------------------------------------------------------
# shipped-tree contract/config cross-pins
# ---------------------------------------------------------------------------

def test_default_config_declares_serving_contracts_and_pairs():
    from tools.lint.engine import DEFAULT_CONFIG
    contracts = DEFAULT_CONFIG["exception_contracts"]
    assert "Router.submit" in contracts["paddle_tpu/serving/router.py"]
    assert "Engine.submit" in contracts["paddle_tpu/serving/engine.py"]
    assert "TrainingSupervisor.run" in \
        contracts["paddle_tpu/resilience/trainer.py"]
    assert any(spec.startswith("_srv_") for spec in
               contracts["paddle_tpu/distributed/ps_service.py"])
    pairs = {p["name"]: p for p in DEFAULT_CONFIG["resource_pairs"]}
    assert {"kv-pages", "sched-pending", "breaker-probe"} <= set(pairs)
    assert pairs["breaker-probe"].get("handleless") is True
    assert "_locked" in DEFAULT_CONFIG["resource_caller_owns_suffixes"]


def test_router_contract_types_are_status_mapped():
    # MIGRATING "Failure-surface invariants": every type the lint
    # contract allows out of Router.submit must map to an honest status
    # through http._STATUS_MAP (or its DeadlineExceeded special case),
    # never fall through to the generic 500
    from tools.lint.engine import DEFAULT_CONFIG
    from paddle_tpu.serving import http as hs
    from paddle_tpu.serving.engine import EngineStopped
    from paddle_tpu.serving.router import NoHealthyReplica
    from paddle_tpu.serving.scheduler import QueueFull
    from paddle_tpu.resilience.policy import DeadlineExceeded
    from paddle_tpu.distributed.rpc import RpcTransportError

    ns = {"QueueFull": QueueFull, "DeadlineExceeded": DeadlineExceeded,
          "EngineStopped": EngineStopped,
          "NoHealthyReplica": NoHealthyReplica,
          "ConnectionError": ConnectionError, "ValueError": ValueError,
          # ISSUE 20: a fleet worker dying before admission
          "RpcTransportError": RpcTransportError}
    allowed = DEFAULT_CONFIG["exception_contracts"][
        "paddle_tpu/serving/router.py"]["Router.submit"]
    assert set(allowed) == set(ns)
    for name in allowed:
        assert hs.status_for(ns[name]("x")) != 500, name


# ---------------------------------------------------------------------------
# blocking-under-lock (ISSUE 19, graft-lint 5.0)
# ---------------------------------------------------------------------------

BUL_HEAD = """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition()
            self.jobs = None

        def start(self):
            threading.Thread(target=self._loop, daemon=True).start()

    """


def test_blocking_under_lock_queue_wait_in_critical_section(tmp_path):
    res = lint_pkg(tmp_path, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        with self._lock:
            item = self.jobs.get()
    """.replace("\n    ", "\n        "),
    })
    assert len(res.new) == 1
    msg = res.new[0].message
    assert "unbounded queue 'self.jobs.get'" in msg
    assert "while holding" in msg and "_lock" in msg
    # the witness narrative ends at the blocking site
    assert res.new[0].related[-1]["message"].startswith("blocks: queue")


def test_blocking_under_lock_propagates_through_call_edge(tmp_path):
    # the lock is taken in the root, the block happens in a callee: the
    # per-call-site held set carries across the edge, and the witness
    # chain names both hops
    res = lint_pkg(tmp_path, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        with self._lock:
            self._pull()

    def _pull(self):
        return self.jobs.get()
    """.replace("\n    ", "\n        "),
    })
    assert len(res.new) == 1
    assert "Worker._pull" in res.new[0].message
    assert "Worker._loop" in res.new[0].message


def test_blocking_under_lock_snapshot_then_block_is_clean(tmp_path):
    # the sanctioned fix: snapshot under the lock, block after releasing
    # — and a bounded sleep under a lock is the poll-jitter idiom, exempt
    res = lint_pkg(tmp_path, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        import time
        with self._lock:
            jobs = self.jobs
        item = jobs.get()
        with self._lock:
            time.sleep(0.01)
    """.replace("\n    ", "\n        "),
    })
    assert res.new == []


def test_blocking_under_lock_condition_wait_releases_own_lock(tmp_path):
    # Condition.wait RELEASES the condition's lock while waiting: waiting
    # under only the condition itself is clean, waiting while ALSO
    # holding an unrelated lock still fires
    clean = lint_pkg(tmp_path, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        with self._cond:
            self._cond.wait()
    """.replace("\n    ", "\n        "),
    })
    assert clean.new == []
    tmp2 = tmp_path / "dirty"
    tmp2.mkdir()
    dirty = lint_pkg(tmp2, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        with self._lock:
            with self._cond:
                self._cond.wait()
    """.replace("\n    ", "\n        "),
    })
    assert len(dirty.new) == 1
    assert "_lock" in dirty.new[0].message


def test_blocking_under_lock_locked_suffix_caller_holds(tmp_path):
    # a *_locked helper blocking with NO resolvable lock on the chain:
    # the convention says the caller holds one — still a finding, with
    # the synthetic marker instead of a lock id
    res = lint_pkg(tmp_path, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        self._flush_locked()

    def _flush_locked(self):
        return self.jobs.get()
    """.replace("\n    ", "\n        "),
    })
    assert len(res.new) == 1
    assert "<caller-held lock>" in res.new[0].message


def test_blocking_under_lock_pragma_suppresses(tmp_path):
    res = lint_pkg(tmp_path, "blocking-under-lock", {
        "pkg/w.py": BUL_HEAD + """\
    def _loop(self):
        with self._lock:
            item = self.jobs.get()  # graft-lint: disable=blocking-under-lock
    """.replace("\n    ", "\n        "),
    })
    assert res.new == []


# ---------------------------------------------------------------------------
# unbounded-wait (ISSUE 19, graft-lint 5.0)
# ---------------------------------------------------------------------------

UW_CFG = {"bounded_wait_paths": ["pkg/srv"],
          "bounded_wait_roots": {"pkg/srv/loop.py": ["Pump._poll_loop"]}}

UW_HEAD = """\
    import queue

    class Pump:
        def __init__(self):
            self.jobs = queue.Queue()

    """


def test_unbounded_wait_untimed_queue_get(tmp_path):
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/srv/loop.py": UW_HEAD + """\
    def _poll_loop(self):
        while True:
            item = self.jobs.get()
    """.replace("\n    ", "\n        "),
    }, config=UW_CFG)
    assert len(res.new) == 1
    msg = res.new[0].message
    assert "unbounded queue 'self.jobs.get'" in msg
    assert "poll thread" in msg and "Pump._poll_loop" in msg
    assert res.new[0].related[-1]["message"].startswith("waits: queue")


def test_unbounded_wait_env_float_timeout_is_bounded(tmp_path):
    # a computed timeout — env_float(...) directly or through a local —
    # is the author stating a bound; both forms pass
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/srv/loop.py": UW_HEAD + """\
    def _poll_loop(self):
        t = env_float("PUMP_POLL_S", 0.5)
        while True:
            a = self.jobs.get(timeout=t)
            b = self.jobs.get(timeout=env_float("PUMP_POLL_S", 0.5))
    """.replace("\n    ", "\n        "),
    }, config=UW_CFG)
    assert res.new == []


def test_unbounded_wait_none_default_timeout_is_unbounded(tmp_path):
    # a timeout threaded through a parameter whose default is None is
    # unbounded in the worst case — exactly the Engine.stop bug shape
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/srv/loop.py": UW_HEAD + """\
    def _poll_loop(self, timeout=None):
        item = self.jobs.get(timeout=timeout)
    """.replace("\n    ", "\n        "),
    }, config=UW_CFG)
    assert len(res.new) == 1


def test_unbounded_wait_deadline_scope_bounds_lexically(tmp_path):
    # an untimed wait under `with deadline_scope(...)` rides the ambient
    # deadline — the resilience-sanctioned alternative to a timeout arg
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/srv/loop.py": UW_HEAD + """\
    def _poll_loop(self):
        with deadline_scope(2.0):
            item = self.jobs.get()
    """.replace("\n    ", "\n        "),
    }, config=UW_CFG)
    assert res.new == []


def test_unbounded_wait_only_fires_inside_strict_paths(tmp_path):
    # the same untimed wait OUTSIDE bounded_wait_paths (a CLI launcher
    # may wait on its child forever) is out of scope
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/cli/loop.py": UW_HEAD + """\
    def _poll_loop(self):
        item = self.jobs.get()
    """.replace("\n    ", "\n        "),
    }, config={"bounded_wait_paths": ["pkg/srv"],
               "bounded_wait_roots": {"pkg/cli/loop.py":
                                      ["Pump._poll_loop"]}})
    assert res.new == []


def test_unbounded_wait_exception_contract_entries_are_roots(tmp_path):
    # the declared failure surface doubles as the root set: an entry
    # point from exception_contracts reaches the untimed wait
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/srv/door.py": UW_HEAD + """\
    def handle(self, req):
        return self.jobs.get()
    """.replace("\n    ", "\n        "),
    }, config={"bounded_wait_paths": ["pkg/srv"],
               "exception_contracts": {"pkg/srv/door.py":
                                       {"Pump.handle": ["ValueError"]}}})
    assert len(res.new) == 1
    assert "entry" in res.new[0].message


def test_unbounded_wait_pragma_suppresses(tmp_path):
    res = lint_pkg(tmp_path, "unbounded-wait", {
        "pkg/srv/loop.py": UW_HEAD + """\
    def _poll_loop(self):
        item = self.jobs.get()  # graft-lint: disable=unbounded-wait
    """.replace("\n    ", "\n        "),
    }, config=UW_CFG)
    assert res.new == []


# ---------------------------------------------------------------------------
# hot-path-stall (ISSUE 19, graft-lint 5.0)
# ---------------------------------------------------------------------------

HPS_CFG = {"fast_path_roots": ["pkg/hot.py::dispatch"]}


def test_hot_path_stall_sleep_through_helper(tmp_path):
    res = lint_pkg(tmp_path, "hot-path-stall", {
        "pkg/hot.py": """\
            import time

            def dispatch(x):
                return _helper(x)

            def _helper(x):
                time.sleep(0.01)
                return x
            """,
    }, config=HPS_CFG)
    assert len(res.new) == 1
    msg = res.new[0].message
    assert "sleep 'time.sleep'" in msg and "dispatch fast path" in msg
    assert res.new[0].related[-1]["message"].startswith("stalls:")


def test_hot_path_stall_contended_lock_only(tmp_path):
    # a lock acquired by a SECOND function is contended — dispatch can
    # queue behind it; the same acquisition with no other holder is not
    contended = {
        "pkg/hot.py": """\
            import threading

            _LOCK = threading.Lock()

            def dispatch(x):
                with _LOCK:
                    return x

            def other():
                with _LOCK:
                    return 1
            """,
    }
    res = lint_pkg(tmp_path, "hot-path-stall", contended, config=HPS_CFG)
    assert len(res.new) == 1
    assert "contended lock 'pkg.hot._LOCK'" in res.new[0].message
    # sole holder: not contended, clean
    tmp2 = tmp_path / "sole"
    tmp2.mkdir()
    sole = dict(contended)
    sole["pkg/hot.py"] = contended["pkg/hot.py"].replace(
        "def other():\n                with _LOCK:\n                    "
        "return 1", "def other():\n                return 1")
    assert lint_pkg(tmp2, "hot-path-stall", sole, config=HPS_CFG).new == []


def test_hot_path_stall_lock_exempt_list(tmp_path):
    # the reviewed short-critical-section locks stay allowed on the fast
    # path via hot_path_lock_exempt
    res = lint_pkg(tmp_path, "hot-path-stall", {
        "pkg/hot.py": """\
            import threading

            _LOCK = threading.Lock()

            def dispatch(x):
                with _LOCK:
                    return x

            def other():
                with _LOCK:
                    return 1
            """,
    }, config=dict(HPS_CFG, hot_path_lock_exempt=["pkg.hot._LOCK"]))
    assert res.new == []


def test_hot_path_stall_warmup_chain_exempts_jit(tmp_path):
    # deliberate pre-compilation through a *warmup* hop is the point;
    # the same jax.jit on a plain dispatch chain is a compile stall
    res = lint_pkg(tmp_path, "hot-path-stall", {
        "pkg/hot.py": """\
            import jax

            def dispatch(x):
                _warmup(x)
                return _compile(x)

            def _warmup(x):
                return jax.jit(x)

            def _compile(x):
                return jax.jit(x)
            """,
    }, config=HPS_CFG)
    assert len(res.new) == 1
    assert "_compile" in res.new[0].message
    assert "jit-compile" in res.new[0].message


def test_hot_path_stall_shipped_config_membership():
    # the exemption list covers exactly the reviewed program-cache /
    # bookkeeping locks, and the strict wait tier covers the serving +
    # supervisor surfaces (MIGRATING, "Latency invariants")
    from tools.lint.engine import DEFAULT_CONFIG
    exempt = DEFAULT_CONFIG["hot_path_lock_exempt"]
    assert "paddle_tpu.core.dispatch_cache._LOCK" in exempt
    assert "paddle_tpu.core.fallback._LOCK" in exempt
    bw = DEFAULT_CONFIG["bounded_wait_paths"]
    assert "paddle_tpu/serving" in bw
    assert "paddle_tpu/resilience/watchdog.py" in bw
    assert "paddle_tpu/distributed/ps_service.py" in bw
    roots = DEFAULT_CONFIG["bounded_wait_roots"]
    assert "Router._poll_loop" in roots["paddle_tpu/serving/router.py"]
    assert "StepWatchdog._loop" in \
        roots["paddle_tpu/resilience/watchdog.py"]


# ---------------------------------------------------------------------------
# may-block summaries on the shipped tree (ISSUE 19)
# ---------------------------------------------------------------------------

def test_serving_blocking_events_are_well_formed():
    """Every may-block event harvested over paddle_tpu/serving/ is
    orphan-free: a pinned 7-slot shape, a registered kind, a real line,
    and lock refs that are themselves well-formed ref tuples — the three
    blocking rules consume these fields blindly."""
    import ast

    from tools.lint.engine import (DEFAULT_CONFIG, REPO_ROOT,
                                   iter_python_files)
    from tools.lint.wholeprogram.summary import (BLOCKING_KINDS,
                                                 build_summary)

    total = 0
    for abspath in iter_python_files(["paddle_tpu/serving"]):
        rel = os.path.relpath(abspath, REPO_ROOT).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as fh:
            src = fh.read()
        summary = build_summary(rel, ast.parse(src), src.splitlines(),
                                DEFAULT_CONFIG)
        for fi in summary.functions:
            for ev in fi.blocking:
                kind, detail, bounded, ds, lrs, recv, line = ev
                total += 1
                assert kind in BLOCKING_KINDS, (rel, fi.qualname, ev)
                assert detail and isinstance(detail, str)
                assert bounded in (0, 1, True, False)
                assert ds in (0, 1, True, False)
                assert isinstance(line, int) and line > 0
                for lr in lrs:
                    assert lr and all(isinstance(p, str) for p in lr)
                if recv is not None:
                    assert all(isinstance(p, str) for p in recv)
        # and the events survive the cache round-trip bit-for-bit
        again = type(summary).from_dict(summary.to_dict())
        assert [fi.blocking for fi in again.functions] == \
            [fi.blocking for fi in summary.functions]
    # the serving tier genuinely waits — an empty harvest means the
    # scanner regressed, not that serving went lock-free
    assert total >= 10


# ---------------------------------------------------------------------------
# --jobs: parallel cold pass (ISSUE 19)
# ---------------------------------------------------------------------------

JOBS_FILES = {
    "pkg/a.py": """\
        import threading

        _LOCK = threading.Lock()

        class Worker:
            def __init__(self):
                self.jobs = None

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with _LOCK:
                    return self.jobs.get()
        """,
    "pkg/b.py": """\
        try:
            import fancy
        except Exception:
            pass
        """,
    "pkg/c.py": """\
        def use():
            with _LOCK:
                return 1

        from pkg.a import _LOCK
        """,
}


def test_jobs_parallel_cold_run_is_byte_identical(tmp_path):
    # the determinism pin: same tree, cold, jobs=1 vs jobs=2 — identical
    # findings (order included), identical scan bookkeeping
    write_pkg(tmp_path, JOBS_FILES)
    serial = run_lint(paths=["."], root=str(tmp_path))
    par = run_lint(paths=["."], root=str(tmp_path), jobs=2)
    assert [f.as_dict() for f in par.new] == \
        [f.as_dict() for f in serial.new]
    assert par.new != []          # the fixture does produce findings
    assert par.scanned == serial.scanned
    assert par.errors == serial.errors
    assert par.parsed_files == serial.parsed_files > 0


def test_jobs_parallel_populates_cache_for_serial_warm_run(tmp_path):
    # a parallel cold run must leave the SAME cache a serial run would:
    # the following serial warm run parses nothing and reports equal
    # findings
    write_pkg(tmp_path, JOBS_FILES)
    cache = tmp_path / "cache.json"
    cold = run_lint(paths=["."], root=str(tmp_path),
                    cache_path=str(cache), jobs=2)
    warm = run_lint(paths=["."], root=str(tmp_path),
                    cache_path=str(cache))
    assert warm.parsed_files == 0
    assert warm.findings_cache_hits == warm.total_files
    assert [f.as_dict() for f in warm.new] == \
        [f.as_dict() for f in cold.new]


def test_jobs_warm_path_is_untouched(tmp_path):
    # with a hot cache, --jobs must not spin up workers or re-parse:
    # the warm run with jobs=4 behaves exactly like the serial warm run
    write_pkg(tmp_path, JOBS_FILES)
    cache = tmp_path / "cache.json"
    run_lint(paths=["."], root=str(tmp_path), cache_path=str(cache))
    warm = run_lint(paths=["."], root=str(tmp_path),
                    cache_path=str(cache), jobs=4)
    assert warm.parsed_files == 0
    assert warm.findings_cache_hits == warm.total_files
    assert warm.summary_cache_hits == warm.total_files


def test_jobs_syntax_error_reported_identically(tmp_path):
    # a worker hitting a SyntaxError must surface the same error row the
    # serial path would, not crash the pool
    write_pkg(tmp_path, dict(JOBS_FILES, **{
        "pkg/broken.py": "def oops(:\n    pass\n"}))
    serial = run_lint(paths=["."], root=str(tmp_path))
    par = run_lint(paths=["."], root=str(tmp_path), jobs=2)
    assert par.errors == serial.errors
    assert len(par.errors) == 1 and "broken.py" in par.errors[0]
    assert [f.as_dict() for f in par.new] == \
        [f.as_dict() for f in serial.new]
