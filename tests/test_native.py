"""Native C++ runtime components: TCPStore, BlockingQueue, host tracer.

Covers the reference's native seams (SURVEY.md §2.1 BlockingQueue feed,
§2.3 TCPStore rendezvous, §5 HostTracer) on our C++ implementations, plus the
pure-Python protocol fallback and native<->Python interop.
"""

import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu import _native
from paddle_tpu.distributed.store import TCPStore


def test_native_builds():
    # g++ is a baked-in toolchain dependency; the native library must build.
    assert _native.available(), _native.build_error()


# ---------------------------------------------------------------------------
# TCPStore
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("use_native", [True, False])
def test_tcp_store_basic(use_native):
    if use_native and not _native.available():
        pytest.skip("native unavailable")
    master = TCPStore(is_master=True, world_size=1, use_native=use_native)
    try:
        master.set("alpha", b"hello")
        assert master.get("alpha") == b"hello"
        assert master.check("alpha")
        assert not master.check("missing")
        assert master.add("counter", 3) == 3
        assert master.add("counter", -1) == 2
        assert master.num_keys() == 2
        assert master.delete_key("alpha")
        assert not master.check("alpha")
        with pytest.raises(TimeoutError):
            master.get("missing", timeout=0.1)
    finally:
        master.close()


@pytest.mark.parametrize("server_native,client_native", [
    (True, False), (False, True)])
def test_tcp_store_interop(server_native, client_native):
    """The C++ and Python ends speak the same wire protocol."""
    if not _native.available():
        pytest.skip("native unavailable")
    master = TCPStore(is_master=True, world_size=2, use_native=server_native)
    try:
        peer = TCPStore("127.0.0.1", master.port, world_size=2,
                        use_native=client_native)
        peer.set("from_peer", b"\x00\x01binary\xff")
        assert master.get("from_peer") == b"\x00\x01binary\xff"
        assert master.add("n", 5) == 5
        assert peer.add("n", 5) == 10
        peer.close()
    finally:
        master.close()


def test_tcp_store_wait_blocks_until_set():
    master = TCPStore(is_master=True, world_size=1)
    try:
        result = {}

        def setter():
            time.sleep(0.2)
            other = TCPStore("127.0.0.1", master.port)
            other.set("late", b"now")
            other.close()

        t = threading.Thread(target=setter)
        t.start()
        t0 = time.monotonic()
        master.wait("late", timeout=5)
        result["elapsed"] = time.monotonic() - t0
        t.join()
        assert master.get("late") == b"now"
        assert result["elapsed"] >= 0.1
    finally:
        master.close()


@pytest.mark.slow
def test_tcp_store_cross_process():
    """A subprocess client rendezvouses through the in-process server."""
    master = TCPStore(is_master=True, world_size=2)
    try:
        code = (
            "from paddle_tpu.distributed.store import TCPStore\n"
            f"s = TCPStore('127.0.0.1', {master.port}, world_size=2)\n"
            "s.set('child_key', b'from-child')\n"
            "assert s.get('parent_key', timeout=60) == b'from-parent'\n"
            "s.add('rendezvous', 1)\n"
            "s.close()\n"
        )
        proc = subprocess.Popen([sys.executable, "-c", code])
        master.set("parent_key", b"from-parent")
        # generous timeouts: the child pays the full interpreter + jax
        # plugin import cost, which can exceed 10s under suite load
        assert master.get("child_key", timeout=60) == b"from-child"
        master.wait("rendezvous", timeout=60)
        assert proc.wait(timeout=60) == 0
    finally:
        master.close()


def test_tcp_store_barrier():
    master = TCPStore(is_master=True, world_size=3)
    try:
        peers = [TCPStore("127.0.0.1", master.port, world_size=3)
                 for _ in range(2)]
        done = []

        def arrive(store, delay):
            time.sleep(delay)
            store.barrier("b0", timeout=10)
            done.append(time.monotonic())

        threads = [threading.Thread(target=arrive, args=(s, d))
                   for s, d in zip(peers, (0.05, 0.15))]
        for t in threads:
            t.start()
        arrive(master, 0.0)
        for t in threads:
            t.join()
        assert len(done) == 3
        # nobody passes the barrier before the last arrival (~0.15s)
        assert max(done) - min(done) < 0.5
        for s in peers:
            s.close()
    finally:
        master.close()


def test_tcp_store_barrier_reusable():
    """The same barrier name must synchronize again on a second round."""
    master = TCPStore(is_master=True, world_size=2)
    try:
        peer = TCPStore("127.0.0.1", master.port, world_size=2)
        order = []

        def worker():
            peer.barrier("r", timeout=10)
            time.sleep(0.2)
            order.append("peer-before-2nd")
            peer.barrier("r", timeout=10)

        t = threading.Thread(target=worker)
        t.start()
        master.barrier("r", timeout=10)
        master.barrier("r", timeout=10)  # must block until peer's 2nd arrival
        order.append("master-after-2nd")
        t.join()
        assert order == ["peer-before-2nd", "master-after-2nd"]
        peer.close()
    finally:
        master.close()


def test_tcp_store_concurrent_get():
    master = TCPStore(is_master=True, world_size=1)
    try:
        payloads = {f"k{i}": bytes([i]) * (100 + i) for i in range(8)}
        for k, v in payloads.items():
            master.set(k, v)
        results, errs = {}, []

        def getter(k):
            try:
                for _ in range(50):
                    assert master.get(k) == payloads[k]
                results[k] = True
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=getter, args=(k,)) for k in payloads]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs and len(results) == 8
    finally:
        master.close()


# ---------------------------------------------------------------------------
# BlockingQueue
# ---------------------------------------------------------------------------
def test_blocking_queue_fifo_and_backpressure():
    if not _native.available():
        pytest.skip("native unavailable")
    q = _native.BlockingQueue(2)
    assert q.push({"i": 0}) and q.push({"i": 1})
    assert not q.push({"i": 2}, timeout=0.05)  # full -> timeout
    assert q.pop()["i"] == 0
    assert q.push({"i": 2}, timeout=1.0)
    assert [q.pop()["i"] for _ in range(2)] == [1, 2]
    assert q.pop(timeout=0.05) is _native.BlockingQueue.TIMEOUT
    q.close()
    assert q.pop() is _native.BlockingQueue.CLOSED
    assert not q.push({"i": 9})


def test_blocking_queue_producer_consumer():
    if not _native.available():
        pytest.skip("native unavailable")
    q = _native.BlockingQueue(4)
    n = 200

    def producer():
        for i in range(n):
            q.push(i)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    got = []
    while True:
        item = q.pop()
        if item is _native.BlockingQueue.CLOSED:
            break
        got.append(item)
    t.join()
    assert got == list(range(n))


def test_dataloader_uses_native_queue():
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, TensorDataset

    xs = np.arange(32, dtype=np.float32).reshape(16, 2)
    ds = TensorDataset([paddle.to_tensor(xs)])
    loader = DataLoader(ds, batch_size=4, shuffle=False)
    batches = [b[0].numpy() for b in loader]
    assert len(batches) == 4
    np.testing.assert_allclose(np.concatenate(batches), xs)


# ---------------------------------------------------------------------------
# host tracer
# ---------------------------------------------------------------------------
def test_native_tracer_roundtrip(tmp_path):
    import paddle_tpu.profiler as profiler

    with profiler.Profiler() as prof:
        with profiler.RecordEvent("stage_a"):
            time.sleep(0.01)
        with profiler.RecordEvent("stage_b"):
            pass
    names = {e["name"] for e in prof.events()}
    assert {"stage_a", "stage_b"} <= names
    a = next(e for e in prof.events() if e["name"] == "stage_a")
    assert a["dur"] >= 0.005
    assert a["type"] == "UserDefined"
    out = tmp_path / "trace.json"
    prof.export_chrome_tracing(str(out))
    data = profiler.load_profiler_result(str(out))
    assert any(e["name"] == "stage_a" for e in data["traceEvents"])


def test_tracer_names_with_special_chars():
    """Quotes/backslashes/non-ASCII in range names must survive the native
    JSON dump (escaping regression)."""
    import paddle_tpu.profiler as profiler

    tricky = ['load "train" shard', "back\\slash", "日本語レンジ", "ctl\x01chr"]
    with profiler.Profiler() as prof:
        for name in tricky:
            with profiler.RecordEvent(name):
                pass
    assert len(prof.events()) >= len(tricky)
    names = {e["name"] for e in prof.events()}
    assert any("train" in n for n in names)
