"""On-chip smoke tier: one fast test per op family, generated from ops.yaml.

All 800+ default-tier tests pin ``jax_platforms=cpu`` (conftest), which is
exactly how the real chip's missing lowerings survived five review rounds
(ROADMAP item 2). This module is the transfer proof: every op FAMILY in the
manifest (``paddle_tpu/ops/ops.yaml``) gets one tiny, fast invocation that
runs UNPINNED on real hardware —

    PADDLE_TPU_TIER=1 python -m pytest tests -m tpu

— and skips cleanly on CPU hosts (conftest adds the skip when no
accelerator is present). Green here means green CI finally says something
about the device the framework is named for; an op with no TPU lowering
degrades through the backend-fallback path (core/fallback.py) with a
warning instead of failing the tier.

Rot protection: the family list is DERIVED from ops.yaml at collection
time, and ``test_smoke_covers_every_family`` (unmarked — it runs in
tier-1, on CPU) fails the moment a new op lands in a family with no smoke
entry. Adding an op to an existing family costs nothing; adding a new
family means writing one ~3-line smoke fn here.
"""

import os
import re

import numpy as np
import pytest

OPS_YAML = os.path.join(os.path.dirname(__file__), os.pardir,
                        "paddle_tpu", "ops", "ops.yaml")


def _load_ops():
    """[{op, module (last segment), arity}] — tiny line parser so the test
    does not depend on a yaml library."""
    ops, cur = [], None
    with open(OPS_YAML) as f:
        for line in f:
            line = line.rstrip()
            if line.startswith("- op: "):
                cur = {"op": line[6:].strip(), "module": "?", "arity": 0}
                ops.append(cur)
            elif cur is not None and line.startswith("  module: "):
                cur["module"] = line[10:].strip().rsplit(".", 1)[-1]
            elif cur is not None and line.startswith("  args: "):
                sig = line[8:].strip().strip('"').strip("()")
                n = 0
                for part in sig.split(","):
                    part = part.strip()
                    if not part or "=" in part:
                        break
                    n += 1
                cur["arity"] = n
    return ops


# name-pattern rules run first (ordered); then the module map; _helpers
# splits by arity. Coarse on purpose: a family is "ops that exercise the
# same lowering surface", not a taxonomy.
_NAME_RULES = (
    (re.compile(r"conv"), "conv"),
    (re.compile(r"pool"), "pool"),
    (re.compile(r"dropout"), "dropout"),
    (re.compile(r"(_norm$|^normalize$)"), "norm"),
    (re.compile(r"embedding"), "embedding"),
    (re.compile(r"(attention|^softmax_mask_fuse)"), "attention"),
    (re.compile(r"(loss|entropy|_cost$)"), "loss"),
    (re.compile(r"^segment_"), "segment"),
    (re.compile(r"^(as_strided|strides|is_contiguous|view_as|view|unfold)$"),
     "strided"),
    (re.compile(r"^(bernoulli_|standard_gamma|top_p_sampling|binomial|"
                r"log_normal|cauchy_|geometric_)"), "sampling"),
)

_MODULE_FAMILIES = {
    "activation": "activation",
    "array": "tensor_array",
    "conv_pool": "resample",       # leftovers: interpolate/upsample/shuffle
    "creation": "creation",
    "flash_attention": "attention",
    "geometric": "segment",
    "indexing": "indexing",
    "linalg": "linalg",
    "loss_ops": "loss",
    "manipulation": "manipulation",
    "math": "math",
    "math_ext": "math_ext",
    "math_ext2": "math_ext2",
    "math_ext4": "math_ext4",
    "nn_ext": "nn_misc",
    "nn_ops": "nn_misc",
    "quant": "quantization",
    "reduce": "reduce",
}


def family_of(op: str, module: str, arity: int) -> str:
    for pat, fam in _NAME_RULES:
        if pat.search(op):
            return fam
    if module == "_helpers":
        return "elementwise_unary" if arity <= 1 else "elementwise_binary"
    return _MODULE_FAMILIES.get(module, module)


_OPS = _load_ops()
# + synthetic families for compiled SUBSYSTEM paths that no single ops.yaml
# entry covers: the serving engine's paged gather->step->scatter decode
# program is its own lowering surface (dynamic_slice/scatter over the page
# pool fused with the decode step), the online-shutdown contract
# (stop(drain=True) against a live step loop) exercises the compiled path
# from a background thread — host-sync + device-buffer lifetime behavior
# the offline run() drain cannot see — and the paged-attention Pallas
# decode kernel (ISSUE 13) has its own Mosaic lowering (scalar-prefetch
# page streaming + in-kernel int8 dequant) that only a real chip compiles
FAMILIES = sorted({family_of(o["op"], o["module"], o["arity"])
                   for o in _OPS}
                  | {"serving_decode", "serving_drain", "paged_attention"})


def _t(data, dtype="float32", stop_gradient=True):
    import paddle_tpu as paddle
    return paddle.to_tensor(np.asarray(data, dtype=dtype),
                            stop_gradient=stop_gradient)


def _rand(*shape):
    return np.random.default_rng(0).standard_normal(shape).astype("float32")


# One tiny invocation per family. Keep each under a second of compile on
# the chip: smallest shapes that still hit the family's real lowering.
def _smoke_activation():
    import paddle_tpu as paddle
    out = paddle.nn.functional.gelu(_t(_rand(4, 8))).numpy()
    assert out.shape == (4, 8) and np.isfinite(out).all()


def _smoke_attention():
    import paddle_tpu as paddle
    q = _t(_rand(1, 4, 2, 8))
    out = paddle.nn.functional.scaled_dot_product_attention(q, q, q)
    assert out.numpy().shape == (1, 4, 2, 8)


def _smoke_conv():
    import paddle_tpu as paddle
    out = paddle.nn.functional.conv2d(_t(_rand(1, 3, 8, 8)),
                                      _t(_rand(4, 3, 3, 3)))
    assert out.numpy().shape == (1, 4, 6, 6)


def _smoke_creation():
    import paddle_tpu as paddle
    out = paddle.full([2, 3], 7.0).numpy()
    np.testing.assert_allclose(out, np.full((2, 3), 7.0))


def _smoke_dropout():
    import paddle_tpu as paddle
    x = _t(_rand(4, 4))
    out = paddle.nn.functional.dropout(x, p=0.5, training=False).numpy()
    np.testing.assert_allclose(out, x.numpy())


def _smoke_elementwise_binary():
    import paddle_tpu as paddle
    a, b = _rand(3, 4), _rand(3, 4)
    np.testing.assert_allclose(paddle.add(_t(a), _t(b)).numpy(), a + b,
                               rtol=1e-6)


def _smoke_elementwise_unary():
    import paddle_tpu as paddle
    a = np.abs(_rand(3, 4)) + 0.1
    np.testing.assert_allclose(paddle.sqrt(_t(a)).numpy(), np.sqrt(a),
                               rtol=1e-6)


def _smoke_embedding():
    import paddle_tpu as paddle
    out = paddle.nn.functional.embedding(
        _t([[0, 2], [1, 3]], dtype="int64"), _t(_rand(8, 5)))
    assert out.numpy().shape == (2, 2, 5)


def _smoke_indexing():
    import paddle_tpu as paddle
    a = _rand(5, 3)
    out = paddle.index_select(_t(a), _t([0, 3], dtype="int64")).numpy()
    np.testing.assert_allclose(out, a[[0, 3]])


def _smoke_linalg():
    import paddle_tpu as paddle
    a, b = _rand(4, 3), _rand(3, 5)
    np.testing.assert_allclose(paddle.matmul(_t(a), _t(b)).numpy(), a @ b,
                               rtol=1e-4, atol=1e-5)


def _smoke_loss():
    import paddle_tpu as paddle
    out = paddle.nn.functional.mse_loss(_t(_rand(4, 2)), _t(_rand(4, 2)))
    assert np.isfinite(out.numpy()).all()


def _smoke_manipulation():
    import paddle_tpu as paddle
    a = _rand(2, 6)
    out = paddle.transpose(paddle.reshape(_t(a), [3, 4]), [1, 0]).numpy()
    np.testing.assert_allclose(out, a.reshape(3, 4).T)


def _smoke_math():
    import paddle_tpu as paddle
    a = _rand(3, 3)
    np.testing.assert_allclose(paddle.clip(_t(a), -0.5, 0.5).numpy(),
                               np.clip(a, -0.5, 0.5))


def _smoke_math_ext():
    import paddle_tpu as paddle
    out = paddle.cdist(_t(_rand(4, 3)), _t(_rand(5, 3))).numpy()
    assert out.shape == (4, 5) and (out >= 0).all()


def _smoke_math_ext2():
    import paddle_tpu as paddle
    a, b = _rand(2, 2), _rand(2, 2)
    out = paddle.block_diag(_t(a), _t(b)).numpy()
    assert out.shape == (4, 4) and np.allclose(out[:2, :2], a)


def _smoke_math_ext4():
    import paddle_tpu as paddle
    a, b = _rand(3, 2), _rand(3, 2)
    np.testing.assert_allclose(paddle.add_n([_t(a), _t(b)]).numpy(), a + b,
                               rtol=1e-6)


def _smoke_nn_misc():
    import paddle_tpu as paddle
    out = paddle.nn.functional.linear(_t(_rand(4, 3)), _t(_rand(3, 5)))
    assert out.numpy().shape == (4, 5)


def _smoke_norm():
    import paddle_tpu as paddle
    out = paddle.nn.functional.layer_norm(
        _t(_rand(4, 8)), 8, weight=_t(np.ones(8)), bias=_t(np.zeros(8)))
    assert abs(float(out.numpy().mean())) < 1e-3


def _smoke_pool():
    import paddle_tpu as paddle
    out = paddle.nn.functional.max_pool2d(_t(_rand(1, 2, 8, 8)),
                                          kernel_size=2)
    assert out.numpy().shape == (1, 2, 4, 4)


def _smoke_quantization():
    import paddle_tpu as paddle
    w = _t(_rand(8, 4))
    qw, scale = paddle.nn.quant.weight_quantize(w)
    deq = paddle.nn.quant.weight_dequantize(qw, scale).numpy()
    np.testing.assert_allclose(deq, w.numpy(), atol=0.05)


def _smoke_reduce():
    import paddle_tpu as paddle
    a = _rand(3, 4)
    np.testing.assert_allclose(paddle.logsumexp(_t(a)).numpy(),
                               np.log(np.exp(a).sum()), rtol=1e-5)


def _smoke_resample():
    import paddle_tpu as paddle
    out = paddle.nn.functional.pixel_shuffle(_t(_rand(1, 4, 3, 3)), 2)
    assert out.numpy().shape == (1, 1, 6, 6)


def _smoke_sampling():
    import paddle_tpu as paddle
    out = paddle.standard_gamma(_t(np.full((64,), 2.0))).numpy()
    assert out.shape == (64,) and (out >= 0).all()


def _smoke_segment():
    import paddle_tpu as paddle
    out = paddle.geometric.segment_sum(
        _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]),
        _t([0, 0, 1], dtype="int64")).numpy()
    np.testing.assert_allclose(out, [[4.0, 6.0], [5.0, 6.0]])


def _smoke_serving_decode():
    # the serving engine's compiled paged-decode program (gather pages ->
    # step -> scatter written page) on the real chip: 2 requests batched
    # continuously must decode the exact tokens of the dense bs=1 loop
    # over the SAME toy callables
    import jax
    import jax.numpy as jnp
    from paddle_tpu import serving
    from paddle_tpu.core.tensor import Tensor as T

    L = H = 1
    D, M, V = 8, 32, 13
    posw = (jnp.arange(M, dtype=jnp.float32) + 1.0) / M
    ramp = (jnp.arange(D, dtype=jnp.float32) + 1.0) / D

    def readout(c, valid):                   # (B, H, M, D), (B, M) -> (B,)
        s = (c.astype(jnp.float32) * valid[:, None, :, None]
             * posw[None, None, :, None]).sum(axis=(1, 2, 3))
        return (s * 97.0).astype(jnp.int32) % V

    def step(tok, cache, t):
        c, td = cache._data, t._data.astype(jnp.int32)
        kv = ((tok._data[:, 0].astype(jnp.float32) + 1.0) / V)[:, None] * ramp

        def wr(cb, kvb, tb):
            page = jnp.broadcast_to(kvb[None, None, None, None, :],
                                    (L, 2, H, 1, D)).astype(cb.dtype)
            return jax.lax.dynamic_update_slice(cb, page, (0, 0, 0, tb, 0))

        c2 = jax.vmap(wr, in_axes=(2, 0, 0), out_axes=2)(c, kv, td)
        valid = (jnp.arange(M)[None, :] <= td[:, None]).astype(jnp.float32)
        return T(readout(c2[0, 0], valid)[:, None]), T(c2)

    def prefill(ids, cache):
        c, idsd = cache._data, ids._data
        lp = idsd.shape[1]
        kv = ((idsd[0].astype(jnp.float32) + 1.0) / V)[:, None] * ramp
        c = c.at[:, :, 0, :, :lp, :].set(
            jnp.broadcast_to(kv[None, :, :], (H, lp, D)).astype(c.dtype))
        valid = (jnp.arange(M) < lp)[None, :].astype(jnp.float32)
        return T(readout(c[0, 0], valid)[:, None]), T(c)

    prompts = [np.arange(8, dtype=np.int32) % V,
               (np.arange(8, dtype=np.int32) * 3) % V]

    def dense(prompt, n_new):
        cache = T(jnp.zeros((L, 2, 1, H, M, D), jnp.float32))
        tok, cache = prefill(T(jnp.asarray(prompt[None, :], jnp.int32)),
                             cache)
        toks, t = [int(np.asarray(tok._data)[0, 0])], prompt.size
        for _ in range(n_new - 1):
            tok, cache = step(tok, cache, T(jnp.asarray([t], jnp.int32)))
            toks.append(int(np.asarray(tok._data)[0, 0]))
            t += 1
        return toks

    cfg = serving.ServingConfig(num_layers=L, num_heads=H, head_dim=D,
                                max_len=M, max_batch=2, buckets=(1, 2),
                                page_size=8)
    eng = serving.Engine(prefill, step, cfg)
    futs = [eng.submit(serving.GenerationRequest(p, max_new_tokens=4))
            for p in prompts]
    eng.run()
    for p, f in zip(prompts, futs):
        assert f.result(timeout=30).tokens == dense(p, 4)


def _smoke_serving_drain():
    # the online-shutdown contract on the real chip: a live start() loop
    # decoding on-device must stop(drain=True) with every Future resolved,
    # every page back in the pool, and a second stop() a no-op — the
    # graceful-drain path does its compiled steps from the background
    # thread, which is exactly the surface the offline run() drain skips
    import jax.numpy as jnp
    from paddle_tpu import serving
    from paddle_tpu.core.tensor import Tensor as T

    L = H = 1
    D, M, V = 8, 32, 13
    ramp = (jnp.arange(D, dtype=jnp.float32) + 1.0) / D

    def step(tok, cache, t):
        c = cache._data
        nxt = (tok._data[:, 0] * 7 + t._data.astype(jnp.int32)) % V
        kv = ((nxt.astype(jnp.float32) + 1.0) / V)[:, None] * ramp
        c = c + 0.0 * kv.sum()          # touch the cache: keep the gather/
        return T(nxt[:, None].astype(jnp.int32)), T(c)  # scatter leg live

    def prefill(ids, cache):
        nxt = (ids._data.sum(axis=1).astype(jnp.int32)) % V
        return T(nxt[:, None]), T(cache._data)

    cfg = serving.ServingConfig(num_layers=L, num_heads=H, head_dim=D,
                                max_len=M, max_batch=2, buckets=(1, 2),
                                page_size=8, max_queue=8)
    eng = serving.Engine(prefill, step, cfg).warmup()
    prompts = [np.arange(6, dtype=np.int32) % V,
               (np.arange(6, dtype=np.int32) * 5) % V]
    eng.start()
    import threading
    admitted = threading.Event()
    first = set()

    def on_tok(rid, _tok):
        first.add(rid)
        if len(first) >= len(prompts):
            admitted.set()

    futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=6, stream=on_tok)) for p in prompts]
    # drain finishes IN-FLIGHT work only (queued requests resolve
    # EngineStopped): wait for both to hold slots before shutting down
    assert admitted.wait(timeout=60)
    eng.stop(drain=True, timeout=60)
    eng.stop(drain=True, timeout=1)      # idempotent
    for f in futs:
        assert f.done()
        res = f.result(timeout=0)
        assert len(res.tokens) == 6 and res.finish_reason == "length"
    assert eng.kv.outstanding_pages == 0
    assert eng.active_requests == 0 and eng.queue_depth == 0


def _smoke_paged_attention():
    # the paged-attention decode kernel COMPILED (not interpreted) on the
    # real chip, pinned against the per-layer dense reference on both kv
    # storage legs — bf16 near-ulp, int8 bit-identical dequant grid
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.serving.kv_cache import quantize_pages

    rng = np.random.default_rng(0)
    B, H, D, ps, S, L = 2, 2, 128, 32, 3, 2
    P = 8
    assert pa.kernel_eligible(ps, D, jnp.bfloat16)
    assert pa.kernel_eligible(ps, D, jnp.int8)
    poolf = jnp.asarray(rng.standard_normal((P, L, 2, H, ps, D)),
                        jnp.float32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    t = jnp.asarray([2 * ps + 5, ps - 1], jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    layer = jnp.asarray(1, jnp.int32)
    q8, sc = quantize_pages(poolf)
    for pool, scales, tol in ((poolf.astype(jnp.bfloat16), None, 2e-2),
                              (q8, sc, 2e-2)):
        got = pa.paged_attention(q, kn, vn, pool, scales, tables, t,
                                 layer, page_size=ps, impl="kernel",
                                 interpret=False)
        want = pa.paged_attention_dense(q, kn, vn, pool, scales, tables,
                                        t, layer, page_size=ps)
        err = float(np.abs(np.asarray(got, np.float32)
                           - np.asarray(want, np.float32)).max())
        assert err <= tol, (pool.dtype, err)


def _smoke_strided():
    import paddle_tpu as paddle
    t = _t(np.arange(12, dtype="float32").reshape(3, 4))
    assert t.strides == [4, 1] and t.is_contiguous()
    out = paddle.as_strided(t, [2, 2], [4, 1]).numpy()
    np.testing.assert_allclose(out, [[0.0, 1.0], [4.0, 5.0]])


def _smoke_tensor_array():
    import paddle_tpu as paddle
    arr = paddle.tensor.create_array("float32")
    i = paddle.zeros([1], dtype="int64")
    paddle.tensor.array_write(_t([1.0, 2.0]), i, arr)
    out = paddle.tensor.array_read(arr, i).numpy()
    np.testing.assert_allclose(out, [1.0, 2.0])


SMOKE = {name[len("_smoke_"):]: fn for name, fn in list(globals().items())
         if name.startswith("_smoke_")}


def test_smoke_covers_every_family():
    """Tier-1 (CPU) rot gate: every family derivable from ops.yaml has a
    smoke entry, and the tier is big enough to mean something."""
    missing = sorted(set(FAMILIES) - set(SMOKE))
    assert not missing, (
        f"op families with no on-chip smoke test: {missing} — add a "
        f"_smoke_<family>() fn to tests/test_tpu_smoke.py")
    assert len(FAMILIES) >= 26, FAMILIES


@pytest.mark.tpu
@pytest.mark.parametrize("family", FAMILIES)
def test_family_smoke(family):
    SMOKE[family]()
