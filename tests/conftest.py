"""Test env: force CPU backend with 8 virtual devices BEFORE backend init.

This is the CPU-backed fake-device pattern from SURVEY.md §4 (the analogue of
the reference's custom_cpu plugin / Gloo backend): the whole distributed stack
runs in CI on an 8-device CPU mesh.

NOTE: this environment pre-imports jax (axon TPU plugin), so plain env vars
are latched already — ``jax.config.update`` still works because the backend
itself initializes lazily on first device query.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import gc

    import paddle_tpu as paddle
    # reference cycles (optimizer accumulator closures, layer graphs) keep
    # dead models in the weakref state registry until a gc pass; collect so
    # one test's mesh-committed state can't leak into the next test's
    # to_static signature
    gc.collect()
    paddle.seed(2024)
    np.random.seed(2024)
    yield
    # the global default Program records ops with strong tensor refs; a
    # test that ran static ops outside a program_guard would otherwise pin
    # its (possibly mesh-committed) tensors into every later test's
    # to_static state signature
    import paddle_tpu.static as _static
    if _static._static_mode:
        paddle.disable_static()
    _static._default_main = _static.Program()
    _static._default_startup = _static.Program()
