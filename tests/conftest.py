"""Test env: force CPU backend with 8 virtual devices BEFORE backend init.

This is the CPU-backed fake-device pattern from SURVEY.md §4 (the analogue of
the reference's custom_cpu plugin / Gloo backend): the whole distributed stack
runs in CI on an 8-device CPU mesh.

NOTE: this environment pre-imports jax (axon TPU plugin), so plain env vars
are latched already — ``jax.config.update`` still works because the backend
itself initializes lazily on first device query.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

# The eager compiled-op cache (core/dispatch_cache.py) trades per-signature
# warmup compiles for steady-state dispatch speed. This suite is
# compile-dominated and repeats most signatures only a handful of times, so
# suite-wide it costs wall clock without reaching steady state; its own
# suite (test_dispatch_cache.py) enables it explicitly, as does the
# eager-dispatch benchmark.
os.environ.setdefault("PADDLE_TPU_EAGER_CACHE", "0")

# Whole-step static capture (ISSUE 11) stays off suite-wide for the same
# wall-clock reason (every supervised/hapi test would compile a whole-step
# program it runs a handful of times) AND because the eager tier's bitwise
# pins are eager-tier claims: a captured step is bitwise-deterministic
# within its own tier but differs from per-op eager at FMA/ulp scale (XLA
# contracts a*x+b*y inside fused kernels). test_step_capture.py opts in
# per-test and pins the captured tier's own invariants.
os.environ.setdefault("PADDLE_TPU_STEP_CAPTURE", "off")

# Program cost accounting (ISSUE 16) captures XLA cost/memory analysis by
# AOT-lowering every fresh executable a second time — once per compile,
# which is exactly what this compile-dominated suite is made of. Off
# suite-wide; test_cost.py opts in per-test, as does the bench row.
os.environ.setdefault("PADDLE_TPU_COST", "off")

import jax  # noqa: E402

# The on-chip smoke tier (`PADDLE_TPU_TIER=1 pytest -m tpu`) must run
# UNPINNED so `-m tpu` tests see the real accelerator; every other
# invocation (tier-1 CI included) pins the CPU backend as before, and the
# `tpu`-marked tests auto-skip below.
_TPU_TIER = os.environ.get("PADDLE_TPU_TIER", "").strip().lower() in (
    "1", "true", "on")
if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")

# The persistent XLA compilation cache used to live at tests/.jax_cache,
# shared across every pytest process that ever ran. On this jaxlib's CPU
# backend that is UNSOUND: a cache accumulated by heterogeneous processes
# can serve an executable for a byte-identical program (same lowered HLO,
# same cache key) that computes garbage in a later process — reproduced
# as wrong greedy tokens from the serving engine's donated decode
# programs and as spuriously COMMITTED state arrays that then broke the
# placement-sensitive step-capture/ZeRO suites, with the outcome
# depending on PYTHONHASHSEED and on which sibling processes wrote the
# cache (ISSUE 13 post-mortem). Cold compiles are always correct, so the
# CPU tier runs without a cross-process cache; the on-chip tier
# (PADDLE_TPU_TIER=1) keeps one — TPU executable serialization is the
# supported path and compiles there are the expensive part.
if _TPU_TIER:
    _cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache_tpu")
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import gc

    import paddle_tpu as paddle
    # reference cycles (optimizer accumulator closures, layer graphs) keep
    # dead models in the weakref state registry until a gc pass; collect so
    # one test's mesh-committed state can't leak into the next test's
    # to_static signature
    gc.collect()
    paddle.seed(2024)
    np.random.seed(2024)
    yield
    # the global default Program records ops with strong tensor refs; a
    # test that ran static ops outside a program_guard would otherwise pin
    # its (possibly mesh-committed) tensors into every later test's
    # to_static state signature
    import paddle_tpu.static as _static
    if _static._static_mode:
        paddle.disable_static()
    _static._default_main = _static.Program()
    _static._default_startup = _static.Program()


@pytest.fixture()
def metrics():
    """Fresh, enabled observability registry for the duration of one test
    (shared by the serving + chaos suites: metric assertions must never
    see another test's counters)."""
    from paddle_tpu import observability as obs
    obs.enable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()


@pytest.fixture()
def tracing(tmp_path, monkeypatch):
    """Tracing fully on with a clean buffer/ring and dumps routed to
    tmp_path for one test (shared by the trace + chaos suites)."""
    from paddle_tpu.observability import trace
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    trace.set_mode("on")
    trace.clear()
    trace.flight_recorder().clear()
    yield trace
    trace.set_mode("off")
    trace.clear()
    trace.flight_recorder().clear()


# ---------------------------------------------------------------------------
# Test tiers. The DEFAULT tier is the core loop: autograd, to_static,
# optimizers, distributed/pipeline/ZeRO, checkpoint, quant, IO — the
# subsystems where a regression is structural. Measured 8:07 solo on this
# 1-core CI host (2026-07-31, 831 tests, warm persistent cache; the floor
# is aggregate jit-compile time, not any single test — everything >10s
# individually lives in the slow tier). The broad API surface
# (op/nn/vision/distribution parametrization sweeps) and the multi-process
# /long-horizon tests run under `-m slow` (CI's full tier: `pytest -m ""`).
# ---------------------------------------------------------------------------

_SLOW_MODULES = {
    "test_api_ext", "test_api_ext2", "test_api_ext3",
    "test_nn", "test_nn_ext", "test_op_dtype_sweep", "test_ops_math",
    "test_rnn", "test_vision_models", "test_vision_ops_nn_utils",
    "test_vision_det_ops", "test_detection",
    "test_distribution_ops", "test_distribution_ext",
    "test_audio_utils", "test_fft", "test_geometric_text",
    "test_hapi", "test_gpt", "test_sparse",
}


def _accelerator_present() -> bool:
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


# capability probe: the distributed stack (comm.py, pipeline engines, ring
# attention) calls the top-level ``jax.shard_map`` alias; older jax builds
# only ship ``jax.experimental.shard_map``. Tests exercising those paths
# carry ``@pytest.mark.requires_shard_map`` and skip — with the reason
# visible — instead of going known-red on such containers.
_HAS_SHARD_MAP = hasattr(jax, "shard_map")


def pytest_collection_modifyitems(config, items):
    slow = pytest.mark.slow
    # `-m tpu` smoke tests need the real chip: under the (CPU-pinned)
    # default tiers they skip cleanly instead of failing on a host with no
    # accelerator. Probed once per collection.
    chip = _accelerator_present() if any(
        "tpu" in item.keywords for item in items) else False
    skip_tpu = pytest.mark.skip(
        reason="requires the real TPU chip "
               "(run: PADDLE_TPU_TIER=1 python -m pytest tests -m tpu)")
    skip_shard_map = pytest.mark.skip(
        reason="installed jax lacks the top-level jax.shard_map alias "
               "(needs jax >= 0.4.35 with the new name)")
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in _SLOW_MODULES and "slow" not in item.keywords:
            item.add_marker(slow)
        if "tpu" in item.keywords and not chip:
            item.add_marker(skip_tpu)
        if "requires_shard_map" in item.keywords and not _HAS_SHARD_MAP:
            item.add_marker(skip_shard_map)
