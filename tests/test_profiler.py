"""Profiler / RecordEvent / memory-stats tests (SURVEY.md §5 aux parity)."""

import json
import os

import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, make_scheduler,
    export_chrome_tracing,
)


def _work():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    with RecordEvent("user_block"):
        y = (x @ x).sum()
    y.backward()
    return y


def test_profiler_records_ops_and_user_events():
    p = Profiler(targets=[ProfilerTarget.CPU])
    p.start()
    _work()
    p.stop()
    names = {e["name"] for e in p.events()}
    assert "user_block" in names
    assert "matmul" in names or any("matmul" in n for n in names)
    # op hook must be uninstalled after stop
    from paddle_tpu.core import tensor as tmod
    assert tmod._op_profile_hook is None


def test_profiler_summary_and_chrome_export(tmp_path):
    p = Profiler(targets=[ProfilerTarget.CPU])
    with p:
        _work()
        p.step()
        _work()
    s = p.summary()
    assert "Calls" in s and "user_block" in s
    path = str(tmp_path / "trace.json")
    p.export_chrome_tracing(path)
    data = json.load(open(path))
    assert len(data["traceEvents"]) >= 2
    assert all(ev["ph"] == "X" for ev in data["traceEvents"])


def test_make_scheduler_windows():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED          # closed
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED          # repeat exhausted


def test_scheduler_gates_recording():
    sched = make_scheduler(closed=1, ready=0, record=1)
    p = Profiler(targets=[ProfilerTarget.CPU], scheduler=sched)
    p.start()                      # step 0: CLOSED — nothing recorded
    _work()
    p.step()                       # step 1: RECORD_AND_RETURN
    _work()
    p.stop()
    names = [e["name"] for e in p.events()]
    # only one window of work recorded (one user_block, not two)
    assert names.count("user_block") == 1


def test_on_trace_ready_handler(tmp_path):
    d = str(tmp_path / "traces")
    fired = []
    handler = export_chrome_tracing(d)

    def on_ready(prof):
        fired.append(prof.step_num)
        handler(prof)

    p = Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=on_ready)
    with p:
        _work()
    assert fired
    assert os.listdir(d)


def test_timer_only_benchmark():
    p = Profiler(timer_only=True)
    p.start()
    _work()
    p.step()
    _work()
    p.stop()
    b = p.benchmark_summary()
    assert b["steps"] >= 2 and b["avg_step_s"] > 0
    assert p.events() == []  # no tracing in timer_only mode


def test_memory_stats_api():
    # CPU PJRT may not report stats — the API must still return ints ≥ 0.
    assert paddle.device.memory_allocated() >= 0
    assert paddle.device.max_memory_allocated() >= 0
    assert paddle.device.tpu.max_memory_reserved() >= 0
    assert paddle.device.cuda.memory_reserved() >= 0
    paddle.device.empty_cache()
    paddle.device.synchronize()


def test_record_event_explicit_begin_end():
    p = Profiler(targets=[ProfilerTarget.CPU])
    p.start()
    ev = RecordEvent("manual")
    ev.begin()
    ev.end()
    ev.end()  # double-end is a no-op
    p.stop()
    assert any(e["name"] == "manual" for e in p.events())


@pytest.mark.tpu
@pytest.mark.slow
def test_memory_stats_on_real_chip():
    """Round-1 gap: the PJRT memory-stats parity surface was never verified
    against real HBM. Allocate a known-size buffer on the chip and check
    the counters move accordingly."""
    import subprocess
    import sys

    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items()
             if k != "JAX_PLATFORMS" or v != "cpu"})
    if "tpu" not in probe.stdout.lower():
        pytest.skip("no TPU attached")

    code = r"""
import numpy as np
import paddle_tpu as paddle
import jax, jax.numpy as jnp

if jax.devices()[0].memory_stats() is None:
    # relay-attached PJRT clients may not forward allocator stats
    print("MEMSTATS_UNAVAILABLE")
    raise SystemExit(0)
base = paddle.device.memory_allocated()
big = jax.device_put(jnp.zeros((64, 1024, 1024), jnp.float32))  # 256MB
jax.block_until_ready(big)
after = paddle.device.memory_allocated()
peak = paddle.device.max_memory_allocated()
grew = after - base
assert grew >= 200 * 1024 * 1024, (base, after)
assert peak >= after, (peak, after)
del big
print("MEMSTATS_OK", grew)
"""
    env = dict(os.environ)
    env.pop("PADDLE_PLATFORM", None)
    if env.get("JAX_PLATFORMS") == "cpu":
        del env["JAX_PLATFORMS"]
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
    if "MEMSTATS_UNAVAILABLE" in out.stdout:
        pytest.skip("attached PJRT client does not forward memory stats "
                    "(relay tunnel limitation); parity surface covered on "
                    "directly-attached chips")
    assert "MEMSTATS_OK" in out.stdout
