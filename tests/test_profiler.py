"""Profiler / RecordEvent / memory-stats tests (SURVEY.md §5 aux parity)."""

import json
import os

import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, make_scheduler,
    export_chrome_tracing,
)


def _work():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    with RecordEvent("user_block"):
        y = (x @ x).sum()
    y.backward()
    return y


def test_profiler_records_ops_and_user_events():
    p = Profiler(targets=[ProfilerTarget.CPU])
    p.start()
    _work()
    p.stop()
    names = {e["name"] for e in p.events()}
    assert "user_block" in names
    assert "matmul" in names or any("matmul" in n for n in names)
    # op hook must be uninstalled after stop
    from paddle_tpu.core import tensor as tmod
    assert tmod._op_profile_hook is None


def test_profiler_summary_and_chrome_export(tmp_path):
    p = Profiler(targets=[ProfilerTarget.CPU])
    with p:
        _work()
        p.step()
        _work()
    s = p.summary()
    assert "Calls" in s and "user_block" in s
    path = str(tmp_path / "trace.json")
    p.export_chrome_tracing(path)
    data = json.load(open(path))
    assert len(data["traceEvents"]) >= 2
    assert all(ev["ph"] == "X" for ev in data["traceEvents"])


def test_make_scheduler_windows():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED          # closed
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED          # repeat exhausted


def test_scheduler_gates_recording():
    sched = make_scheduler(closed=1, ready=0, record=1)
    p = Profiler(targets=[ProfilerTarget.CPU], scheduler=sched)
    p.start()                      # step 0: CLOSED — nothing recorded
    _work()
    p.step()                       # step 1: RECORD_AND_RETURN
    _work()
    p.stop()
    names = [e["name"] for e in p.events()]
    # only one window of work recorded (one user_block, not two)
    assert names.count("user_block") == 1


def test_on_trace_ready_handler(tmp_path):
    d = str(tmp_path / "traces")
    fired = []
    handler = export_chrome_tracing(d)

    def on_ready(prof):
        fired.append(prof.step_num)
        handler(prof)

    p = Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=on_ready)
    with p:
        _work()
    assert fired
    assert os.listdir(d)


def test_timer_only_benchmark():
    p = Profiler(timer_only=True)
    p.start()
    _work()
    p.step()
    _work()
    p.stop()
    b = p.benchmark_summary()
    assert b["steps"] >= 2 and b["avg_step_s"] > 0
    assert p.events() == []  # no tracing in timer_only mode


def test_memory_stats_api():
    # CPU PJRT may not report stats — the API must still return ints ≥ 0.
    assert paddle.device.memory_allocated() >= 0
    assert paddle.device.max_memory_allocated() >= 0
    assert paddle.device.tpu.max_memory_reserved() >= 0
    assert paddle.device.cuda.memory_reserved() >= 0
    paddle.device.empty_cache()
    paddle.device.synchronize()


def test_record_event_explicit_begin_end():
    p = Profiler(targets=[ProfilerTarget.CPU])
    p.start()
    ev = RecordEvent("manual")
    ev.begin()
    ev.end()
    ev.end()  # double-end is a no-op
    p.stop()
    assert any(e["name"] == "manual" for e in p.events())
