"""End-to-end tracing + crash flight recorder (ISSUE 12).

The acceptance surface for ``paddle_tpu.observability.trace`` / ``http``:

* span trees — thread-local nesting, explicit cross-thread handoff via
  ``SpanContext``, balance on every exit path (the ``span_problems``
  validator the chaos suites reuse);
* Chrome trace-event export — a serving ``submit()`` under load and a
  supervised training run each produce a Perfetto-loadable document with
  a CONNECTED span tree per request/step (verified structurally);
* the always-on flight recorder — ring wrap-around, dump-on-abort with
  the injected fault site in the tail, the ``TrainAborted.flight_dump``
  handle;
* the ``/metrics`` + ``/healthz`` + ``/debug`` scrape endpoint;
* the SLO-shaped serving histogram boundaries (the bucket satellite);
* near-zero disabled-mode overhead (structural: the shared no-op span,
  the uninstalled per-op hook).
"""

import json
import os
import threading
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.observability import http as obs_http
from paddle_tpu.observability import trace
from paddle_tpu.resilience import faults, reset_policies
from paddle_tpu.resilience.trainer import TrainAborted, TrainingSupervisor

from test_serving import PROMPTS, dense_reference, make_engine


@pytest.fixture(autouse=True)
def _fast_retry_policies(monkeypatch):
    for site in ("STEP", "DATA", "SAVE"):
        monkeypatch.setenv(f"PADDLE_TPU_RETRY_TRAIN_{site}_BASE_DELAY",
                           "0.001")
        monkeypatch.setenv(f"PADDLE_TPU_RETRY_TRAIN_{site}_MAX_DELAY",
                           "0.002")
    reset_policies()
    yield
    reset_policies()


def _attrs(e):
    return e.get("attrs") or {}


def _req_events(evs, rid):
    return [e for e in evs if _attrs(e).get("rid") == rid]


# ---------------------------------------------------------------------------
# span core
# ---------------------------------------------------------------------------

class TestSpanCore:
    def test_disabled_span_is_shared_noop(self):
        assert trace.mode() == "off"
        s1 = trace.span("a", x=1)
        s2 = trace.span("b")
        assert s1 is s2                      # one shared object, no alloc
        with s1:
            pass
        assert trace.events() == []
        assert trace.new_trace("t") is None
        assert trace.current() is None

    def test_thread_local_nesting(self, tracing):
        with trace.span("outer"):
            with trace.span("inner"):
                cur = trace.current()
        evs = trace.events()
        assert trace.span_problems(evs) == []
        b = {e["name"]: e for e in evs if e["kind"] == "B"}
        assert b["inner"]["parent"] == b["outer"]["span"]
        assert b["inner"]["trace"] == b["outer"]["trace"]
        assert cur is not None and cur.span == b["inner"]["span"]
        assert trace.current() is None       # stack unwound

    def test_cross_thread_handoff(self, tracing):
        ctx = trace.new_trace("job-1", rid=1)
        out = {}

        def worker():
            with trace.span("phase", parent=ctx) as sp:
                out["ctx"] = sp.ctx

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert out["ctx"].trace == ctx.trace
        evs = trace.events()
        assert trace.span_problems(evs) == []
        b = [e for e in evs if e["kind"] == "B"][0]
        assert b["trace"] == ctx.trace and b["parent"] == 0

    def test_span_balanced_through_exceptions(self, tracing):
        with pytest.raises(faults.KillPoint):
            with trace.span("doomed"):
                raise faults.KillPoint("simulated death")
        assert trace.span_problems() == []
        end = [e for e in trace.events() if e["kind"] == "E"][0]
        assert end["attrs"]["error"] == "KillPoint"

    def test_instant_attaches_to_current_span(self, tracing):
        with trace.span("s") as sp:
            trace.instant("tick", n=1)
        ev = [e for e in trace.events() if e["kind"] == "i"][0]
        assert ev["trace"] == sp.ctx.trace and ev["parent"] == sp.ctx.span
        assert ev["attrs"] == {"n": 1}

    def test_span_problems_detects_imbalance(self, tracing):
        with trace.span("ok"):
            pass
        evs = trace.events()
        # drop the end event: the validator must notice
        broken = [e for e in evs if e["kind"] != "E"]
        assert trace.span_problems(broken) != []
        assert trace.span_problems(evs) == []

    def test_make_event_envelope(self):
        ev = trace.make_event("step", "telemetry", attrs={"step": 3})
        assert {"ts", "kind", "name", "attrs"} <= set(ev)
        assert ev["kind"] == "step" and ev["attrs"]["step"] == 3

    def test_unknown_env_mode_stays_off(self, monkeypatch):
        # a typo of "flight" must not silently enable the most expensive
        # tier (per-op hook + 500k-event buffer) on a production host
        monkeypatch.setenv("PADDLE_TPU_TRACE", "fligth")
        assert trace._env_mode() == "off"
        monkeypatch.setenv("PADDLE_TPU_TRACE", "flight")
        assert trace._env_mode() == "flight"
        monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
        assert trace._env_mode() == "on"

    def test_flight_mode_does_not_grow_track_labels(self):
        # flight mode is the bounded tier: per-request new_trace calls
        # must not leak label-map entries (the exporter never reads them)
        trace.set_mode("flight")
        try:
            before = len(trace._STATE.tracks)
            for _ in range(50):
                trace.new_trace("request-x")
            assert len(trace._STATE.tracks) == before
        finally:
            trace.set_mode("off")
            trace.flight_recorder().clear()

    def test_per_op_hook_only_in_on_mode(self, tracing):
        from paddle_tpu.core import tensor as tensor_mod
        assert tensor_mod._op_trace_hook is not None
        x = paddle.to_tensor([1.0, 2.0])
        _ = x + x
        assert any(e["kind"] == "O" for e in trace.events())
        trace.set_mode("flight")
        assert tensor_mod._op_trace_hook is None
        trace.set_mode("off")
        assert tensor_mod._op_trace_hook is None


# ---------------------------------------------------------------------------
# chrome export
# ---------------------------------------------------------------------------

class TestChromeExport:
    def test_export_structure_and_json(self, tracing, tmp_path):
        ctx = trace.new_trace("request-9", rid=9)
        with trace.span("serving.submit", parent=ctx, rid=9):
            trace.instant("serving.queued", parent=ctx, rid=9)
        doc = trace.export_chrome()
        json.dumps(doc)                      # serializable as-is
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        evs = doc["traceEvents"]
        phases = {e["ph"] for e in evs}
        assert "X" in phases and "i" in phases and "M" in phases
        for e in evs:
            assert {"name", "ph", "pid"} <= set(e)
            if e["ph"] in ("X", "i"):
                assert e["ts"] >= 0
            if e["ph"] == "X":
                assert e["dur"] >= 0
        # track metadata names the request
        tracks = [e for e in evs if e["ph"] == "M"
                  and e["name"] == "thread_name"]
        assert any(t["args"]["name"] == "request-9" for t in tracks)
        # file form
        p = trace.export_chrome(str(tmp_path / "t.json"))
        assert json.load(open(p))["traceEvents"]

    def test_crash_open_span_exports_as_begin(self, tracing):
        evs = []
        with trace.span("outer"):
            evs = list(trace.events())       # B emitted, E not yet
        doc = trace.export_chrome(evs=evs)
        assert [e for e in doc["traceEvents"] if e["ph"] == "B"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_wraps_keeping_latest(self):
        fr = trace.FlightRecorder(capacity=8)
        for i in range(20):
            fr.record(trace.make_event("ev", f"e{i}"))
        snap = fr.snapshot()
        assert [e["name"] for e in snap] == [f"e{i}" for i in range(12, 20)]

    def test_capacity_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_EVENTS", "32")
        assert trace.FlightRecorder().capacity == 32
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_EVENTS", "bogus")
        assert trace.FlightRecorder().capacity == 512

    def test_record_lands_in_ring_even_when_tracing_off(self):
        assert trace.mode() == "off"
        trace.flight_recorder().clear()
        trace.record("fault", site="x.y")
        trace.instant("lifecycle", rid=1)
        names = [e["name"] for e in trace.flight_recorder().snapshot()]
        assert names == ["fault", "lifecycle"]
        assert trace.events() == []          # buffer untouched
        trace.flight_recorder().clear()

    def test_dump_is_parseable_and_atomic(self, tracing, tmp_path):
        trace.record("fault", site="train.step", injected="error")
        p = trace.flight_dump("unit_test", extra="info")
        assert p and os.path.dirname(p) == str(tmp_path)
        doc = json.load(open(p))
        assert doc["reason"] == "unit_test" and doc["pid"] == os.getpid()
        assert doc["info"]["extra"] == "info"
        assert doc["events"][-1]["attrs"]["site"] == "train.step"
        assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]

    def test_dump_failure_is_swallowed(self, tracing, tmp_path):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("x")              # a FILE where a dir is needed
        p = trace.flight_recorder().dump(
            "nope", path=str(blocker / "deeper" / "f.json"))
        assert p is None                     # logged, never raised


# ---------------------------------------------------------------------------
# scrape endpoint
# ---------------------------------------------------------------------------

class TestHTTPEndpoint:
    def test_routes(self, tracing, metrics):
        # isolate from beacons earlier suites left behind (an engine test
        # that never stop()s leaves its beacon to go stale minutes later)
        trace._HEALTH.beats.clear()
        obs.inc("http.test_total")
        trace.heartbeat("test.engine", ttl_s=60.0)
        with trace.span("s"):
            pass
        srv = obs_http.start_http_server(0)
        try:
            body = urllib.request.urlopen(
                srv.url + "/metrics", timeout=5).read().decode()
            assert "http_test_total 1" in body
            r = urllib.request.urlopen(srv.url + "/healthz", timeout=5)
            h = json.load(r)
            assert r.status == 200 and h["status"] == "ok"
            assert h["components"]["test.engine"]["ok"]
            f = json.load(urllib.request.urlopen(
                srv.url + "/debug/flight", timeout=5))
            assert "events" in f and f["capacity"] >= 8
            t = json.load(urllib.request.urlopen(
                srv.url + "/debug/trace", timeout=5))
            assert t["traceEvents"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/nope", timeout=5)
            assert ei.value.code == 404
        finally:
            srv.close()
            trace.heartbeat_clear("test.engine")

    def test_healthz_503_on_stale_beacon(self):
        trace._HEALTH.beats.clear()
        trace.heartbeat("stale.engine", ttl_s=0.0)
        srv = obs_http.start_http_server(0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/healthz", timeout=5)
            assert ei.value.code == 503
            doc = json.load(ei.value)
            assert doc["status"] == "unhealthy"
            assert not doc["components"]["stale.engine"]["ok"]
        finally:
            srv.close()
            trace.heartbeat_clear("stale.engine")

    def test_env_opt_in_is_singleton_and_off_by_default(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_OBS_HTTP_PORT", raising=False)
        assert obs_http.maybe_serve_from_env() is None
        monkeypatch.setenv("PADDLE_TPU_OBS_HTTP_PORT", "0")
        monkeypatch.setattr(obs_http, "_GLOBAL", None)
        monkeypatch.setattr(obs_http, "_DISABLED", False)
        srv = obs_http.maybe_serve_from_env()
        try:
            assert srv is not None
            assert obs_http.maybe_serve_from_env() is srv   # one per process
        finally:
            srv.close()
            monkeypatch.setattr(obs_http, "_GLOBAL", None)

    def test_env_bad_port_disables_quietly_and_latches(self, monkeypatch,
                                                       caplog):
        import logging
        monkeypatch.setenv("PADDLE_TPU_OBS_HTTP_PORT", "not-a-port")
        monkeypatch.setattr(obs_http, "_GLOBAL", None)
        monkeypatch.setattr(obs_http, "_DISABLED", False)
        with caplog.at_level(logging.WARNING,
                             logger="paddle_tpu.observability.http"):
            assert obs_http.maybe_serve_from_env() is None
            # latched: the second opt-in attempt neither retries nor
            # re-warns (an engine is constructed per request batch)
            assert obs_http.maybe_serve_from_env() is None
        assert len([r for r in caplog.records
                    if "disabled" in r.message]) == 1
        assert obs_http._DISABLED


# ---------------------------------------------------------------------------
# serving integration: the request's connected span tree
# ---------------------------------------------------------------------------

class TestServingTrace:
    def test_request_trace_connected_across_threads(self, tracing, metrics):
        eng = make_engine(max_batch=4)
        reqs = [serving.GenerationRequest(p, max_new_tokens=10)
                for p in PROMPTS[:3]]
        futs = [eng.submit(r) for r in reqs]
        eng.start()                          # submit() thread != step thread
        try:
            for f in futs:
                f.result(timeout=60)
        finally:
            eng.stop(drain=True, timeout=10)
        evs = trace.events()
        assert trace.span_problems(evs) == []
        for r, f, p in zip(reqs, futs, PROMPTS[:3]):
            assert f.result().tokens == dense_reference(p, 10)
            mine = _req_events(evs, r.request_id)
            names = {e["name"] for e in mine}
            assert {"serving.submit", "serving.queued", "serving.prefill",
                    "serving.decode_step", "serving.complete"} <= names
            # CONNECTED: every event of this request shares one trace id,
            # and every span parents to the request root or a sibling span
            trace_ids = {e["trace"] for e in mine}
            assert len(trace_ids) == 1
            spans = {e["span"] for e in mine if e["kind"] == "B"}
            for e in mine:
                par = e.get("parent", 0)
                assert par == 0 or par in spans
        # the engine's own decode spans live on their own track
        assert any(e["name"] == "serving.decode" for e in evs)

    def test_faulted_request_trace_carries_fault_event(self, tracing,
                                                       metrics):
        sched = faults.FaultSchedule()
        sched.error("serving.step", on=(1, 5))   # slot 0 faults twice
        eng = make_engine(max_batch=4)
        reqs = [serving.GenerationRequest(p, max_new_tokens=4)
                for p in PROMPTS[:2]]
        with faults.installed(sched):
            futs = [eng.submit(r) for r in reqs]
            eng.run()
            eng.stop(drain=True, timeout=10)
        failed = [r for r, f in zip(reqs, futs)
                  if f.exception(timeout=0) is not None]
        assert failed, "schedule should fail at least one request"
        evs = trace.events()
        for r in failed:
            fevs = [e for e in _req_events(evs, r.request_id)
                    if e["name"] == "serving.fault"]
            assert fevs, "faulted request's trace lost its fault event"
            assert fevs[-1]["attrs"]["error"] == "FaultInjected"
        assert trace.span_problems(evs) == []

    def test_recovery_dumps_flight_with_fault_site(self, tracing, metrics,
                                                   tmp_path):
        sched = faults.FaultSchedule()
        sched.error("serving.watchdog", on=(1, 2))   # attempt + retry ->
        eng = make_engine(max_batch=4, max_replays=2)  # crash-recovery
        with faults.installed(sched):
            futs = [eng.submit(serving.GenerationRequest(
                p, max_new_tokens=3)) for p in PROMPTS[:2]]
            eng.run()
            eng.stop(drain=True, timeout=10)
        for f, p in zip(futs, PROMPTS[:2]):   # replay finished the work
            assert f.result(timeout=0).tokens == dense_reference(p, 3)
        path = os.path.join(
            str(tmp_path), f"flight-{os.getpid()}-serving_recover.json")
        assert os.path.exists(path)
        doc = json.load(open(path))
        assert doc["reason"] == "serving_recover"
        fault_sites = [e["attrs"].get("site") for e in doc["events"]
                       if e["name"] == "fault"]
        assert fault_sites and fault_sites[-1] == "serving.watchdog"

    def test_slo_bucket_boundaries_registered(self):
        reg = obs.default_registry()
        ttft = reg.get("serving.ttft_seconds")
        tpot = reg.get("serving.tpot_seconds")
        qw = reg.get("serving.queue_wait_seconds")
        from paddle_tpu.serving.engine import TPOT_BUCKETS, TTFT_BUCKETS
        from paddle_tpu.serving.scheduler import QUEUE_WAIT_BUCKETS
        assert ttft.boundaries == TTFT_BUCKETS
        assert tpot.boundaries == TPOT_BUCKETS
        assert qw.boundaries == QUEUE_WAIT_BUCKETS
        # the satellite's point: sub-10ms decode steps resolve into
        # several buckets instead of clipping into one or two
        assert sum(1 for b in TPOT_BUCKETS if b < 0.01) >= 5
        assert sum(1 for b in QUEUE_WAIT_BUCKETS if b <= 0.025) >= 4

    def test_tracing_off_serving_still_correct_and_bufferless(self, metrics):
        assert trace.mode() == "off"
        buf_before = len(trace.events())
        eng = make_engine(max_batch=4)
        fut = eng.submit(serving.GenerationRequest(PROMPTS[0],
                                                   max_new_tokens=4))
        eng.run()
        eng.stop(drain=True, timeout=5)
        assert fut.result(timeout=0).tokens == dense_reference(PROMPTS[0], 4)
        assert len(trace.events()) == buf_before


# ---------------------------------------------------------------------------
# training integration: the step's span tree + abort dumps
# ---------------------------------------------------------------------------

def _build_run(seed=7, n=16, batch_size=8):
    from paddle_tpu.core.tensor import Parameter
    Parameter._param_counter = 0
    paddle.seed(seed)
    net = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    rng = np.random.default_rng(seed)
    ds = paddle.io.TensorDataset(
        [paddle.to_tensor(rng.normal(size=(n, 8)).astype(np.float32)),
         paddle.to_tensor(rng.normal(size=(n, 4)).astype(np.float32))])
    loader = paddle.io.DataLoader(ds, batch_size=batch_size, shuffle=True)
    loss_fn = paddle.nn.MSELoss()

    def step_fn(batch):
        x, y = batch
        loss = loss_fn(net(x), y)
        loss.backward()
        return loss

    def update_fn():
        opt.step()
        opt.clear_grad()

    return SimpleNamespace(net=net, opt=opt, loader=loader, step=step_fn,
                           update=update_fn)


class TestTrainingTrace:
    def test_supervised_run_has_connected_step_tree(self, tracing):
        r = _build_run()
        sup = TrainingSupervisor(r.net, r.opt, r.loader)
        rep = sup.run(r.step, r.loader, epochs=1, update_fn=r.update)
        assert rep.steps == 2
        evs = trace.events()
        assert trace.span_problems(evs) == []
        b = [e for e in evs if e["kind"] == "B"]
        by_name = {}
        for e in b:
            by_name.setdefault(e["name"], []).append(e)
        assert len(by_name["train.step"]) >= 2
        run_span = by_name["train.run"][0]
        for step_ev in by_name["train.step"]:
            assert step_ev["parent"] == run_span["span"]
            assert step_ev["trace"] == run_span["trace"]
        # fetch/fwd_bwd/update are children of SOME train.step
        step_ids = {e["span"] for e in by_name["train.step"]}
        for name in ("train.fetch", "train.fwd_bwd", "train.update"):
            assert all(e["parent"] in step_ids for e in by_name[name]), name
        doc = trace.export_chrome()
        json.dumps(doc)
        assert [e for e in doc["traceEvents"]
                if e["ph"] == "X" and e["name"] == "train.step"]

    def test_retry_event_attached_inside_step(self, tracing):
        r = _build_run()
        sched = faults.FaultSchedule().error("train.step", on=(2,))
        sup = TrainingSupervisor(r.net, r.opt, r.loader)
        with faults.installed(sched):
            rep = sup.run(r.step, r.loader, epochs=1, update_fn=r.update)
        assert rep.retries == 1
        evs = trace.events()
        retries = [e for e in evs if e["name"] == "train.retry"]
        assert retries and retries[0]["attrs"]["site"] == "train.step"
        step_spans = {e["span"] for e in evs if e["kind"] == "B"
                      and e["name"] == "train.step"}
        assert retries[0]["parent"] in step_spans
        assert trace.span_problems(evs) == []

    def test_abort_dump_tail_names_fault_site(self, tracing, tmp_path):
        r = _build_run()
        sched = faults.FaultSchedule().error("train.step", on=(1, 2, 3))
        sup = TrainingSupervisor(r.net, r.opt, r.loader)   # no ckpt_dir
        with faults.installed(sched):
            with pytest.raises(TrainAborted) as ei:
                sup.run(r.step, r.loader, epochs=1, update_fn=r.update)
        dump = ei.value.flight_dump
        assert dump and os.path.exists(dump)
        doc = json.load(open(dump))
        assert doc["reason"] == "train_aborted"
        fevs = [e for e in doc["events"] if e["name"] == "fault"]
        assert fevs and fevs[-1]["attrs"]["site"] == "train.step"
        assert trace.span_problems() == []   # balanced through the abort

    def test_kill_dump_written_on_supervisor_exit(self, tracing, tmp_path):
        r = _build_run()
        sched = faults.FaultSchedule().kill("train.step", on=(2,))
        sup = TrainingSupervisor(r.net, r.opt, r.loader,
                                 ckpt_dir=str(tmp_path / "ck"), save_every=1)
        with faults.installed(sched):
            with pytest.raises(faults.KillPoint):
                sup.run(r.step, r.loader, epochs=1, update_fn=r.update)
        path = os.path.join(
            str(tmp_path), f"flight-{os.getpid()}-supervisor_exit.json")
        doc = json.load(open(path))
        assert doc["info"]["error"] == "KillPoint"
        fevs = [e for e in doc["events"] if e["name"] == "fault"]
        assert fevs[-1]["attrs"]["site"] == "train.step"
        assert trace.span_problems() == []   # spans unwound by the kill


# ---------------------------------------------------------------------------
# envelope unification + hapi
# ---------------------------------------------------------------------------

class TestEnvelopeUnification:
    def test_step_telemetry_record_is_envelope_and_rings(self, tmp_path,
                                                         metrics):
        trace.flight_recorder().clear()
        obs.counter("tt.n_total").inc(2)
        path = str(tmp_path / "s.jsonl")
        w = obs.StepTelemetryWriter(path, baseline="zero")
        rec = w.write(1, loss=0.5)
        w.close()
        assert {"ts", "kind", "name", "attrs"} <= set(rec)
        assert rec["kind"] == "step" and rec["name"] == "telemetry"
        assert rec["attrs"]["counters"]["tt.n_total"] == 2
        assert rec["attrs"]["loss"] == 0.5
        # mirrored into the flight ring: a crash dump's tail carries the
        # last steps' telemetry
        ring = trace.flight_recorder().snapshot()
        assert ring and ring[-1]["kind"] == "step"
        assert obs.read_jsonl(path)[0]["attrs"]["step"] == 1
        trace.flight_recorder().clear()

    def test_hapi_fit_spans(self, tracing):
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
            loss=paddle.nn.MSELoss())
        rng = np.random.default_rng(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32)),
             paddle.to_tensor(rng.normal(size=(8, 2)).astype(np.float32))])
        model.fit(ds, batch_size=4, epochs=1, verbose=0)
        evs = trace.events()
        assert trace.span_problems(evs) == []
        names = {e["name"] for e in evs if e["kind"] == "B"}
        assert {"hapi.fit", "hapi.train_batch"} <= names
