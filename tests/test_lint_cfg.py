"""graft-lint 4.0 CFG builder (tools/lint/cfg.py).

Fixture matrix over the constructs the exception/resource rules lean on —
branches, loops, nested try, finally cloning, with, early return, raise
inside a handler (typed bare-raise targets) — plus the shipped-tree
property pin: every function in ``paddle_tpu/serving/`` builds a CFG with
no orphan blocks.
"""

import ast
import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint.cfg import build_cfg, iter_cfgs  # noqa: E402
from tools.lint.engine import iter_python_files  # noqa: E402


def cfg_of(src):
    tree = ast.parse(textwrap.dedent(src))
    return build_cfg(tree.body[0])


def edge_kinds(cfg):
    return {kind for _s, _t, kind in cfg.edges()}


def kind_targets(cfg, kind):
    return {t for _s, t, k in cfg.edges() if k == kind}


def call_block(cfg, name):
    """The block whose own statement list holds the bare call ``name()``."""
    for b in cfg.blocks.values():
        for s in b.stmts:
            if (isinstance(s, ast.Expr) and isinstance(s.value, ast.Call)
                    and isinstance(s.value.func, ast.Name)
                    and s.value.func.id == name):
                return b
    raise AssertionError(f"no block calls {name}()")


# ---------------------------------------------------------------------------
# the construct matrix
# ---------------------------------------------------------------------------

def test_straight_line_single_block():
    cfg = cfg_of("""
        def f(x):
            y = x + 1
            return y
    """)
    assert cfg.orphan_blocks() == []
    # one statement-bearing block; every such block also carries the
    # blanket uncaught-exception edge to raise_exit
    code = [b for b in cfg.blocks.values() if b.stmts]
    assert len(code) == 1
    assert kind_targets(cfg, "return") == {cfg.exit}
    assert kind_targets(cfg, "except") == {cfg.raise_exit}


def test_branch_true_false_join():
    cfg = cfg_of("""
        def f(x):
            if x:
                a = 1
            else:
                a = 2
            return a
    """)
    assert cfg.orphan_blocks() == []
    assert {"true", "false"} <= edge_kinds(cfg)
    # both arms exist as statement-bearing blocks and rejoin
    (src,) = [b for b in cfg.blocks.values()
              if b.stmts and isinstance(b.stmts[-1], ast.If)]
    arms = {t for t, k in src.succs if k in ("true", "false")}
    assert len(arms) == 2
    # both arms rejoin at the same block
    joins = {t for a in arms for t, k in cfg.blocks[a].succs if k == "next"}
    assert len(joins) == 1


def test_branch_without_else_falls_through():
    cfg = cfg_of("""
        def f(x):
            if x:
                x = 0
            return x
    """)
    (src,) = [b for b in cfg.blocks.values()
              if b.stmts and isinstance(b.stmts[-1], ast.If)]
    assert {k for _t, k in src.succs
            if k in ("true", "false")} == {"true", "false"}
    assert cfg.orphan_blocks() == []


def test_loop_back_break_continue_edges():
    cfg = cfg_of("""
        def f(xs):
            for x in xs:
                if x < 0:
                    continue
                if x > 9:
                    break
                use(x)
            return xs
    """)
    assert cfg.orphan_blocks() == []
    assert {"back", "break", "continue", "true", "false"} <= edge_kinds(cfg)
    # the loop header holds the For node and owns the body/after split
    (hdr,) = [b for b in cfg.blocks.values() if b.label == "loop"]
    assert isinstance(hdr.stmts[0], ast.For)
    assert {k for _t, k in hdr.succs} >= {"true", "false"}
    # continue re-enters the header; break does not
    assert hdr.bid in kind_targets(cfg, "continue")
    assert hdr.bid not in kind_targets(cfg, "break")


def test_while_true_has_no_false_exit():
    cfg = cfg_of("""
        def f(q):
            while True:
                if q.done():
                    break
                q.step()
    """)
    (hdr,) = [b for b in cfg.blocks.values() if b.label == "loop"]
    assert "false" not in {k for _t, k in hdr.succs}
    assert cfg.orphan_blocks() == []


def test_try_block_level_except_edges_and_propagation():
    cfg = cfg_of("""
        def f(x):
            try:
                risky(x)
            except ValueError:
                return -1
            return 0
    """)
    handlers = [b for b in cfg.blocks.values() if b.label == "handler"]
    assert len(handlers) == 1
    assert handlers[0].handler_types == ("ValueError",)
    # the protected suite wires except edges to the handler AND (no
    # catch-all) outward to raise_exit
    body = call_block(cfg, "risky")
    tgt = {t for t, k in body.succs if k == "except"}
    assert handlers[0].bid in tgt and cfg.raise_exit in tgt


def test_catch_all_handler_stops_propagation():
    cfg = cfg_of("""
        def f(x):
            try:
                risky(x)
            except Exception:
                return -1
            return 0
    """)
    body = call_block(cfg, "risky")
    assert cfg.raise_exit not in {t for t, k in body.succs if k == "except"}


def test_nested_try_inner_handlers_then_outer():
    cfg = cfg_of("""
        def f(x):
            try:
                try:
                    risky(x)
                except KeyError:
                    inner()
                other(x)
            except ValueError:
                outer()
    """)
    assert cfg.orphan_blocks() == []
    types = {b.handler_types for b in cfg.blocks.values()
             if b.handler_types is not None}
    assert types == {("KeyError",), ("ValueError",)}
    # risky(x)'s block targets the inner handler, the outer handler and
    # (neither is a catch-all) the raise exit
    body = call_block(cfg, "risky")
    tgt = {t for t, k in body.succs if k == "except"}
    assert cfg.raise_exit in tgt
    assert {cfg.blocks[t].handler_types
            for t in tgt if t != cfg.raise_exit} == \
        {("KeyError",), ("ValueError",)}


def test_bare_raise_in_handler_takes_typed_targets():
    # `except T: ...; raise` re-raises exactly T: an enclosing handler
    # naming T exactly catches it FOR SURE — no blind raise_exit edge
    cfg = cfg_of("""
        def f(x):
            try:
                try:
                    risky(x)
                except KeyError:
                    raise
            except KeyError:
                return -1
    """)
    inner = [b for b in cfg.blocks.values()
             if b.handler_types == ("KeyError",) and b.stmts
             and isinstance(b.stmts[-1], ast.Raise)][0]
    raise_tgts = {t for t, k in inner.succs if k == "raise"}
    assert cfg.raise_exit not in raise_tgts
    assert all(cfg.blocks[t].handler_types == ("KeyError",)
               for t in raise_tgts)


def test_bare_raise_propagates_past_unrelated_handler():
    # the outer handler names a DIFFERENT type: it stays a possible
    # target (subclassing is invisible here) but so does raise_exit
    cfg = cfg_of("""
        def f(x):
            try:
                try:
                    risky(x)
                except KeyError:
                    raise
            except ValueError:
                return -1
    """)
    inner = [b for b in cfg.blocks.values()
             if b.handler_types == ("KeyError",)][0]
    raise_tgts = {t for t, k in inner.succs if k == "raise"}
    assert cfg.raise_exit in raise_tgts


def test_explicit_raise_edges_to_handler_and_exit():
    cfg = cfg_of("""
        def f(x):
            try:
                raise ValueError(x)
            except ValueError:
                return -1
    """)
    raiser = [b for b in cfg.blocks.values()
              if b.stmts and isinstance(b.stmts[-1], ast.Raise)][0]
    tgts = {t for t, k in raiser.succs if k == "raise"}
    handler = [b for b in cfg.blocks.values()
               if b.handler_types == ("ValueError",)][0]
    assert handler.bid in tgts and cfg.raise_exit in tgts


def test_finally_cloned_per_continuation():
    fn = ast.parse(textwrap.dedent("""
        def f(x):
            try:
                if x:
                    return 1
                risky(x)
            finally:
                cleanup()
            return 0
    """)).body[0]
    cfg = build_cfg(fn)
    cleanup_stmt = fn.body[0].finalbody[0]
    clones = cfg.blocks_with(cleanup_stmt)
    # one copy each for: the return unwind, the exceptional unwind, and
    # the normal fall-through continuation
    assert len(clones) >= 3
    # the exceptional clone ends at raise_exit; the return clone at exit
    ends = set()
    for c in clones:
        for t, _k in c.succs:
            ends.add(t)
    assert cfg.exit in ends or any(
        t == cfg.exit for c in clones for t, k in c.succs)
    assert any(t == cfg.raise_exit for c in clones for t, _k in c.succs)
    assert cfg.orphan_blocks() == []


def test_with_statement_sits_in_preceding_block():
    cfg = cfg_of("""
        def f(x):
            with lock() as h:
                use(h)
            return x
    """)
    assert cfg.orphan_blocks() == []
    withers = [b for b in cfg.blocks.values()
               if any(isinstance(s, ast.With) for s in b.stmts)]
    assert len(withers) == 1
    # the body is a separate block reached by a next edge
    assert any(k == "next" for _t, k in withers[0].succs)


def test_early_return_and_visible_dead_code():
    cfg = cfg_of("""
        def f(x):
            return x
            unreachable()
    """)
    # the return reaches exit; the trailing statement stays visible as
    # an orphan block rather than silently vanishing
    assert kind_targets(cfg, "return") == {cfg.exit}
    orphans = cfg.orphan_blocks()
    assert len(orphans) == 1 and orphans[0].label == "dead"


def test_iter_cfgs_qualnames():
    tree = ast.parse(textwrap.dedent("""
        def top():
            def inner():
                pass

        class C:
            def m(self):
                pass
    """))
    quals = [q for q, _fn, _cfg in iter_cfgs(tree)]
    assert quals == ["top", "top.inner", "C.m"]


# ---------------------------------------------------------------------------
# shipped-tree property pin
# ---------------------------------------------------------------------------

def test_every_serving_function_builds_an_orphan_free_cfg():
    """ISSUE 18: the serving tier is what the resource/exception rules
    walk — every function there must build, and a well-formed build of
    live code has no orphan blocks (an orphan means the builder lost an
    edge, which would silently hide leak paths)."""
    checked = 0
    for abspath in iter_python_files(["paddle_tpu/serving"]):
        with open(abspath, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        for qual, _fn, cfg in iter_cfgs(tree):
            orphans = cfg.orphan_blocks()
            assert orphans == [], (abspath, qual, orphans)
            # exits are consistent too: some path reaches exit or raise
            assert cfg.reachable() - {cfg.entry}, (abspath, qual)
            checked += 1
    assert checked > 100  # the tier is not empty / the glob still works
