"""Tests for API batch 7: stacking/splitting/special-function ops, wave-3
losses and layers, fused attention/FFN functionals, namespace fills."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestStackSplit:
    def test_stacks(self):
        a = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"))
        assert paddle.hstack([a, a]).shape == [2, 6]
        assert paddle.vstack([a, a]).shape == [4, 3]
        assert paddle.dstack([a, a]).shape == [2, 3, 2]
        assert paddle.column_stack([a, a]).shape == [2, 6]
        assert paddle.row_stack([a, a]).shape == [4, 3]
        bd = paddle.block_diag([a, a])
        assert bd.shape == [4, 6]
        assert np.asarray(bd.numpy())[0, 3:].sum() == 0

    def test_splits(self):
        a = paddle.to_tensor(np.arange(12).reshape(2, 6).astype("float32"))
        assert [t.shape for t in paddle.hsplit(a, 3)] == [[2, 2]] * 3
        assert [t.shape for t in paddle.vsplit(a, 2)] == [[1, 6]] * 2
        parts = paddle.tensor_split(a, [2, 4], axis=1)
        assert [p.shape for p in parts] == [[2, 2], [2, 2], [2, 2]]
        d = paddle.to_tensor(np.zeros((2, 2, 4), "float32"))
        assert [t.shape for t in paddle.dsplit(d, 2)] == [[2, 2, 2]] * 2

    def test_atleast_unflatten(self):
        s = paddle.to_tensor(np.array(3.0, "float32"))
        assert paddle.atleast_1d(s).shape == [1]
        assert paddle.atleast_2d(s).shape == [1, 1]
        assert paddle.atleast_3d(s).shape == [1, 1, 1]
        assert paddle.unflatten(paddle.zeros([2, 6]), 1, [3, 2]).shape == \
            [2, 3, 2]
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]


class TestScatterViews:
    def test_scatter_nd_adds_duplicates(self):
        out = paddle.scatter_nd(
            paddle.to_tensor(np.array([[0], [2], [0]])),
            paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32")), [4])
        assert out.numpy().tolist() == [4.0, 0.0, 2.0, 0.0]

    def test_select_slice_scatter(self):
        ss = paddle.select_scatter(paddle.zeros([3, 3]), paddle.ones([3]),
                                   0, 1)
        assert np.asarray(ss.numpy())[1].tolist() == [1.0, 1.0, 1.0]
        sl = paddle.slice_scatter(paddle.zeros([4]), paddle.ones([2]),
                                  [0], [1], [3], [1])
        assert sl.numpy().tolist() == [0.0, 1.0, 1.0, 0.0]

    def test_take_modes(self):
        a = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"))
        assert paddle.take(a, paddle.to_tensor(np.array([0, 5]))).numpy() \
            .tolist() == [0.0, 5.0]
        assert paddle.take(a, paddle.to_tensor(np.array([7])),
                           mode="wrap").numpy().tolist() == [1.0]
        assert paddle.take(a, paddle.to_tensor(np.array([7])),
                           mode="clip").numpy().tolist() == [5.0]
        with pytest.raises(IndexError):
            paddle.take(a, paddle.to_tensor(np.array([99])))


class TestSpecialFunctions:
    def test_scipy_matches(self):
        from scipy import special as S
        x = np.array([0.5, 1.5, 2.5], "float32")
        xt = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.i0e(xt).numpy(), S.i0e(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.i1e(xt).numpy(), S.i1e(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.gammaln(xt).numpy(), S.gammaln(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            paddle.gammainc(xt, xt).numpy(), S.gammainc(x, x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.polygamma(xt, 1).numpy(), S.polygamma(1, x), rtol=1e-4)
        np.testing.assert_allclose(
            paddle.multigammaln(xt, 1).numpy(), S.multigammaln(x, 1),
            rtol=1e-4)

    def test_logit_and_logaddexp2(self):
        p = paddle.to_tensor(np.array([0.25], "float32"))
        np.testing.assert_allclose(paddle.logit(p).numpy(), [np.log(1 / 3)],
                                   rtol=1e-5)
        a = paddle.to_tensor(np.array([1.0], "float32"))
        np.testing.assert_allclose(
            paddle.logaddexp2(a, a).numpy(), [2.0], rtol=1e-6)

    def test_diag_embed_matches_torch(self):
        v = np.random.randn(2, 3).astype("float32")
        ref = torch.diag_embed(torch.tensor(v)).numpy()
        ours = paddle.diag_embed(paddle.to_tensor(v)).numpy()
        np.testing.assert_allclose(ours, ref)
        ref_off = torch.diag_embed(torch.tensor(v), offset=1).numpy()
        ours_off = paddle.diag_embed(paddle.to_tensor(v), offset=1).numpy()
        np.testing.assert_allclose(ours_off, ref_off)

    def test_svdvals_and_matrix_transpose(self):
        a = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(
            paddle.linalg.svdvals(paddle.to_tensor(a)).numpy(),
            np.linalg.svd(a, compute_uv=False), rtol=1e-4)
        assert paddle.linalg.matrix_transpose(
            paddle.zeros([2, 3, 4])).shape == [2, 4, 3]


class TestWave3Losses:
    def test_multilabel_matches_torch(self):
        x = np.random.randn(4, 6).astype("float32")
        y = (np.random.rand(4, 6) > 0.5).astype("float32")
        ref = float(TF.multilabel_soft_margin_loss(torch.tensor(x),
                                                   torch.tensor(y)))
        ours = float(nn.functional.multi_label_soft_margin_loss(
            paddle.to_tensor(x), paddle.to_tensor(y)))
        assert abs(ref - ours) < 1e-5

    def test_triplet_with_distance_matches_torch(self):
        a = np.random.randn(5, 8).astype("float32")
        p = np.random.randn(5, 8).astype("float32")
        n = np.random.randn(5, 8).astype("float32")
        ref = float(TF.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(p), torch.tensor(n)))
        ours = float(nn.functional.triplet_margin_with_distance_loss(
            paddle.to_tensor(a), paddle.to_tensor(p), paddle.to_tensor(n)))
        assert abs(ref - ours) < 1e-4

    @pytest.mark.slow
    def test_hsigmoid_trains(self):
        paddle.seed(0)
        layer = nn.HSigmoidLoss(8, 10)
        opt = paddle.optimizer.Adam(learning_rate=0.1,
                                    parameters=layer.parameters())
        x = paddle.to_tensor(np.random.randn(16, 8).astype("float32"))
        y = paddle.to_tensor(np.random.randint(0, 10, (16,)))
        first = last = None
        for _ in range(20):
            loss = layer(x, y).mean()  # per-sample (N, 1) -> scalar
            if first is None:
                first = float(loss)
            last = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert last < first * 0.7

    def test_dice_loss_perfect_prediction(self):
        lab = np.random.randint(0, 3, (2, 5, 1))
        onehot = np.eye(3, dtype="float32")[lab.squeeze(-1)]
        v = float(nn.functional.dice_loss(paddle.to_tensor(onehot),
                                          paddle.to_tensor(lab)))
        assert v < 0.01


class TestWave3Layers:
    def test_zeropads(self):
        assert nn.ZeroPad1D([1, 2])(paddle.zeros([1, 2, 5])).shape == \
            [1, 2, 8]
        assert nn.ZeroPad2D([1, 2, 3, 4])(paddle.zeros([1, 1, 5, 5])).shape \
            == [1, 1, 12, 8]
        assert nn.ZeroPad3D(1)(paddle.zeros([1, 1, 2, 2, 2])).shape == \
            [1, 1, 4, 4, 4]

    def test_embedding_bag_modes(self):
        w = np.random.randn(10, 4).astype("float32")
        ids = np.array([[1, 2], [3, 4]])
        for mode in ("mean", "sum", "max"):
            eb = nn.EmbeddingBag(10, 4, mode=mode)
            eb.weight._set_data(paddle.to_tensor(w)._data)
            out = np.asarray(eb(paddle.to_tensor(ids)).numpy())
            ref = {"mean": w[ids].mean(1), "sum": w[ids].sum(1),
                   "max": w[ids].max(1)}[mode]
            np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_embedding_bag_offsets(self):
        w = np.random.randn(10, 4).astype("float32")
        out = nn.functional.embedding_bag(
            paddle.to_tensor(np.array([1, 2, 3, 4, 5])),
            paddle.to_tensor(w),
            offsets=paddle.to_tensor(np.array([0, 2])), mode="sum")
        ref = np.stack([w[[1, 2]].sum(0), w[[3, 4, 5]].sum(0)])
        np.testing.assert_allclose(np.asarray(out.numpy()), ref, atol=1e-6)


class TestFusedFunctionals:
    def test_fused_feedforward_matches_manual(self):
        h = paddle.to_tensor(np.random.randn(2, 3, 8).astype("float32"))
        w1 = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
        w2 = paddle.to_tensor(np.random.randn(16, 8).astype("float32"))
        out = paddle.incubate.nn.functional.fused_feedforward(
            h, w1, w2, training=False, pre_layer_norm=True)
        ref = h + nn.functional.linear(
            nn.functional.relu(nn.functional.linear(
                nn.functional.layer_norm(h, [8]), w1)), w2)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_fused_mha_matches_sdpa(self):
        np.random.seed(3)
        h = paddle.to_tensor(np.random.randn(2, 3, 8).astype("float32"))
        qkvw = paddle.to_tensor(np.random.randn(3, 2, 4, 8)
                                .astype("float32"))
        lw = paddle.to_tensor(np.eye(8, dtype="float32"))
        out = paddle.incubate.nn.functional.fused_multi_head_attention(
            h, qkvw, lw, pre_layer_norm=True, training=False)
        # manual: ln -> einsum qkv -> sdpa -> reshape -> identity proj + res
        ln = nn.functional.layer_norm(h, [8])
        import jax.numpy as jnp
        qkv = jnp.einsum("bsh,tndh->tbsnd", ln._data, qkvw._data)
        q, k, v = (paddle.Tensor(qkv[i]) for i in range(3))
        att = nn.functional.scaled_dot_product_attention(q, k, v)
        ref = h + paddle.Tensor(att._data.reshape(2, 3, 8))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_fused_matmul_bias(self):
        x = np.random.randn(3, 4).astype("float32")
        w = np.random.randn(4, 5).astype("float32")
        b = np.random.randn(5).astype("float32")
        out = paddle.incubate.nn.functional.fused_matmul_bias(
            paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), x @ w + b, atol=1e-5)


class TestTransformsWave3:
    def test_geometric_transforms_shapes(self):
        from paddle_tpu.vision import transforms as T
        img = np.random.rand(3, 16, 16).astype("float32")
        assert T.RandomErasing(prob=1.0)(img).shape == img.shape
        assert T.RandomAffine(15, translate=(0.1, 0.1),
                              scale=(0.9, 1.1))(img).shape == img.shape
        assert T.RandomPerspective(prob=1.0)(img).shape == img.shape
        assert T.RandAugment()(img).shape == img.shape
        assert T.AutoAugment()(img).shape == img.shape

    def test_erase_and_gamma(self):
        from paddle_tpu.vision import transforms as T
        img = np.ones((3, 8, 8), "float32")
        er = T.erase(img, 2, 2, 4, 4, 0.0)
        assert er[0, 3, 3] == 0.0 and er[0, 0, 0] == 1.0
        g = T.adjust_gamma(img * 0.25, 2.0)
        np.testing.assert_allclose(g, 0.0625, atol=1e-6)

    def test_identity_affine_is_noop(self):
        from paddle_tpu.vision import transforms as T
        img = np.random.rand(3, 9, 9).astype("float32")
        out = T._affine_sample(img, [1, 0, 0, 0, 1, 0])
        np.testing.assert_allclose(out, img)

    def test_image_backend(self):
        import paddle_tpu.vision as vision
        assert vision.get_image_backend() == "numpy"
        with pytest.raises(ValueError):
            vision.set_image_backend("nope")


class TestNamespaceWave3:
    def test_namespaces_resolve(self):
        import paddle_tpu.distributed as dist
        assert dist.fleet.meta_parallel.PipelineLayer
        assert dist.fleet.meta_optimizers.DygraphShardingOptimizer
        assert dist.fleet.layers.ColumnParallelLinear
        assert dist.communication.all_reduce is dist.collective.all_reduce
        assert paddle.text.datasets.Imdb
        assert paddle.audio.backends.list_available_backends() == ["wave"]
        with pytest.raises(RuntimeError):
            paddle.audio.datasets.TESS()
        assert paddle.static.sparsity.calculate_density
        assert paddle.incubate.operators.softmax_mask_fuse
        assert paddle.incubate.layers.shuffle_batch
        assert paddle.incubate.jit.inference

    def test_audio_wave_backend_roundtrip(self, tmp_path):
        import wave as wavelib
        path = tmp_path / "t.wav"
        data = (np.sin(np.linspace(0, 40, 1600)) * 2 ** 14).astype("<i2")
        with wavelib.open(str(path), "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(16000)
            w.writeframes(data.tobytes())
        sig, sr = paddle.audio.backends.load(path)
        assert sr == 16000 and sig.shape == [1600]

    def test_static_ema(self):
        from paddle_tpu.core.tensor import Parameter
        from paddle_tpu import static
        p = Parameter(np.array([2.0], "float32"), name="ema_t")
        ema = static.ExponentialMovingAverage(0.5)
        ema.update([p])
        p._set_data(p._data * 0 + 4.0)
        ema.update([p])
        with ema.apply():
            np.testing.assert_allclose(np.asarray(p.numpy()), [3.0])
        np.testing.assert_allclose(np.asarray(p.numpy()), [4.0])

    def test_callbacks_exist(self):
        cb = paddle.callbacks.ReduceLROnPlateau(patience=1)
        vd = paddle.callbacks.VisualDL(log_dir="/tmp/vdl_test")
        assert cb and vd


class TestReviewFixes7:
    def test_zeropad_channels_last(self):
        zp = nn.ZeroPad2D([1, 1, 2, 2], data_format="NHWC")
        out = zp(paddle.zeros([1, 4, 4, 3]))
        assert out.shape == [1, 8, 6, 3]

    def test_multilabel_weight_per_class(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        y = paddle.to_tensor((np.random.rand(4, 8) > 0.5).astype("float32"))
        w = paddle.to_tensor(np.random.rand(8).astype("float32"))
        v = nn.functional.multi_label_soft_margin_loss(x, y, weight=w)
        ref = float(TF.multilabel_soft_margin_loss(
            torch.tensor(np.asarray(x.numpy())),
            torch.tensor(np.asarray(y.numpy())),
            weight=torch.tensor(np.asarray(w.numpy()))))
        assert abs(float(v) - ref) < 1e-5

    def test_hsigmoid_per_sample_shape(self):
        layer = nn.HSigmoidLoss(8, 10)
        v = layer(paddle.to_tensor(np.random.randn(5, 8).astype("float32")),
                  paddle.to_tensor(np.random.randint(0, 10, (5,))))
        assert v.shape == [5, 1]

    def test_pairwise_distance_identical_inputs(self):
        x = paddle.to_tensor(np.random.randn(2, 512).astype("float32"))
        d = nn.functional.pairwise_distance(x, x)
        # eps perturbs the difference once: ~eps*sqrt(D), not eps*D
        assert float(np.abs(d.numpy()).max()) < 1e-4

    def test_fused_mha_cache_roundtrip(self):
        h = paddle.to_tensor(np.random.randn(1, 1, 8).astype("float32"))
        qkvw = paddle.to_tensor(np.random.randn(3, 2, 4, 8)
                                .astype("float32"))
        lw = paddle.to_tensor(np.eye(8, dtype="float32"))
        cache = paddle.zeros([2, 1, 2, 3, 4])  # (2, B, H, L=3, D)
        out, new_cache = \
            paddle.incubate.nn.functional.fused_multi_head_attention(
                h, qkvw, lw, cache_kv=cache, training=False)
        assert out.shape == [1, 1, 8]
        assert new_cache.shape == [2, 1, 2, 4, 4]

    def test_random_affine_shear_changes_image(self):
        from paddle_tpu.vision import transforms as T
        img = np.arange(3 * 9 * 9, dtype="float32").reshape(3, 9, 9)
        out = T.RandomAffine(degrees=0, shear=30)(img)
        assert out.shape == img.shape
        assert not np.allclose(out, img)

    def test_reduce_lr_single_step_per_epoch(self):
        cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                                patience=2, min_delta=0.0)

        class FakeOpt:
            lr = 0.1

            def get_lr(self):
                return self.lr

            def set_lr(self, v):
                self.lr = v

        class FakeModel:
            _optimizer = FakeOpt()

        cb.model = FakeModel()
        for _ in range(2):
            cb.on_epoch_end(0, {"loss": 1.0})
            cb.on_eval_end({"loss": 1.0})  # must NOT double-count
        assert cb.model._optimizer.lr == 0.1  # patience=2 not yet exhausted
        cb.on_epoch_end(0, {"loss": 1.0})
        assert cb.model._optimizer.lr == 0.05

    def test_audio_8bit_unsigned(self, tmp_path):
        import wave as wavelib
        path = tmp_path / "u8.wav"
        data = np.full(100, 128, np.uint8)  # silence in unsigned 8-bit
        with wavelib.open(str(path), "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(1)
            w.setframerate(8000)
            w.writeframes(data.tobytes())
        sig, sr = paddle.audio.backends.load(path)
        np.testing.assert_allclose(np.asarray(sig.numpy()), 0.0, atol=1e-6)


class TestWave4Ops:
    def test_trace(self):
        a = np.arange(12, dtype="float32").reshape(3, 4)
        np.testing.assert_allclose(paddle.trace(paddle.to_tensor(a)).numpy(),
                                   np.trace(a))
        np.testing.assert_allclose(
            paddle.trace(paddle.to_tensor(a), offset=1).numpy(),
            np.trace(a, offset=1))
        t = paddle.to_tensor(a, stop_gradient=False)
        paddle.trace(t).backward()
        np.testing.assert_allclose(t.grad.numpy(), np.eye(3, 4))

    def test_view_reshape_and_dtype(self):
        a = np.arange(8, dtype="float32")
        v = paddle.view(paddle.to_tensor(a), [2, 4])
        assert v.shape == [2, 4]
        b = paddle.view(paddle.to_tensor(a), "int32")
        assert str(b.dtype) == "int32"
        np.testing.assert_array_equal(b.numpy(), a.view(np.int32))
        # different-width reinterpret rescales the LAST dim (paddle.view)
        h = paddle.view(paddle.to_tensor(a), "float16")
        assert h.shape == [16], h.shape
        np.testing.assert_array_equal(h.numpy(), a.view(np.float16))
        back = paddle.view(h, "float32")
        assert back.shape == [8]
        np.testing.assert_allclose(back.numpy(), a)

    def test_polar(self):
        r = np.array([1.0, 2.0], "float32")
        t = np.array([0.0, np.pi / 2], "float32")
        z = paddle.polar(paddle.to_tensor(r), paddle.to_tensor(t)).numpy()
        np.testing.assert_allclose(z, r * np.exp(1j * t), atol=1e-6)

    def test_pdist(self):
        x = np.random.default_rng(0).normal(0, 1, (5, 3)).astype("float32")
        got = paddle.pdist(paddle.to_tensor(x)).numpy()
        from scipy.spatial.distance import pdist as sp_pdist
        np.testing.assert_allclose(got, sp_pdist(x), rtol=1e-5)

    def test_igamma_igammac(self):
        from scipy.special import gammainc, gammaincc
        x = np.array([1.0, 2.0, 3.0], "float32")
        a = np.array([0.5, 1.5, 2.5], "float32")
        # reference naming is inverted vs scipy: igamma == upper Q
        np.testing.assert_allclose(
            paddle.igamma(paddle.to_tensor(x), paddle.to_tensor(a)).numpy(),
            gammaincc(x, a), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.igammac(paddle.to_tensor(x), paddle.to_tensor(a)).numpy(),
            gammainc(x, a), rtol=1e-5)

    def test_sinc(self):
        x = np.array([-1.5, 0.0, 0.5], "float32")
        np.testing.assert_allclose(paddle.sinc(paddle.to_tensor(x)).numpy(),
                                   np.sinc(x), rtol=1e-6)

    def test_reduce_as(self):
        x = np.random.rand(4, 3, 2).astype("float32")
        tgt = np.zeros((3, 1), "float32")
        got = paddle.reduce_as(paddle.to_tensor(x),
                               paddle.to_tensor(tgt)).numpy()
        np.testing.assert_allclose(got, x.sum(axis=0).sum(axis=1,
                                                          keepdims=True),
                                   rtol=1e-6)

    def test_log_normal_and_geometric(self):
        paddle.seed(7)
        s = paddle.log_normal(mean=0.0, std=0.5, shape=[2000])
        logs = np.log(s.numpy())
        assert abs(logs.mean()) < 0.1 and abs(logs.std() - 0.5) < 0.1
        t = paddle.to_tensor(np.zeros(2000, "float32"))
        t.geometric_(0.3)
        vals = t.numpy()
        assert vals.min() >= 1
        assert abs(vals.mean() - 1 / 0.3) < 0.4


class TestWave5Ops:
    def test_max_unpool_1d_3d_roundtrip(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(
            np.arange(16, dtype="float32").reshape(1, 1, 16))
        pooled, idx = F.max_pool1d(x, kernel_size=2, stride=2,
                                   return_mask=True)
        restored = F.max_unpool1d(pooled, idx, kernel_size=2)
        dense = np.zeros(16, "float32")
        dense[1::2] = np.arange(16, dtype="float32")[1::2]
        np.testing.assert_allclose(restored.numpy().ravel(), dense)

        # 3-D: hand-built indices (max_pool3d has no mask mode): place the
        # pooled values at known flat positions of the 4x4x4 output
        vals = np.array([[[ [[10., 20.], [30., 40.]],
                            [[50., 60.], [70., 80.]] ]]], "float32")
        idx = np.array([[[ [[21, 23], [29, 31]],
                           [[53, 55], [61, 63]] ]]], "int32")
        r3 = F.max_unpool3d(paddle.to_tensor(vals), paddle.to_tensor(idx),
                            kernel_size=2)
        assert r3.shape == [1, 1, 4, 4, 4]
        flat = r3.numpy().ravel()
        np.testing.assert_allclose(flat[idx.ravel()], vals.ravel())
        assert flat.sum() == vals.sum()

    def test_fractional_max_pool2d(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(
            np.arange(49, dtype="float32").reshape(1, 1, 7, 7))
        out, mask = F.fractional_max_pool2d(x, output_size=3,
                                            random_u=0.3, return_mask=True)
        assert out.shape == [1, 1, 3, 3]
        # regions tile the input: global max must survive
        assert float(out.numpy().max()) == 48.0
        flat = x.numpy().ravel()
        np.testing.assert_allclose(
            np.take(flat, mask.numpy().ravel()), out.numpy().ravel())

    def test_cartesian_prod_numel_cumsum_(self):
        a = paddle.to_tensor(np.array([1, 2], "int32"))
        b = paddle.to_tensor(np.array([3, 4, 5], "int32"))
        cp = paddle.cartesian_prod([a, b]).numpy()
        assert cp.shape == (6, 2)
        assert (cp[0] == [1, 3]).all() and (cp[-1] == [2, 5]).all()
        assert int(paddle.numel(paddle.to_tensor(np.zeros((3, 4))))) == 12
        t = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"))
        t.cumsum_()
        np.testing.assert_allclose(t.numpy(), [1.0, 3.0, 6.0])

    def test_svd_lowrank(self):
        from paddle_tpu import linalg
        rng = np.random.default_rng(0)
        # a genuinely low-rank matrix is recovered to tolerance
        A = (rng.normal(0, 1, (20, 4)) @ rng.normal(0, 1, (4, 15))
             ).astype("float32")
        u, s_, v = linalg.svd_lowrank(paddle.to_tensor(A), q=6)
        rec = u.numpy() @ np.diag(s_.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, A, atol=1e-3)


class TestMethodWave:
    def test_bound_linalg_methods(self):
        t = paddle.to_tensor(np.eye(3, dtype="float32") * 4)
        np.testing.assert_allclose(t.cholesky().numpy(), np.eye(3) * 2,
                                   rtol=1e-5)
        x = paddle.to_tensor(np.array([[2.0, 0.0], [0.0, 2.0]], "float32"))
        sol = x.solve(paddle.to_tensor(np.array([[2.0], [4.0]], "float32")))
        np.testing.assert_allclose(sol.numpy(), [[1.0], [2.0]], rtol=1e-5)

    def test_unstack_increment_is_empty_floor_mod(self):
        parts = paddle.to_tensor(
            np.arange(6, dtype="float32").reshape(2, 3)).unstack(axis=0)
        assert len(parts) == 2 and parts[0].shape == [3]
        np.testing.assert_allclose(parts[1].numpy(), [3.0, 4.0, 5.0])
        c = paddle.to_tensor(np.asarray(1.0, "float32"))
        paddle.increment(c, 2.5)
        assert float(c) == 3.5
        assert bool(paddle.is_empty(
            paddle.to_tensor(np.zeros((0, 3), "float32"))))
        np.testing.assert_allclose(
            paddle.floor_mod(paddle.to_tensor(np.array([7.0], "float32")),
                             paddle.to_tensor(np.array([3.0], "float32"))
                             ).numpy(), [1.0])

    def test_incubate_fused_softmax_and_identity_loss(self):
        import paddle_tpu.incubate as inc
        x = paddle.to_tensor(np.random.rand(2, 2, 4, 4).astype("float32"),
                             stop_gradient=False)
        out = inc.softmax_mask_fuse_upper_triangle(x)
        o = out.numpy()
        np.testing.assert_allclose(o.sum(-1), np.ones((2, 2, 4)), rtol=1e-5)
        assert (o[..., 0, 1:] < 1e-6).all()
        inc.identity_loss(out, reduction="mean").backward()
        assert x.grad is not None


# ---------------------------------------------------------------------------
# einsum edge-case wave (VERDICT r2 weak #8: the reference treats einsum as
# a heavily-tested surface — upstream test/legacy_test/test_einsum*.py)
# ---------------------------------------------------------------------------

class TestEinsumEdgeCases:
    def _t(self, shape, seed=0):
        rng = np.random.default_rng(seed)
        a = rng.normal(0, 1, shape).astype(np.float32)
        return paddle.to_tensor(a), a

    @pytest.mark.parametrize("eq,shapes", [
        ("ij,jk->ik", [(3, 4), (4, 5)]),            # matmul
        ("ij->ji", [(3, 4)]),                        # transpose
        ("ij->", [(3, 4)]),                          # full sum
        ("ij->j", [(3, 4)]),                         # axis sum
        ("ii->i", [(4, 4)]),                         # diagonal
        ("ii->", [(4, 4)]),                          # trace
        ("ij,ij->ij", [(3, 4), (3, 4)]),             # hadamard
        ("i,j->ij", [(3,), (4,)]),                   # outer
        ("bij,bjk->bik", [(2, 3, 4), (2, 4, 5)]),    # bmm
        ("...ij,...jk->...ik", [(2, 2, 3, 4), (2, 2, 4, 5)]),  # ellipsis bmm
        ("...i->...", [(2, 3, 4)]),                  # ellipsis sum
        ("i...,i...->...", [(3, 2, 4), (3, 2, 4)]),  # leading ellipsis
        ("ij,jk,kl->il", [(2, 3), (3, 4), (4, 5)]),  # 3-operand chain
        ("ijk,ikl->ijl", [(2, 3, 4), (2, 4, 5)]),
        ("ab,cb->ac", [(3, 4), (5, 4)]),             # shared contracted
        ("i,i->", [(5,), (5,)]),                     # dot
    ])
    def test_matches_numpy(self, eq, shapes):
        ts, arrs = zip(*[self._t(s, i) for i, s in enumerate(shapes)])
        got = paddle.einsum(eq, *ts).numpy()
        want = np.einsum(eq, *arrs)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    def test_implicit_output_mode(self):
        # no '->': output labels are the sorted non-repeated labels
        t1, a1 = self._t((3, 4), 1)
        t2, a2 = self._t((4, 5), 2)
        np.testing.assert_allclose(paddle.einsum("ij,jk", t1, t2).numpy(),
                                   np.einsum("ij,jk", a1, a2),
                                   rtol=2e-5, atol=2e-6)

    def test_broadcast_dims(self):
        t1, a1 = self._t((1, 4), 3)
        t2, a2 = self._t((3, 4), 4)
        np.testing.assert_allclose(
            paddle.einsum("...j,...j->...", t1, t2).numpy(),
            np.einsum("...j,...j->...", a1, a2), rtol=2e-5, atol=2e-6)

    def test_bad_equation_raises_with_diagnostics(self):
        t1, _ = self._t((3, 4))
        t2, _ = self._t((4, 5))
        with pytest.raises(Exception):
            paddle.einsum("ij,jk->iq", t1, t2)       # unknown output label
        with pytest.raises(Exception):
            paddle.einsum("ij,kk->ik", t1, t2)       # shape mismatch for k
        with pytest.raises(Exception):
            paddle.einsum("ijj->i", t1)              # rank mismatch

    def test_einsum_grad_flows(self):
        t1, a1 = self._t((3, 4), 5)
        t2, a2 = self._t((4, 5), 6)
        t1.stop_gradient = False
        t2.stop_gradient = False
        paddle.einsum("ij,jk->ik", t1, t2).sum().backward()
        np.testing.assert_allclose(np.asarray(t1.grad._data),
                                   np.ones((3, 5)) @ a2.T, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(t2.grad._data),
                                   a1.T @ np.ones((3, 5)), rtol=2e-5)


class TestTopLevelTailOps:
    """Round-3 probe additions: add_n / remainder / rank / shape /
    shard_index / is_tensor (upstream python/paddle/tensor/ surface)."""

    def test_add_n(self):
        a = paddle.to_tensor(np.ones((2, 3), np.float32))
        b = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        np.testing.assert_allclose(paddle.add_n([a, b, b]).numpy(), 5.0)
        # gradient splits to every addend
        a.stop_gradient = False
        paddle.add_n([a, a]).sum().backward()
        np.testing.assert_allclose(np.asarray(a.grad._data), 2.0)

    def test_remainder_and_alias(self):
        x = paddle.to_tensor(np.array([7.0, -7.0], np.float32))
        y = paddle.to_tensor(np.array([3.0, 3.0], np.float32))
        np.testing.assert_allclose(paddle.remainder(x, y).numpy(),
                                   np.array([1.0, 2.0]))  # python semantics

    def test_rank_and_shape(self):
        t = paddle.to_tensor(np.zeros((4, 5, 6), np.float32))
        assert int(paddle.rank(t)) == 3
        assert int(t.rank()) == 3
        sh = paddle.shape(t)
        assert list(sh.numpy()) == [4, 5, 6]
        assert str(sh.numpy().dtype) == "int32"

    def test_shard_index(self):
        ids = paddle.to_tensor(np.array([0, 5, 9, 15], np.int64))
        out0 = paddle.shard_index(ids, 16, 2, 0)
        out1 = paddle.shard_index(ids, 16, 2, 1)
        assert list(out0.numpy()) == [0, 5, -1, -1]
        assert list(out1.numpy()) == [-1, -1, 1, 7]
        with pytest.raises(ValueError):
            paddle.shard_index(ids, 16, 2, 5)

    def test_is_tensor(self):
        assert paddle.is_tensor(paddle.to_tensor([1.0]))
        assert not paddle.is_tensor(np.zeros(3))


class TestRound3TailLayers:
    def test_lp_pool_matches_torch(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as TF
        import paddle_tpu.nn as pnn
        x = np.abs(np.random.default_rng(0).normal(
            0, 1, (2, 3, 16))).astype(np.float32)  # fractional p needs >=0
        for p_, k in ((2, 4), (3, 2), (1.5, 2)):
            got = pnn.LPPool1D(norm_type=p_, kernel_size=k)(
                paddle.to_tensor(x)).numpy()
            want = TF.lp_pool1d(torch.tensor(x), norm_type=p_,
                                kernel_size=k).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        x2 = np.random.default_rng(1).normal(0, 1, (2, 3, 8, 8)) \
            .astype(np.float32)
        got2 = pnn.LPPool2D(norm_type=2, kernel_size=2, stride=2)(
            paddle.to_tensor(x2)).numpy()
        want2 = TF.lp_pool2d(torch.tensor(x2), norm_type=2, kernel_size=2,
                             stride=2).numpy()
        np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-5)

    def test_pca_lowrank_reconstructs(self):
        rng = np.random.default_rng(2)
        # rank-3 data + noise
        base = rng.normal(0, 1, (40, 3)) @ rng.normal(0, 1, (3, 10))
        x = (base + 0.01 * rng.normal(0, 1, (40, 10))).astype(np.float32)
        u, s, v = paddle.linalg.pca_lowrank(paddle.to_tensor(x), q=3)
        centered = x - x.mean(0)
        recon = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        err = np.linalg.norm(recon - centered) / np.linalg.norm(centered)
        assert err < 0.05, err
        assert s.shape == [3]


class TestFunctionalTail:
    def test_bilinear_matches_torch(self):
        torch = pytest.importorskip("torch")
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(0)
        x1 = rng.normal(0, 1, (4, 5)).astype(np.float32)
        x2 = rng.normal(0, 1, (4, 6)).astype(np.float32)
        w = rng.normal(0, 1, (3, 5, 6)).astype(np.float32)
        b = rng.normal(0, 1, (3,)).astype(np.float32)
        got = F.bilinear(paddle.to_tensor(x1), paddle.to_tensor(x2),
                         paddle.to_tensor(w), paddle.to_tensor(b)).numpy()
        want = torch.nn.functional.bilinear(
            torch.tensor(x1), torch.tensor(x2), torch.tensor(w),
            torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_gather_tree_matches_reference_algorithm(self):
        """Canonical upstream recurrence, checked against an explicit
        per-beam numpy backtrace."""
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(1)
        T, B, W = 5, 3, 4
        ids = rng.integers(0, 9, (T, B, W)).astype(np.int64)
        parents = rng.integers(0, W, (T, B, W)).astype(np.int64)

        ref = np.zeros_like(ids)
        for b in range(B):
            for w in range(W):
                parent = parents[T - 1, b, w]
                ref[T - 1, b, w] = ids[T - 1, b, w]
                for t in range(T - 2, -1, -1):
                    ref[t, b, w] = ids[t, b, parent]
                    parent = parents[t, b, parent]

        got = F.gather_tree(paddle.to_tensor(ids),
                            paddle.to_tensor(parents)).numpy()
        np.testing.assert_array_equal(got, ref)


class TestRound4TailOps:
    """Round-4 API-tail wave: msort, float_power, binomial, crop,
    bernoulli_/normal_ in-place fills (reference python/paddle/tensor/)."""

    def test_msort(self):
        x = np.random.default_rng(0).normal(0, 1, (5, 4)).astype(np.float32)
        np.testing.assert_allclose(paddle.msort(paddle.to_tensor(x)).numpy(),
                                   np.sort(x, axis=0))

    def test_float_power(self):
        x = np.random.default_rng(1).uniform(0.5, 3, (8,)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.float_power(paddle.to_tensor(x), 2.5).numpy(),
            np.float_power(x, 2.5).astype(np.float32), rtol=1e-5)
        y = np.full((8,), 1.5, np.float32)
        np.testing.assert_allclose(
            paddle.float_power(paddle.to_tensor(x),
                               paddle.to_tensor(y)).numpy(),
            np.float_power(x, y).astype(np.float32), rtol=1e-5)

    def test_crop(self):
        x = np.arange(60, dtype=np.float32).reshape(3, 4, 5)
        got = paddle.crop(paddle.to_tensor(x), shape=[2, 2, 3],
                          offsets=[1, 1, 2]).numpy()
        np.testing.assert_allclose(got, x[1:3, 1:3, 2:5])
        got = paddle.crop(paddle.to_tensor(x), shape=[-1, 2, -1]).numpy()
        np.testing.assert_allclose(got, x[:, :2, :])

    def test_binomial_moments(self):
        paddle.seed(0)
        n = paddle.to_tensor(np.full((4000,), 20.0, np.float32))
        p = paddle.to_tensor(np.full((4000,), 0.3, np.float32))
        s = paddle.binomial(n, p).numpy()
        assert np.issubdtype(s.dtype, np.integer)
        assert s.min() >= 0 and s.max() <= 20
        assert abs(s.mean() - 6.0) < 0.3          # n*p
        assert abs(s.var() - 4.2) < 0.6           # n*p*(1-p)

    def test_inplace_random_fills(self):
        paddle.seed(1)
        t = paddle.to_tensor(np.zeros((6000,), np.float32))
        out = t.bernoulli_(0.25)
        assert out is t
        vals = t.numpy()
        assert set(np.unique(vals)).issubset({0.0, 1.0})
        assert 0.22 < vals.mean() < 0.28
        t2 = paddle.to_tensor(np.zeros((6000,), np.float32))
        paddle.normal_(t2, mean=2.0, std=0.5)
        assert abs(t2.numpy().mean() - 2.0) < 0.05
        assert abs(t2.numpy().std() - 0.5) < 0.05
