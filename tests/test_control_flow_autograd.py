"""Control-flow ops, TensorArray, and functional autograd parity tests.

Mirrors the reference's test style (test/legacy_test/test_while_loop_op.py,
test_cond.py, test_switch_case.py, test_tensor_array_*.py,
test_autograd_functional_dynamic.py): numpy references, eager + compiled.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn


class TestWhileLoop:
    def test_counter_sum(self):
        i = paddle.to_tensor(0)
        s = paddle.to_tensor(0.0)
        i2, s2 = snn.while_loop(lambda i, s: i < 5,
                                lambda i, s: [i + 1, s + 2.0], [i, s])
        assert int(i2) == 5
        assert float(s2) == 10.0

    def test_matrix_state(self):
        x = paddle.to_tensor(np.eye(3, dtype=np.float32))
        (y,) = snn.while_loop(lambda t: t.sum() < 24.0,
                              lambda t: [t * 2.0], [x])
        assert float(y.sum()) == 24.0

    def test_inside_jit(self):
        @paddle.jit.to_static
        def f(n, x):
            _, out = snn.while_loop(lambda i, a: i < n,
                                    lambda i, a: [i + 1, a * 2.0],
                                    [paddle.to_tensor(0), x])
            return out

        x = paddle.to_tensor(np.ones(4, np.float32))
        assert np.allclose(f(paddle.to_tensor(3), x).numpy(), 8.0)


class TestCond:
    def test_concrete_pred(self):
        r = snn.cond(paddle.to_tensor(True), lambda: paddle.to_tensor(1.0),
                     lambda: paddle.to_tensor(2.0))
        assert float(r) == 1.0

    def test_traced_pred(self):
        @paddle.jit.to_static
        def f(x):
            return snn.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        assert np.allclose(f(x).numpy(), [2, 4])
        assert np.allclose(f(-x).numpy(), [-2, -3])

    def test_nested_structure(self):
        @paddle.jit.to_static
        def f(x):
            a, b = snn.cond(x.sum() > 0,
                            lambda: (x, x + 1), lambda: (x - 1, x))
            return a + b

        x = paddle.to_tensor(np.array([1.0], np.float32))
        assert np.allclose(f(x).numpy(), [3.0])


class TestCaseSwitch:
    def test_case_first_match(self):
        r = snn.case([(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0)),
                      (paddle.to_tensor(True), lambda: paddle.to_tensor(2.0))],
                     default=lambda: paddle.to_tensor(3.0))
        assert float(r) == 2.0

    def test_case_default(self):
        r = snn.case([(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0))],
                     default=lambda: paddle.to_tensor(9.0))
        assert float(r) == 9.0

    def test_switch_case_jit(self):
        @paddle.jit.to_static
        def g(idx, x):
            return snn.switch_case(idx, {0: lambda: x + 1, 2: lambda: x * 3},
                                   default=lambda: x)

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        assert np.allclose(g(paddle.to_tensor(0), x).numpy(), [2, 3])
        assert np.allclose(g(paddle.to_tensor(2), x).numpy(), [3, 6])
        assert np.allclose(g(paddle.to_tensor(7), x).numpy(), [1, 2])

    def test_switch_case_eager_list(self):
        x = paddle.to_tensor(np.array([1.0], np.float32))
        r = snn.switch_case(paddle.to_tensor(1),
                            [lambda: x, lambda: x * 5])
        assert float(r) == 5.0


class TestTensorArray:
    def test_write_read_length(self):
        arr = paddle.create_array("float32")
        paddle.array_write(paddle.to_tensor([1.0, 2.0]), 0, arr)
        paddle.array_write(paddle.to_tensor([3.0, 4.0]), 1, arr)
        assert int(paddle.array_length(arr)) == 2
        assert np.allclose(paddle.array_read(arr, 1).numpy(), [3, 4])

    def test_to_tensor_stack_concat(self):
        arr = paddle.create_array(
            "float32", [np.ones((2,), np.float32), np.zeros((2,), np.float32)])
        t, _ = paddle.tensor_array_to_tensor(arr, axis=0, use_stack=True)
        assert list(t.shape) == [2, 2]
        t2, sizes = paddle.tensor_array_to_tensor(arr, axis=0, use_stack=False)
        assert list(t2.shape) == [4]
        assert sizes.numpy().tolist() == [2, 2]

    def test_overwrite(self):
        arr = paddle.create_array("float32")
        paddle.array_write(paddle.to_tensor([1.0]), 0, arr)
        paddle.array_write(paddle.to_tensor([7.0]), 0, arr)
        assert float(paddle.array_read(arr, 0)) == 7.0


class TestFunctionalAutograd:
    def test_jacobian_tensor_form(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        y = x * x
        J = paddle.autograd.jacobian(y, x)
        assert np.allclose(J.numpy(), np.diag([2.0, 4.0]))

    def test_jacobian_matrix_out(self):
        x = paddle.to_tensor(np.array([[1.0, 2.0]], np.float32),
                             stop_gradient=False)
        w = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 3.0]], np.float32))
        y = paddle.matmul(x, w)
        J = paddle.autograd.jacobian(y, x)  # (1,2,1,2)
        assert list(J.shape) == [1, 2, 1, 2]
        assert np.allclose(J.numpy().reshape(2, 2), w.numpy().T)

    def test_jacobian_functional(self):
        J = paddle.autograd.jacobian(
            lambda t: t * t, paddle.to_tensor(np.array([1.0, 3.0], np.float32)))
        assert np.allclose(J.numpy(), np.diag([2.0, 6.0]))

    def test_hessian_functional(self):
        H = paddle.autograd.hessian(
            lambda t: (t ** 3).sum(),
            paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
        assert np.allclose(H.numpy(), np.diag([6.0, 12.0]))

    def test_hessian_tensor_form_raises(self):
        x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
        y = (x * x).sum()
        with pytest.raises(NotImplementedError):
            paddle.autograd.hessian(y, x)

    def test_jvp_vjp(self):
        x = paddle.to_tensor(np.array([3.0], np.float32))
        _, t = paddle.autograd.jvp(lambda a: a * a, x)
        assert np.allclose(t.numpy(), [6.0])
        _, g = paddle.autograd.vjp(lambda a: a * a, x)
        assert np.allclose(g.numpy(), [6.0])

    def test_jacobian_class(self):
        J = paddle.autograd.Jacobian(
            lambda t: t * 2.0, paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
        assert np.allclose(np.asarray(J[0, 0]), 2.0)


class TestNamespaceParity:
    def test_clip_in_nn(self):
        assert paddle.nn.ClipGradByGlobalNorm is not None
        assert paddle.nn.ClipGradByNorm is not None
        assert paddle.nn.ClipGradByValue is not None

    def test_regularizer_module(self):
        r = paddle.regularizer.L2Decay(1e-4)
        assert r is not None
        assert paddle.regularizer.L1Decay(1e-4) is not None

    def test_sharding_namespace(self):
        assert callable(paddle.distributed.sharding.group_sharded_parallel)
        assert callable(paddle.distributed.group_sharded_parallel)


class TestControlFlowGradients:
    """Gradients THROUGH control-flow ops (reference: while_op/
    conditional_block_op grad support in paddle/fluid/operators/controlflow/)."""

    def test_cond_grad_eager(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        y = snn.cond(paddle.to_tensor(True), lambda: x * 2, lambda: x - 1)
        y.sum().backward()
        assert x.grad is not None
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_cond_grad_traced(self):
        @paddle.jit.to_static
        def f(x):
            y = snn.cond(x.sum() > 0, lambda: x * 3, lambda: x * 5)
            y.sum().backward()
            return y

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        f(x)
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])
        x2 = paddle.to_tensor(np.array([-1.0, -2.0], np.float32),
                              stop_gradient=False)
        f(x2)
        np.testing.assert_allclose(x2.grad.numpy(), [5.0, 5.0])

    def test_switch_case_grad_traced(self):
        @paddle.jit.to_static
        def f(idx, x):
            y = snn.switch_case(idx, {0: lambda: x * 2, 1: lambda: x * 7},
                                default=lambda: x * 0)
            y.sum().backward()
            return y

        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        f(paddle.to_tensor(1), x)
        np.testing.assert_allclose(x.grad.numpy(), [7.0, 7.0, 7.0])

    def test_while_loop_grad_eager(self):
        x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
        i = paddle.to_tensor(0)
        i2, y = snn.while_loop(lambda i, a: i < 3,
                               lambda i, a: [i + 1, a * 2.0], [i, x])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])  # d(8x)/dx

    def test_while_loop_grad_traced_raises(self):
        @paddle.jit.to_static
        def f(n, x):
            _, y = snn.while_loop(lambda i, a: i < n,
                                  lambda i, a: [i + 1, a * 2.0],
                                  [paddle.to_tensor(0), x])
            return y

        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        with pytest.raises(RuntimeError, match="not .*differentiable|while_loop"):
            f(paddle.to_tensor(3), x)
