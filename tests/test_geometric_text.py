"""paddle.geometric / paddle.text / incubate.nn tests (reference:
test/legacy_test/test_graph_send_recv_op.py numpy refs, test_viterbi_decode,
fused-transformer equivalence vs the unfused composition)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import geometric


class TestMessagePassing:
    def setup_method(self, _):
        # graph: 0->1, 0->2, 1->2, 2->0
        self.src = paddle.to_tensor(np.array([0, 0, 1, 2], np.int64))
        self.dst = paddle.to_tensor(np.array([1, 2, 2, 0], np.int64))
        self.x = paddle.to_tensor(np.array(
            [[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32))

    def test_send_u_recv_sum(self):
        out = geometric.send_u_recv(self.x, self.src, self.dst, "sum")
        ref = np.array([[5, 6], [1, 2], [4, 6]], np.float32)
        np.testing.assert_allclose(np.asarray(out._data), ref)

    def test_send_u_recv_mean_max(self):
        out = geometric.send_u_recv(self.x, self.src, self.dst, "mean")
        ref = np.array([[5, 6], [1, 2], [2, 3]], np.float32)
        np.testing.assert_allclose(np.asarray(out._data), ref)
        out = geometric.send_u_recv(self.x, self.src, self.dst, "max")
        ref = np.array([[5, 6], [1, 2], [3, 4]], np.float32)
        np.testing.assert_allclose(np.asarray(out._data), ref)

    def test_send_u_recv_grad(self):
        x = paddle.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
        out = geometric.send_u_recv(x, self.src, self.dst, "sum")
        out.sum().backward()
        # node 0 sent twice, nodes 1/2 once each
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   [[2, 2], [1, 1], [1, 1]])

    def test_send_ue_recv(self):
        e = paddle.to_tensor(np.full((4, 2), 10.0, np.float32))
        out = geometric.send_ue_recv(self.x, e, self.src, self.dst,
                                     "add", "sum")
        ref = np.array([[15, 16], [11, 12], [24, 26]], np.float32)
        np.testing.assert_allclose(np.asarray(out._data), ref)

    def test_send_uv(self):
        out = geometric.send_uv(self.x, self.x, self.src, self.dst, "mul")
        ref = np.asarray(self.x._data)[np.array([0, 0, 1, 2])] * \
            np.asarray(self.x._data)[np.array([1, 2, 2, 0])]
        np.testing.assert_allclose(np.asarray(out._data), ref)

    def test_segment_ops(self):
        data = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
        ids = paddle.to_tensor(np.array([0, 0, 1, 1], np.int64))
        np.testing.assert_allclose(
            np.asarray(geometric.segment_sum(data, ids)._data),
            [[2, 4], [10, 12]])
        np.testing.assert_allclose(
            np.asarray(geometric.segment_mean(data, ids)._data),
            [[1, 2], [5, 6]])
        np.testing.assert_allclose(
            np.asarray(geometric.segment_min(data, ids)._data),
            [[0, 1], [4, 5]])

    def test_sample_and_reindex(self):
        # CSC: node j's neighbors = row[colptr[j]:colptr[j+1]]
        row = paddle.to_tensor(np.array([1, 2, 0, 0, 1], np.int64))
        colptr = paddle.to_tensor(np.array([0, 2, 3, 5], np.int64))
        nodes = paddle.to_tensor(np.array([0, 2], np.int64))
        nbrs, cnt = geometric.sample_neighbors(row, colptr, nodes)
        assert np.asarray(cnt._data).tolist() == [2, 2]
        src, dst, uniq = geometric.reindex_graph(nodes, nbrs, cnt)
        assert np.asarray(uniq._data)[0] == 0 and np.asarray(uniq._data)[1] == 2
        assert np.asarray(dst._data).tolist() == [0, 0, 1, 1]


class TestText:
    def test_datasets_shapes(self):
        ds = paddle.text.Imdb(mode="train")
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label in (0, 1)
        h = paddle.text.UCIHousing(mode="test")
        x, y = h[0]
        assert x.shape == (13,) and y.shape == (1,)
        c = paddle.text.Conll05st(mode="test")
        words, pred, mark, labels = c[0]
        assert len(words) == len(labels)
        m = paddle.text.Movielens(mode="test")
        assert len(m[0]) == 7

    def test_viterbi_decode_matches_bruteforce(self):
        rng = np.random.default_rng(0)
        b, l, t = 2, 5, 3
        emis = rng.normal(size=(b, l, t)).astype(np.float32)
        trans = rng.normal(size=(t, t)).astype(np.float32)
        scores, path = paddle.text.viterbi_decode(
            paddle.to_tensor(emis), paddle.to_tensor(trans),
            include_bos_eos_tag=False)
        # brute force over all t^l paths
        import itertools
        for bi in range(b):
            best, best_path = -1e9, None
            for p in itertools.product(range(t), repeat=l):
                s = emis[bi, 0, p[0]]
                for i in range(1, l):
                    s += trans[p[i - 1], p[i]] + emis[bi, i, p[i]]
                if s > best:
                    best, best_path = s, p
            assert abs(float(scores._data[bi]) - best) < 1e-3
            assert np.asarray(path._data)[bi].tolist() == list(best_path)


class TestFusedLayers:
    def test_fused_mha_runs_and_trains(self):
        paddle.seed(0)
        layer = paddle.incubate.nn.FusedMultiHeadAttention(
            32, 4, dropout_rate=0.0, attn_dropout_rate=0.0)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 6, 32)).astype(np.float32))
        out = layer(x)
        assert list(out.shape) == [2, 6, 32]
        out.mean().backward()
        assert layer.qkv.weight.grad is not None

    def test_fused_ffn_matches_manual(self):
        paddle.seed(0)
        ffn = paddle.incubate.nn.FusedFeedForward(16, 32, dropout_rate=0.0,
                                                  act_dropout_rate=0.0)
        ffn.eval()
        x = paddle.to_tensor(np.random.default_rng(1).normal(
            size=(2, 4, 16)).astype(np.float32))
        got = np.asarray(ffn(x)._data)
        import paddle_tpu.nn.functional as F
        manual = ffn.ln(x + ffn.linear2(F.relu(ffn.linear1(x))))
        np.testing.assert_allclose(got, np.asarray(manual._data), atol=1e-5)

    def test_fused_linear(self):
        lin = paddle.incubate.nn.FusedLinear(4, 8)
        out = lin(paddle.to_tensor(np.ones((2, 4), np.float32)))
        assert list(out.shape) == [2, 8]


    def test_viterbi_bos_eos_and_lengths(self):
        rng = np.random.default_rng(3)
        b, l, t = 2, 4, 5  # tags 3=BOS, 4=EOS under the reference convention
        emis = rng.normal(size=(b, l, t)).astype(np.float32)
        trans = rng.normal(size=(t, t)).astype(np.float32)
        lengths = np.array([2, 4], np.int64)
        scores, path = paddle.text.viterbi_decode(
            paddle.to_tensor(emis), paddle.to_tensor(trans),
            paddle.to_tensor(lengths), include_bos_eos_tag=True)
        import itertools
        for bi, ln in enumerate(lengths):
            best, best_path = -1e9, None
            for p in itertools.product(range(t), repeat=int(ln)):
                s = trans[t - 2, p[0]] + emis[bi, 0, p[0]]
                for i in range(1, int(ln)):
                    s += trans[p[i - 1], p[i]] + emis[bi, i, p[i]]
                s += trans[p[-1], t - 1]
                if s > best:
                    best, best_path = s, p
            assert abs(float(scores._data[bi]) - best) < 1e-3
            got = np.asarray(path._data)[bi][:int(ln)].tolist()
            assert got == list(best_path), (bi, got, best_path)
