"""Smoke-run every example script (the BASELINE configs) in a subprocess on
the CPU mesh — the scripts are user-facing entry points and must stay
runnable."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    ("train_resnet.py", ["--steps", "2", "--batch", "8",
                         "--image-size", "32", "--arch", "resnet18"]),
    ("finetune_bert.py", ["--steps", "2"]),
    ("train_ppyoloe.py", ["--steps", "1", "--image-size", "64"]),
    ("train_llama_hybrid.py", ["--dp", "2", "--mp", "2", "--steps", "2"]),
    ("train_deepfm.py", ["--steps", "2", "--batch", "32"]),
]


@pytest.mark.parametrize("script,args", CASES, ids=[c[0] for c in CASES])
def test_example_runs(script, args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert out.returncode == 0, f"{script} failed:\n{out.stdout}\n{out.stderr}"
    assert "loss" in out.stdout
