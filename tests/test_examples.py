"""Smoke-run every example script (the BASELINE configs) in a subprocess on
the CPU mesh — the scripts are user-facing entry points and must stay
runnable."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    ("train_resnet.py", ["--steps", "2", "--batch", "8",
                         "--image-size", "32", "--arch", "resnet18"]),
    ("finetune_bert.py", ["--steps", "2"]),
    ("train_ppyoloe.py", ["--steps", "1", "--image-size", "64"]),
    ("train_llama_hybrid.py", ["--dp", "2", "--mp", "2", "--steps", "2"]),
    ("train_deepfm.py", ["--steps", "2", "--batch", "32"]),
]


@pytest.mark.parametrize("script,args", CASES, ids=[c[0] for c in CASES])
@pytest.mark.slow
def test_example_runs(script, args):
    env = dict(os.environ)
    # plain JAX_PLATFORMS env is latched away by TPU-plugin sitecustomize
    # hooks; the examples pin programmatically from these vars instead
    env["PADDLE_PLATFORM"] = "cpu"
    env["PADDLE_PLATFORM_DEVICE_COUNT"] = "8"
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert out.returncode == 0, f"{script} failed:\n{out.stdout}\n{out.stderr}"
    assert "loss" in out.stdout


@pytest.mark.slow
@pytest.mark.tpu
@pytest.mark.parametrize("script,args", CASES, ids=[c[0] for c in CASES])
def test_example_runs_on_chip(script, args):
    """Hardware smoke: the same entry points must run on the real device
    (regression guard for compiled-program bugs the CPU mesh can't see,
    e.g. the round-1 aliased-donation INVALID_ARGUMENT)."""
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"
             or v != "cpu"}, cwd=ROOT)
    if "tpu" not in probe.stdout.lower():
        pytest.skip(f"no real accelerator visible: {probe.stdout.strip()!r}")
    env = dict(os.environ)
    env.pop("PADDLE_PLATFORM", None)
    if env.get("JAX_PLATFORMS") == "cpu":
        del env["JAX_PLATFORMS"]
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split() if "host_platform_device_count" not in f)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=560, env=env, cwd=ROOT)
    assert out.returncode == 0, f"{script} failed:\n{out.stdout}\n{out.stderr}"
    assert "loss" in out.stdout
