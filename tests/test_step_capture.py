"""Whole-step static capture with buffer donation (ISSUE 11).

The acceptance surface for ``core/step_capture.py`` /
``paddle.jit.capture_step``:

* **one executable per signature** — N supervised steps compile exactly
  one XLA program (``jit.compiles_total == 1``) and a warm captured step
  performs exactly ONE dispatch (zero eager op dispatches leak around the
  program call); shape changes and ``set_flags`` writes re-trace instead
  of serving a stale executable;
* **parity** — the captured trajectory tracks the eager tier at ulp
  scale on both optimizer legs (fp32 Adam and int8 block-quantized
  moments). NOT bitwise, by measurement and by construction: XLA
  contracts ``a*x + b*y`` to FMA inside the fused whole-step kernel,
  which per-op eager dispatch cannot express (micro-repro:
  ``jit(lambda: b1*m + (1-b1)*g)`` differs from the op-by-op value by
  1 ulp, with ``--xla_allow_excess_precision=false`` making no
  difference). The forward alone IS bitwise — pinned on step 1;
* **bitwise within the captured tier** — identical captured runs are
  bit-identical, kill-at-step resume under the PR 10 supervisor running
  the captured path continues bit-identically, and donation never leaves
  state readable-after-donate (save → restore → continue);
* **NaN gate** — a non-finite loss withholds the folded update
  in-program: parameters, moments, step count bitwise untouched;
* **clean bypasses** — seams (live trace, dispatch.* fault injection,
  ``off``) run the eager tier with identical semantics, counted by
  reason; per-step host writes into carried state (``scheduler.step()``
  inside the captured update) raise typed, never serve stale constants.
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import step_capture as sc
from paddle_tpu.core.tensor import Parameter
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.trainer import TrainingSupervisor


@pytest.fixture(autouse=True)
def _capture_on(monkeypatch):
    """The suite default is PADDLE_TPU_STEP_CAPTURE=off (conftest); this
    module is the captured tier's own suite."""
    monkeypatch.setenv("PADDLE_TPU_STEP_CAPTURE", "auto")
    sc.stats_clear()
    yield
    sc.stats_clear()


def build_run(seed=7, *, q8=False, n=32, batch_size=8, shuffle=True):
    """One complete training setup, as a fresh process would construct it
    (the test_train_chaos pattern: param names must be deterministic per
    construction order)."""
    Parameter._param_counter = 0
    paddle.seed(seed)
    net = paddle.nn.Linear(8, 4)
    kw = dict(moment_dtype="int8") if q8 else {}
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters(), **kw)
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, 8)).astype(np.float32)
    ys = rng.normal(size=(n, 4)).astype(np.float32)
    ds = paddle.io.TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
    loader = paddle.io.DataLoader(ds, batch_size=batch_size, shuffle=shuffle)
    loss_fn = paddle.nn.MSELoss()

    def step_fn(batch):
        x, y = batch
        loss = loss_fn(net(x), y)
        loss.backward()
        return loss

    def update_fn():
        opt.step()
        opt.clear_grad()

    def clear_fn():
        opt.clear_grad()

    from types import SimpleNamespace
    return SimpleNamespace(net=net, opt=opt, loader=loader, loss=loss_fn,
                           step=step_fn, update=update_fn, clear=clear_fn)


def run_supervised(r, tmpdir=None, *, epochs=2, save_every=2, **knobs):
    sup = TrainingSupervisor(r.net, r.opt, r.loader,
                             ckpt_dir=str(tmpdir) if tmpdir else None,
                             save_every=save_every, **knobs)
    return sup.run(r.step, r.loader, epochs=epochs, update_fn=r.update,
                   clear_fn=r.clear)


def eager_losses(*, q8=False, steps=12, monkeypatch=None):
    """The eager-tier trajectory of the same run (capture off)."""
    os.environ["PADDLE_TPU_STEP_CAPTURE"] = "off"
    try:
        r = build_run(q8=q8, shuffle=False)
        out = []
        for _ in range(3):
            for batch in r.loader:
                loss = r.step(batch)
                r.update()
                out.append(float(np.asarray(loss._data)))
                if len(out) >= steps:
                    return out
        return out
    finally:
        os.environ["PADDLE_TPU_STEP_CAPTURE"] = "auto"


def captured_losses(*, q8=False, steps=12):
    r = build_run(q8=q8, shuffle=False)
    cap = sc.capture_step(r.step, update_fn=r.update, clear_fn=r.clear)
    out = []
    for _ in range(3):
        for batch in r.loader:
            loss = cap(batch)
            out.append(float(np.asarray(loss._data)))
            if len(out) >= steps:
                return out
    return out


# ---------------------------------------------------------------------------
# one executable per signature, one dispatch per step
# ---------------------------------------------------------------------------

class TestOneProgramPerSignature:
    def test_supervised_run_compiles_exactly_one_program(self, metrics):
        r = build_run()
        run_supervised(r, None, epochs=2, save_every=0)
        snap = metrics.snapshot()
        # 2 epochs x 4 batches = 8 steps: ONE compiled program, 7 hits
        assert snap.get("jit.compiles_total", 0) == 1
        assert snap.get("train.capture_retraces_total", 0) == 1
        assert snap.get("train.capture_hits_total", 0) == 7
        assert "train.capture_bypasses_total" not in snap
        assert snap.get("train.capture_donated_bytes", 0) > 0

    def test_warm_captured_step_is_one_dispatch(self, metrics):
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update)
        batch = next(iter(r.loader))
        cap(batch)          # trace + compile
        before = metrics.snapshot().get("dispatch.ops_total", 0)
        cap(batch)          # warm: the single program call, zero eager ops
        after = metrics.snapshot().get("dispatch.ops_total", 0)
        assert after - before == 0
        assert cap.stats == {"hits": 1, "retraces": 1, "bypasses": {}}

    def test_shape_change_retraces_never_stale(self):
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update)
        it = iter(r.loader)
        b = next(it)
        cap(b)
        small = [b[0][:3], b[1][:3]]      # new leading dim: new signature
        cap(small)
        assert cap.stats["retraces"] == 2

    def test_flags_epoch_retraces(self):
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update)
        batch = next(iter(r.loader))
        cap(batch)
        cap(batch)
        assert cap.stats["retraces"] == 1
        # any runtime set_flags bumps the epoch: compiled steps bake flag
        # reads at trace time, so the old program must never be served
        paddle.set_flags({"FLAGS_log_level": 0})
        cap(batch)
        assert cap.stats["retraces"] == 2

    def test_closure_scalar_mutation_retraces(self):
        # the PR 2 structural signature keys on closure CONTENT: a python
        # scalar the step math bakes in must retire the program when it
        # changes, not serve the stale constant
        Parameter._param_counter = 0
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        # lr=0: params never move, so the two calls differ ONLY through
        # the mutated closure scalar
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=net.parameters())
        scale = [1.0]

        def step(x):
            loss = (net(x) * scale[0]).sum()
            loss.backward()
            return loss

        cap = sc.capture_step(step, update_fn=lambda: (opt.step(),
                                                       opt.clear_grad()))
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        l1 = float(np.asarray(cap(x)._data))
        scale[0] = -1.0
        l2 = float(np.asarray(cap(x)._data))
        assert cap.stats["retraces"] == 2
        assert l2 == -l1


# ---------------------------------------------------------------------------
# parity: captured vs eager (both optimizer legs)
# ---------------------------------------------------------------------------

class TestParityEagerVsCaptured:
    @pytest.mark.parametrize("q8", [False, True], ids=["adam_fp32",
                                                       "adam_int8"])
    def test_trajectory_tracks_eager_at_ulp_scale(self, q8):
        ref = eager_losses(q8=q8)
        got = captured_losses(q8=q8)
        # step 1's loss is pre-update forward over identical params:
        # bitwise (whole-program fwd == per-op fwd; measured). The full
        # trajectory is NOT bitwise — XLA fuses the optimizer update's
        # a*x+b*y chains to FMA inside the whole-step kernel, which
        # per-op dispatch cannot — so the pin is ulp-scale closeness.
        assert got[0] == ref[0]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_scheduler_stepped_outside_rides_carried_state(self):
        # the LR VALUE is carried state (opt_lr): a host-side
        # scheduler.step() between captured calls takes effect on the
        # next call with NO retrace — the "LR step" half of the tentpole
        def run(captured):
            os.environ["PADDLE_TPU_STEP_CAPTURE"] = \
                "auto" if captured else "off"
            try:
                Parameter._param_counter = 0
                paddle.seed(5)
                net = paddle.nn.Linear(8, 4)
                sched = paddle.optimizer.lr.StepDecay(0.05, step_size=2,
                                                      gamma=0.1)
                opt = paddle.optimizer.Adam(learning_rate=sched,
                                            parameters=net.parameters())
                loss_fn = paddle.nn.MSELoss()
                rng = np.random.default_rng(5)
                x = paddle.to_tensor(rng.normal(size=(8, 8))
                                     .astype(np.float32))
                y = paddle.to_tensor(rng.normal(size=(8, 4))
                                     .astype(np.float32))

                def step():
                    loss = loss_fn(net(x), y)
                    loss.backward()
                    return loss

                def update():
                    opt.step()
                    opt.clear_grad()

                cap = sc.capture_step(step, update_fn=update) \
                    if captured else None
                out = []
                for _ in range(6):
                    loss = cap() if captured else (step(), update())[0]
                    out.append(float(np.asarray(loss._data)))
                    sched.step()       # host-side, between steps: legal
                if captured:
                    assert cap.stats["retraces"] == 1
                    assert cap.stats["hits"] == 5
                return out
            finally:
                os.environ["PADDLE_TPU_STEP_CAPTURE"] = "auto"

        np.testing.assert_allclose(run(True), run(False), rtol=1e-5,
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# bitwise within the captured tier: determinism, kill-resume, donation
# ---------------------------------------------------------------------------

class TestCapturedTierBitwise:
    @pytest.mark.parametrize("q8", [False, True], ids=["adam_fp32",
                                                       "adam_int8"])
    def test_identical_captured_runs_are_bitwise(self, q8):
        assert captured_losses(q8=q8) == captured_losses(q8=q8)

    def test_kill_at_step_resume_bitwise_on_captured_path(self, tmp_path):
        # the PR 10 acceptance proof, re-run over the captured step: a
        # KillPoint at step 6 escapes, a FRESH supervisor (fresh model,
        # fresh trace, fresh executable) resumes from the last verified
        # TrainState and the trajectory is bitwise identical
        r = build_run()
        ref = run_supervised(r, tmp_path / "ref", save_every=1).losses
        assert len(ref) == 8

        r2 = build_run()
        ck = tmp_path / "ck"
        sched = faults.FaultSchedule().kill("train.step", on=(6,))
        with faults.installed(sched):
            with pytest.raises(faults.KillPoint):
                run_supervised(r2, ck, save_every=1)
        assert sched.trace == [("train.step", 6, "kill")]

        r3 = build_run()
        sup = TrainingSupervisor(r3.net, r3.opt, r3.loader, ckpt_dir=str(ck),
                                 save_every=1)
        rep = sup.run(r3.step, r3.loader, epochs=2, update_fn=r3.update,
                      clear_fn=r3.clear, resume=True)
        assert rep.resumed_from == str(ck / "step-5")
        assert rep.losses == ref[5:]       # bitwise, not allclose

    def test_no_use_after_donate_across_save_restore(self, tmp_path):
        # donation rebinds every state tensor to a live output buffer per
        # call; a verified save mid-run, a restore, and the continuation
        # must all read live arrays and stay bitwise on the captured tier
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update, clear_fn=r.clear)
        sup = TrainingSupervisor(r.net, r.opt, r.loader,
                                 ckpt_dir=str(tmp_path), save_every=2)
        rep = sup.run(cap, r.loader, epochs=2)
        assert rep.steps == 8
        ref = rep.losses

        r2 = build_run(shuffle=False)
        cap2 = sc.capture_step(r2.step, update_fn=r2.update,
                               clear_fn=r2.clear)
        sup2 = TrainingSupervisor(r2.net, r2.opt, r2.loader,
                                  ckpt_dir=str(tmp_path / "ck2"),
                                  save_every=2)
        rep2 = sup2.run(cap2, r2.loader, epochs=1)
        # every state tensor must be a live, readable array (a donated
        # input left bound anywhere would raise "Array has been deleted")
        for p in r2.net.parameters():
            np.asarray(p._data)
        np.asarray(r2.opt._step_t._data)
        # fresh process: restore the mid-run checkpoint and continue over
        # a FRESH captured program — bitwise continuation
        r3 = build_run(shuffle=False)
        cap3 = sc.capture_step(r3.step, update_fn=r3.update,
                               clear_fn=r3.clear)
        sup3 = TrainingSupervisor(r3.net, r3.opt, r3.loader,
                                  ckpt_dir=str(tmp_path), save_every=2)
        rep3 = sup3.run(cap3, r3.loader, epochs=2, resume=True)
        assert rep3.resumed_from == str(tmp_path / "step-8")
        assert rep2.losses == ref[:4]
        for p in r3.net.parameters():
            np.asarray(p._data)

    def test_restore_preserves_uncommitted_placement(self, tmp_path):
        # regression (found by the zero-sharding suite): the checkpoint
        # loader used to restore single-host state as COMMITTED arrays
        # (orbax reads under an explicit sharding); the next captured jit
        # then committed its ENTIRE state carry — including unrelated
        # live models' tensors — to that one device, which broke any
        # later mesh-committed program sharing the registry. Restore into
        # an uncommitted destination must stay uncommitted.
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update, clear_fn=r.clear)
        sup = TrainingSupervisor(r.net, r.opt, r.loader,
                                 ckpt_dir=str(tmp_path), save_every=2)
        sup.run(cap, r.loader, epochs=1)
        r2 = build_run(shuffle=False)
        cap2 = sc.capture_step(r2.step, update_fn=r2.update,
                               clear_fn=r2.clear)
        sup2 = TrainingSupervisor(r2.net, r2.opt, r2.loader,
                                  ckpt_dir=str(tmp_path), save_every=2)
        sup2.run(cap2, r2.loader, epochs=2, resume=True)
        from paddle_tpu.core.random import default_generator
        from paddle_tpu.core.tensor import _state_registry
        assert not getattr(default_generator._key._data, "_committed", False)
        for p in r2.net.parameters():
            assert not getattr(p._data, "_committed", False), p.name
        # nothing in the whole live registry got silently pinned either
        assert not any(getattr(t._data, "_committed", False)
                       for _, t in _state_registry.alive_items())


# ---------------------------------------------------------------------------
# NaN gate
# ---------------------------------------------------------------------------

class TestNaNGate:
    def test_nonfinite_loss_withholds_update_in_program(self):
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update, clear_fn=r.clear,
                              nan_gate=True)
        batch = next(iter(r.loader))
        cap(batch)
        before = {p.name: np.asarray(p._data).copy()
                  for p in r.net.parameters()}
        m_before = np.asarray(
            r.opt._accumulators["moment1"][
                id(r.net.parameters()[0])]._data).copy()
        step_before = int(np.asarray(r.opt._step_t._data))
        bad = [paddle.to_tensor(np.full((8, 8), np.inf, np.float32)),
               batch[1]]
        loss = cap(bad)
        assert not np.isfinite(float(np.asarray(loss._data)))
        # the withheld update leaves params, moments AND the step count
        # bitwise untouched — the eager skip path's exact contract
        for p in r.net.parameters():
            assert np.array_equal(before[p.name], np.asarray(p._data))
        assert np.array_equal(
            m_before, np.asarray(r.opt._accumulators["moment1"][
                id(r.net.parameters()[0])]._data))
        assert int(np.asarray(r.opt._step_t._data)) == step_before
        # and a following healthy batch trains normally
        good = cap(batch)
        assert np.isfinite(float(np.asarray(good._data)))
        assert not np.array_equal(before["param_0"],
                                  np.asarray(r.net.parameters()[0]._data))

    def test_vector_loss_gates_on_first_element_like_the_supervisor(self):
        # regression (review finding): the gate must read the SAME value
        # the supervisor's _loss_value / the eager bypass reads — the
        # FIRST element — not all(isfinite(vector)). A vector loss of
        # [finite, nan] applies the update on BOTH tiers.
        def run(captured):
            os.environ["PADDLE_TPU_STEP_CAPTURE"] = \
                "auto" if captured else "off"
            try:
                Parameter._param_counter = 0
                paddle.seed(9)
                net = paddle.nn.Linear(4, 2)
                opt = paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters())
                mask = paddle.to_tensor(np.array([1.0, np.nan], np.float32))

                def step(x):
                    per_out = net(x).sum(axis=0)      # shape (2,)
                    loss = per_out + (mask - mask)    # [v, nan] vector
                    per_out.sum().backward()
                    return loss

                cap = sc.capture_step(
                    step, update_fn=lambda: (opt.step(), opt.clear_grad()),
                    clear_fn=lambda: opt.clear_grad(), nan_gate=True)
                x = paddle.to_tensor(np.ones((3, 4), np.float32))
                w0 = np.asarray(net.parameters()[0]._data).copy()
                cap(x)
                return not np.array_equal(
                    w0, np.asarray(net.parameters()[0]._data))
            finally:
                os.environ["PADDLE_TPU_STEP_CAPTURE"] = "auto"

        assert run(True) is True      # captured tier applied the update
        assert run(False) is True     # and so did the eager bypass

    def test_supervisor_counts_skip_over_captured_path(self):
        r = build_run(shuffle=False)
        # poison one batch: the supervisor must count the skip while the
        # in-program gate withholds the update
        xs = np.asarray([np.asarray(b[0]._data) for b in r.loader])
        cap = sc.capture_step(r.step, update_fn=r.update, clear_fn=r.clear,
                              nan_gate=True)
        sup = TrainingSupervisor(r.net, r.opt, r.loader, max_skipped=3)
        poisoned = [([paddle.to_tensor(np.full((8, 8), np.nan, np.float32)),
                      paddle.to_tensor(np.zeros((8, 4), np.float32))]
                     if i == 2 else
                     [paddle.to_tensor(xs[i]),
                      paddle.to_tensor(np.zeros((8, 4), np.float32))])
                    for i in range(4)]
        rep = sup.run(cap, poisoned, epochs=1)
        assert rep.skipped_batches == 1
        assert rep.steps == 3


# ---------------------------------------------------------------------------
# bypasses and guards
# ---------------------------------------------------------------------------

class TestBypassesAndGuards:
    def test_mode_off_is_the_eager_tier(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STEP_CAPTURE", "off")
        ref = eager_losses(steps=4)
        # same construction THROUGH the capture wrapper with mode off:
        # bitwise identical to plain eager (it IS plain eager)
        os.environ["PADDLE_TPU_STEP_CAPTURE"] = "off"
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update)
        got = []
        for batch in r.loader:
            got.append(float(np.asarray(cap(batch)._data)))
        assert got == ref
        assert cap.stats["hits"] == 0
        assert cap.stats["bypasses"] == {"off": 4}

    def test_dispatch_fault_injection_bypasses(self):
        # scripted per-op faults must keep firing per op: a compiled
        # program would run the dispatch seams only at trace time
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update)
        batch = next(iter(r.loader))
        sched = faults.FaultSchedule().error("dispatch.lower", on=(10 ** 9,))
        with faults.installed(sched):
            cap(batch)
        assert cap.stats["bypasses"] == {"fault_injection": 1}
        cap(batch)     # schedule gone: captures
        assert cap.stats["retraces"] == 1

    def test_live_trace_seam_bypasses(self):
        from paddle_tpu.core.tracing import (TraceState, pop_trace_state,
                                             push_trace_state)
        r = build_run(shuffle=False)
        cap = sc.capture_step(r.step, update_fn=r.update)
        batch = next(iter(r.loader))
        ts = TraceState()
        push_trace_state(ts)
        try:
            cap(batch)
        finally:
            pop_trace_state()
            ts.restore()
        assert cap.stats["bypasses"] == {"capture_seam": 1}

    def test_untraceable_step_memoizes_eager(self):
        Parameter._param_counter = 0
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())

        def step(x):
            loss = net(x).sum()
            loss.backward()
            float(loss)        # host read mid-step: cannot trace
            return loss

        cap = sc.capture_step(step, update_fn=lambda: (opt.step(),
                                                       opt.clear_grad()))
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with pytest.warns(UserWarning, match="cannot be captured"):
            cap(x)
        cap(x)
        assert cap.stats["bypasses"] == {"untraceable": 2}
        assert cap.stats["retraces"] == 0
        for p in net.parameters():     # both eager steps applied
            np.asarray(p._data)

    def test_scheduler_step_inside_captured_update_raises_typed(self):
        Parameter._param_counter = 0
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        sched = paddle.optimizer.lr.StepDecay(0.05, step_size=1, gamma=0.5)
        opt = paddle.optimizer.Adam(learning_rate=sched,
                                    parameters=net.parameters())

        def step(x):
            loss = net(x).sum()
            loss.backward()
            return loss

        def update():
            opt.step()
            opt.clear_grad()
            sched.step()       # per-step host write into carried state

        cap = sc.capture_step(step, update_fn=update)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with pytest.raises(sc.HostStateWriteError, match="scheduler.step"):
            cap(x)

    def test_supervisor_rejects_double_update(self):
        r = build_run()
        cap = sc.capture_step(r.step, update_fn=r.update)
        sup = TrainingSupervisor(r.net, r.opt, r.loader)
        with pytest.raises(ValueError, match="already folds"):
            sup.run(cap, r.loader, epochs=1, update_fn=r.update)

    def test_steps_per_epoch_mode_never_wraps(self):
        # a step that sources its own batches would consume one during a
        # speculative trace; data=None stays on the caller's tier
        r = build_run(shuffle=False)
        batches = list(r.loader)
        it = iter(batches * 3)
        before = sc.capture_info()

        def step(_):
            loss = r.step(next(it))
            return loss

        sup = TrainingSupervisor(r.net, r.opt, None)
        rep = sup.run(step, None, epochs=1, steps_per_epoch=4,
                      update_fn=r.update, clear_fn=r.clear)
        after = sc.capture_info()
        assert rep.steps == 4
        assert after["hits"] == before["hits"]
        assert after["retraces"] == before["retraces"]


# ---------------------------------------------------------------------------
# hapi routing
# ---------------------------------------------------------------------------

class TestHapiRouting:
    def _data(self, seed=1, n=32):
        rng = np.random.default_rng(seed)
        xs = rng.normal(size=(n, 8)).astype(np.float32)
        ys = rng.normal(size=(n, 4)).astype(np.float32)
        return paddle.io.TensorDataset([paddle.to_tensor(xs),
                                        paddle.to_tensor(ys)])

    def _model(self):
        Parameter._param_counter = 0
        paddle.seed(3)
        net = paddle.nn.Linear(8, 4)
        m = paddle.Model(net)
        m.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.05, parameters=net.parameters()),
            loss=paddle.nn.MSELoss())
        return m

    def test_plain_fit_equals_supervised_fit_bitwise(self, tmp_path):
        # both fit paths ride the captured step: the whole-step program is
        # identical, so the trajectories are bitwise equal
        ds = self._data()
        h1 = self._model().fit(ds, batch_size=8, epochs=2, verbose=0)
        h2 = self._model().fit(
            ds, batch_size=8, epochs=2, verbose=0,
            fault_tolerance={"ckpt_dir": str(tmp_path), "save_every": 2})
        assert h1["loss"] == h2["loss"]
        assert sc.capture_info()["hits"] > 0

    def test_plain_fit_tracks_eager_fit(self, monkeypatch):
        ds = self._data()
        cap_hist = self._model().fit(ds, batch_size=8, epochs=2, verbose=0)
        monkeypatch.setenv("PADDLE_TPU_STEP_CAPTURE", "off")
        eager_hist = self._model().fit(ds, batch_size=8, epochs=2, verbose=0)
        np.testing.assert_allclose(cap_hist["loss"], eager_hist["loss"],
                                   rtol=1e-5, atol=1e-6)

    def test_plain_fit_with_metrics_captures(self):
        # metrics update on the program's CONCRETE outputs after each
        # call — the plain path keeps capture even with metrics on
        ds = self._data()
        m = self._model()
        m._metrics = [paddle.metric.Accuracy()]
        before = sc.capture_info()["hits"]
        m.fit(ds, batch_size=8, epochs=1, verbose=0)
        assert sc.capture_info()["hits"] > before

    def test_supervised_fit_with_metrics_stays_eager(self, tmp_path):
        # the supervised split step feeds metrics from inside train_batch;
        # that path needs eager outputs, so capture stays off for it
        ds = self._data()
        m = self._model()
        m._metrics = [paddle.metric.Accuracy()]
        before = sc.capture_info()
        m.fit(ds, batch_size=8, epochs=1, verbose=0,
              fault_tolerance={"ckpt_dir": str(tmp_path)})
        after = sc.capture_info()
        assert after["hits"] == before["hits"]
        assert after["retraces"] == before["retraces"]
