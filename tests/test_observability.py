"""paddle_tpu.observability: registry semantics, hot-seam integration,
exporter round-trips, and the zero-overhead-when-disabled guard."""

import io
import json
import threading
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.core import tensor as core_tensor
from paddle_tpu.observability.registry import Counter, Gauge, Histogram, Registry


@pytest.fixture(autouse=True)
def _isolated_metrics():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_inc_and_value(self):
        r = Registry()
        c = r.counter("x.things_total")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5

    def test_counter_rejects_negative(self):
        r = Registry()
        with pytest.raises(ValueError):
            r.counter("x.n_total").inc(-1)

    def test_get_or_create_returns_same_family(self):
        r = Registry()
        assert r.counter("a") is r.counter("a")

    def test_kind_conflict_raises(self):
        r = Registry()
        r.counter("a")
        with pytest.raises(TypeError):
            r.gauge("a")

    def test_label_set_conflict_raises(self):
        r = Registry()
        r.counter("a", labelnames=("op",))
        with pytest.raises(ValueError):
            r.counter("a", labelnames=("kind",))

    def test_labeled_series_are_independent(self):
        r = Registry()
        c = r.counter("ops_total", labelnames=("op",))
        c.inc(op="add")
        c.inc(op="add")
        c.inc(op="mul")
        assert c.value(op="add") == 2
        assert c.value(op="mul") == 1
        assert c.value(op="sub") == 0

    def test_wrong_labels_raise(self):
        r = Registry()
        c = r.counter("ops_total", labelnames=("op",))
        with pytest.raises(ValueError):
            c.inc(kind="add")
        with pytest.raises(ValueError):
            c.inc()  # missing the declared label

    def test_gauge_set_and_add(self):
        r = Registry()
        g = r.gauge("depth")
        g.set(4)
        assert g.value() == 4
        g.add(-1.5)
        assert g.value() == 2.5

    def test_histogram_buckets_are_cumulative(self):
        r = Registry()
        h = r.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        st = h.stats()
        # cumulative: <=0.1 -> 1, <=1.0 -> 3, <=10.0 -> 4, +Inf -> 5
        assert st["buckets"] == [1, 3, 4, 5]
        assert st["count"] == 5
        assert st["sum"] == pytest.approx(56.05)

    def test_histogram_boundaries_sorted_and_fixed(self):
        r = Registry()
        h = r.histogram("lat2", buckets=(1.0, 0.1))
        assert h.boundaries == (0.1, 1.0)

    def test_histogram_bucket_mismatch_raises(self):
        r = Registry()
        r.histogram("lat3", buckets=(0.1, 1.0))
        with pytest.raises(ValueError):
            r.histogram("lat3", buckets=(30.0, 60.0))
        # omitting buckets accepts whatever the family was created with
        assert r.histogram("lat3").boundaries == (0.1, 1.0)

    def test_snapshot_shapes(self):
        r = Registry()
        r.counter("plain_total").inc(3)
        c = r.counter("by_op_total", labelnames=("op",))
        c.inc(op="add")
        r.histogram("lat", buckets=(1.0,)).observe(0.5)
        snap = r.snapshot()
        assert snap["plain_total"] == 3
        assert snap["by_op_total"] == {"op=add": 1}
        assert snap["lat"]["count"] == 1

    def test_reset_zeroes_but_keeps_families(self):
        r = Registry()
        c = r.counter("n_total")
        c.inc(7)
        r.reset()
        assert c.value() == 0
        assert r.get("n_total") is c

    def test_thread_safety_exact_counts(self):
        r = Registry()
        c = r.counter("n_total")
        h = r.histogram("lat", buckets=(0.5,))
        N, T = 5000, 8

        def work():
            for _ in range(N):
                c.inc()
                h.observe(0.1)

        threads = [threading.Thread(target=work) for _ in range(T)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == N * T
        assert h.stats()["count"] == N * T
        assert h.stats()["buckets"] == [N * T, N * T]


# ---------------------------------------------------------------------------
# dispatch-seam integration + zero-overhead guard
# ---------------------------------------------------------------------------

class TestDispatchIntegration:
    def test_disabled_installs_no_hook(self):
        # the zero-overhead contract: while disabled, apply() carries only
        # the is-None probe it already had — there is no hook to call
        assert core_tensor._op_metrics_hook is None
        x = paddle.to_tensor([1.0, 2.0])
        (x + x).numpy()
        assert obs.snapshot().get("dispatch.ops_total") is None

    def test_enable_counts_ops_and_latency(self):
        obs.enable()
        assert core_tensor._op_metrics_hook is not None
        x = paddle.to_tensor([1.0, 2.0])
        y = x * 2.0
        z = y + 1.0
        snap = obs.snapshot()
        assert snap["dispatch.ops_total"] >= 2
        assert snap["dispatch.latency_seconds"]["count"] == \
            snap["dispatch.ops_total"]
        by_op = snap["dispatch.ops_by_name_total"]
        assert any("multiply" in k or "mul" in k for k in by_op)

    def test_disable_stops_counting(self):
        obs.enable()
        x = paddle.to_tensor([1.0])
        _ = x + 1.0
        before = obs.snapshot()["dispatch.ops_total"]
        obs.disable()
        assert core_tensor._op_metrics_hook is None
        _ = x + 1.0
        assert obs.snapshot()["dispatch.ops_total"] == before

    def test_helpers_are_noops_while_disabled(self):
        obs.inc("some.counter_total")
        obs.set_gauge("some.depth", 3)
        obs.observe("some.lat_seconds", 0.1)
        with obs.scoped_timer("some.timer_seconds"):
            pass
        snap = obs.snapshot()
        assert not any(k.startswith("some.") for k in snap)


class TestJitCounters:
    def test_compile_then_cache_hits(self):
        obs.enable()

        @paddle.jit.to_static
        def f(a):
            return a * 2.0 + 1.0

        x = paddle.to_tensor(np.ones((4,), np.float32))
        f(x)
        f(x)
        f(x)
        snap = obs.snapshot()
        assert snap["jit.compiles_total"] == 1
        assert snap["jit.traces_total"] == 1
        assert snap["jit.cache_hits_total"] == 2
        assert snap["jit.cache_misses_total"] == 1

    def test_graph_break_does_not_count_as_compile(self):
        obs.enable()

        @paddle.jit.to_static(full_graph=False)
        def f(a):
            if float(a.sum()) > 0:  # concrete read -> trace failure
                return a * 2.0
            return a

        x = paddle.to_tensor(np.ones((4,), np.float32))
        f(x)
        snap = obs.snapshot()
        assert snap["jit.graph_breaks_total"] == 1
        assert snap["jit.traces_total"] == 1  # the trace was attempted
        assert snap.get("jit.compiles_total") is None  # but nothing compiled

    def test_small_train_loop_reports_dispatch_and_compiles(self):
        # the acceptance shape: after a small train loop with a to_static
        # step, BOTH dispatch.ops_total and jit.compiles_total are nonzero
        obs.enable()
        paddle.seed(0)
        lin = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())

        @paddle.jit.to_static
        def step(xb):
            loss = (lin(xb) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        x = paddle.to_tensor(np.random.default_rng(0)
                             .normal(size=(8, 4)).astype(np.float32))
        for _ in range(3):
            step(x)
        snap = obs.snapshot()
        assert snap["dispatch.ops_total"] > 0
        assert snap["jit.compiles_total"] >= 1
        assert snap["jit.cache_hits_total"] >= 1


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

class TestPrometheusExport:
    def test_round_trip_counters_and_gauges(self):
        obs.enable()
        obs.inc("rt.things_total", 5)
        obs.inc("rt.by_op_total", 2, op="add")
        obs.inc("rt.by_op_total", 3, op="mul")
        obs.set_gauge("rt.depth", 7)
        parsed = obs.parse_prometheus_text(obs.prometheus_text())
        assert parsed["rt_things_total"][""] == 5
        assert parsed["rt_depth"][""] == 7
        by_op = parsed["rt_by_op_total"]
        assert by_op['{op="add"}'] == 2
        assert by_op['{op="mul"}'] == 3

    def test_round_trip_histogram(self):
        h = obs.histogram("rt.lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        parsed = obs.parse_prometheus_text(obs.prometheus_text())
        buckets = parsed["rt_lat_seconds_bucket"]
        assert buckets['{le="0.1"}'] == 1
        assert buckets['{le="1.0"}'] == 2
        assert buckets['{le="+Inf"}'] == 3
        assert parsed["rt_lat_seconds_count"][""] == 3
        assert parsed["rt_lat_seconds_sum"][""] == pytest.approx(5.55)

    def test_label_values_are_escaped(self):
        obs.enable()
        obs.inc("esc.n_total", 1, name='load "train"\nshard\\x')
        text = obs.prometheus_text()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("esc_n_total{"))
        assert '\\"train\\"' in line
        assert "\\n" in line and "\n" not in line[:-1].split(" ")[0]
        assert "\\\\x" in line

    def test_non_finite_values_render_not_raise(self):
        obs.enable()
        obs.set_gauge("nf.loss", float("nan"))
        obs.set_gauge("nf.peak", float("inf"))
        text = obs.prometheus_text()  # must not raise
        assert "nf_loss NaN" in text
        assert "nf_peak +Inf" in text

    def test_value_keyword_is_rejected_not_mislabeled(self):
        obs.enable()
        with pytest.raises(TypeError, match="positional-only"):
            obs.inc("vk.n_total", value=5)
        assert "vk.n_total" not in obs.snapshot()

    def test_type_headers_present(self):
        obs.counter("t.c_total").inc()
        obs.gauge("t.g").set(1)
        text = obs.prometheus_text()
        assert "# TYPE t_c_total counter" in text
        assert "# TYPE t_g gauge" in text

    def test_dispatch_counters_round_trip(self):
        # acceptance: the exporters round-trip the dispatch counters
        obs.enable()
        x = paddle.to_tensor([1.0, 2.0])
        _ = x + x
        snap = obs.snapshot()
        parsed = obs.parse_prometheus_text(obs.prometheus_text())
        assert parsed["dispatch_ops_total"][""] == snap["dispatch.ops_total"]


class TestJsonlExport:
    def test_step_deltas_and_round_trip(self, tmp_path):
        obs.enable()
        path = str(tmp_path / "steps.jsonl")
        c = obs.counter("jl.ops_total")
        w = obs.StepTelemetryWriter(path)
        c.inc(3)
        obs.set_gauge("jl.depth", 2)
        w.write(1, loss=0.9)
        c.inc(4)
        w.write(2, loss=0.7)
        w.close()
        recs = obs.read_jsonl(path)
        # ISSUE 12: records are the shared trace envelope (ts/kind/name/
        # attrs), the step payload inside attrs
        for r in recs:
            assert {"ts", "kind", "name", "attrs"} <= set(r)
            assert r["kind"] == "step" and r["name"] == "telemetry"
        assert [r["attrs"]["step"] for r in recs] == [1, 2]
        assert recs[0]["attrs"]["counters"]["jl.ops_total"] == 3
        # DELTA, not total
        assert recs[1]["attrs"]["counters"]["jl.ops_total"] == 4
        assert recs[0]["attrs"]["gauges"]["jl.depth"] == 2
        assert recs[0]["attrs"]["loss"] == pytest.approx(0.9)

    def test_dispatch_counters_round_trip_via_jsonl(self, tmp_path):
        obs.enable()
        path = str(tmp_path / "t.jsonl")
        w = obs.StepTelemetryWriter(path)
        x = paddle.to_tensor([1.0])
        _ = x + x
        w.write(1)
        w.close()
        rec = obs.read_jsonl(path)[0]
        assert rec["attrs"]["counters"]["dispatch.ops_total"] >= 1
        # histogram rides along as .count/.sum samples
        assert rec["attrs"]["counters"]["dispatch.latency_seconds.count"] >= 1

    def test_writer_accepts_file_object(self):
        obs.enable()
        obs.counter("fo.n_total").inc()
        buf = io.StringIO()
        w = obs.StepTelemetryWriter(buf, baseline="zero")
        w.write(1)
        rec = json.loads(buf.getvalue())
        assert rec["attrs"]["counters"]["fo.n_total"] == 1


class TestScopedTimer:
    def test_records_into_histogram(self):
        obs.enable()
        with obs.scoped_timer("st.block_seconds", what="x"):
            pass
        snap = obs.snapshot()
        assert snap["st.block_seconds"]["what=x"]["count"] == 1

    def test_free_when_disabled(self):
        with obs.scoped_timer("st.block_seconds"):
            pass
        assert "st.block_seconds" not in obs.snapshot()


# ---------------------------------------------------------------------------
# subsystem integrations
# ---------------------------------------------------------------------------

class TestDataLoaderMetrics:
    def test_batch_and_wait_metrics(self):
        obs.enable()
        xs = np.arange(32, dtype=np.float32).reshape(16, 2)
        ds = paddle.io.TensorDataset([paddle.to_tensor(xs)])
        loader = paddle.io.DataLoader(ds, batch_size=4, shuffle=False)
        n = sum(1 for _ in loader)
        snap = obs.snapshot()
        total = sum(snap["dataloader.batches_total"].values())
        assert n == 4 and total == 4
        assert snap["dataloader.wait_seconds"]["count"] >= 1

    def test_no_metrics_when_disabled(self):
        xs = np.zeros((8, 2), np.float32)
        ds = paddle.io.TensorDataset([paddle.to_tensor(xs)])
        loader = paddle.io.DataLoader(ds, batch_size=4)
        _ = [b for b in loader]
        assert "dataloader.batches_total" not in obs.snapshot()


class TestProfilerBridge:
    def test_record_event_emits_histogram_sample(self):
        obs.enable()
        from paddle_tpu import profiler as prof
        with prof.RecordEvent("aug"):
            pass
        snap = obs.snapshot()
        assert snap["profiler.record_event_seconds"]["name=aug"]["count"] == 1


class TestHapiStepTelemetry:
    def test_fit_writes_jsonl_with_telemetry(self, tmp_path):
        from paddle_tpu.hapi.callbacks import StepTelemetry

        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss())
        rng = np.random.default_rng(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rng.normal(size=(16, 4)).astype(np.float32)),
             paddle.to_tensor(rng.integers(0, 2, 16).astype(np.int64))])
        path = str(tmp_path / "telemetry.jsonl")
        model.fit(ds, batch_size=8, epochs=1, verbose=0,
                  callbacks=[StepTelemetry(path)])
        recs = obs.read_jsonl(path)
        assert len(recs) == 2  # 16 samples / batch 8
        for rec in recs:
            assert rec["attrs"]["counters"].get("dispatch.ops_total", 0) > 0
            assert "loss" in rec["attrs"]
        # the callback turned metrics off again at train end (they were
        # off before fit)
        assert not obs.enabled()

    def test_fit_restores_user_enabled_metrics(self, tmp_path):
        from paddle_tpu.hapi.callbacks import StepTelemetry

        obs.enable()  # the USER's process-wide collection
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss())
        rng = np.random.default_rng(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32)),
             paddle.to_tensor(rng.integers(0, 2, 8).astype(np.int64))])
        model.fit(ds, batch_size=8, epochs=1, verbose=0,
                  callbacks=[StepTelemetry(str(tmp_path / "t.jsonl"))])
        assert obs.enabled()  # fit must not clobber the user's enable

    def test_train_end_cleanup_runs_when_training_raises(self, tmp_path):
        from paddle_tpu.hapi.callbacks import StepTelemetry

        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)

        def exploding_loss(*a):
            raise RuntimeError("boom")
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
            loss=exploding_loss)
        rng = np.random.default_rng(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32)),
             paddle.to_tensor(rng.integers(0, 2, 8).astype(np.int64))])
        cb = StepTelemetry(str(tmp_path / "t.jsonl"))
        with pytest.raises(RuntimeError, match="boom"):
            model.fit(ds, batch_size=8, epochs=1, verbose=0, callbacks=[cb])
        # on_train_end ran on the exception path: metrics state restored
        # (it was off before fit) and the writer handle closed
        assert not obs.enabled()
        assert cb._writer is None

    def test_success_path_teardown_runs_all_callbacks(self, tmp_path):
        # a broken sibling's on_train_end must neither rob StepTelemetry
        # of cleanup nor be swallowed: all teardowns run, first error
        # propagates
        from paddle_tpu.hapi.callbacks import Callback, StepTelemetry

        class BadEnd(Callback):
            def on_train_end(self, logs=None):
                raise RuntimeError("end boom")

        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss())
        rng = np.random.default_rng(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32)),
             paddle.to_tensor(rng.integers(0, 2, 8).astype(np.int64))])
        st = StepTelemetry(str(tmp_path / "t.jsonl"))
        with pytest.raises(RuntimeError, match="end boom"):
            model.fit(ds, batch_size=8, epochs=1, verbose=0,
                      callbacks=[BadEnd(), st])
        assert st._writer is None  # StepTelemetry still tore down
        assert not obs.enabled()

    def test_crashed_fit_does_not_write_final_checkpoint(self, tmp_path):
        import os
        from paddle_tpu.hapi.callbacks import ModelCheckpoint

        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)

        def exploding_loss(*a):
            raise RuntimeError("boom")
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
            loss=exploding_loss)
        rng = np.random.default_rng(0)
        ds = paddle.io.TensorDataset(
            [paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32)),
             paddle.to_tensor(rng.integers(0, 2, 8).astype(np.int64))])
        ck = str(tmp_path / "ck")
        with pytest.raises(RuntimeError, match="boom"):
            model.fit(ds, batch_size=8, epochs=1, verbose=0,
                      callbacks=[ModelCheckpoint(save_dir=ck)])
        # the crashed run must not be indistinguishable from a finished one
        assert not os.path.exists(os.path.join(ck, "final.pdparams"))


class TestPsAsyncPushAccounting:
    def test_dropped_async_push_is_counted_and_logged(self, caplog):
        import logging
        from paddle_tpu.distributed.ps_service import PsClient

        obs.enable()
        client = PsClient("srv", retry_timeout=0.01)

        def failing_call(server, fn, args):
            raise RuntimeError("transport down")
        client._call = failing_call

        with caplog.at_level(logging.ERROR,
                             logger="paddle_tpu.distributed.ps_service"):
            fut = client.push("t", [0], [[1.0]], wait=False)
            with pytest.raises(RuntimeError):
                fut.wait(timeout=10)
        assert obs.snapshot()["ps.dropped_async_pushes_total"] == 1
        assert any("async push" in r.message for r in caplog.records)

    def test_async_push_resolves_through_retry_wrapper(self):
        from paddle_tpu.distributed.ps_service import PsClient

        client = PsClient("srv", retry_timeout=0.01)
        calls = []

        def ok_call(server, fn, args):
            calls.append((server, fn))
            return True
        client._call = ok_call
        fut = client.push("t", [0], [[1.0]], wait=False)
        assert fut.wait(timeout=10) is True
        assert calls and calls[0][0] == "srv"
        client.close()

    def test_close_stops_drain_thread(self):
        from paddle_tpu.distributed.ps_service import PsClient

        client = PsClient("srv", retry_timeout=0.01)
        client._call = lambda server, fn, args: True
        fut = client.push("t", [0], [[1.0]], wait=False)
        fut.wait(timeout=10)
        q_t = client._async_pool
        assert q_t is not None
        client.close(wait=True, timeout=5)
        assert client._async_pool is None
        assert not q_t[1].is_alive()
        client.close()  # idempotent

    def test_queue_cap_drops_oldest_and_counts(self):
        import threading as th
        from paddle_tpu.distributed.ps_service import PsClient

        obs.enable()
        client = PsClient("srv", retry_timeout=0.01, max_pending_async=2)
        gate = th.Event()
        client._call = lambda server, fn, args: gate.wait(5) or True
        for _ in range(6):
            client.push("t", [0], [[1.0]], wait=False)
        gate.set()
        client.close(wait=True, timeout=10)
        # at least pushes 2..4-ish were evicted by the cap, all counted
        assert obs.snapshot()["ps.dropped_async_pushes_total"] >= 2

    def test_async_pushes_use_their_own_dedup_stream(self):
        from paddle_tpu.distributed.ps_service import PsClient

        client = PsClient("srv", retry_timeout=0.01)
        seen = []
        client._call = lambda server, fn, args: seen.append(args) or True
        client.push("t", [0], [[1.0]], wait=True)
        client.push("t", [0], [[1.0]], wait=False).wait(timeout=10)
        client.close()
        sync_key, async_key = seen[0][6], seen[1][6]
        assert async_key == sync_key + "/async1"

    def test_server_does_not_dedup_across_streams(self):
        # the silent-drop scenario: sync push (seq 6) overtakes an async
        # retry (seq 5); with per-stream keys the late push still applies
        from paddle_tpu.distributed import ps_service as pss

        pss.reset_server_state()
        arr = np.zeros((4, 2), np.float32)
        pss._srv_create("t", arr.tobytes(), (4, 2), "float32")
        ids = np.array([0], np.int64)
        g = np.ones((1, 2), np.float32)
        pss._srv_push("t", ids.tobytes(), g.tobytes(), 1, 2, 1.0, "ck", 6)
        pss._srv_push("t", ids.tobytes(), g.tobytes(), 1, 2, 1.0,
                      "ck/async", 5)
        raw, shape, dtype = pss._srv_table_snapshot("t")
        table = np.frombuffer(raw, dtype).reshape(shape)
        assert table[0, 0] == -2.0  # BOTH pushes applied (sgd: -lr*g each)
        # same stream still dedups
        pss._srv_push("t", ids.tobytes(), g.tobytes(), 1, 2, 1.0, "ck", 6)
        raw, shape, dtype = pss._srv_table_snapshot("t")
        assert np.frombuffer(raw, dtype).reshape(shape)[0, 0] == -2.0
        pss.reset_server_state()


class TestElasticStoreHealth:
    class _DeadStore:
        def check(self, key):
            raise ConnectionError("store down")

        def get(self, key, timeout=None):
            raise ConnectionError("store down")

        def set(self, key, val):
            raise ConnectionError("store down")

    def _agent(self, deadline):
        from paddle_tpu.distributed.fleet.elastic.manager import (
            ElasticManager, MultiNodeElasticAgent)
        # bypass __init__ plumbing that builds a local TCPStore
        agent = MultiNodeElasticAgent.__new__(MultiNodeElasticAgent)
        agent.store = self._DeadStore()
        agent.store_lost_deadline = deadline
        agent.store_lost = False
        agent._store_fail_first = None
        agent._store_fail_count = 0
        agent._read_fail_throttle = obs.LogThrottle()
        agent._write_fail_throttle = obs.LogThrottle()
        agent._key_fail_first = {}
        agent.node_timeout = 10.0
        return agent

    def test_read_failure_counts_and_stays_fresh_before_deadline(self):
        obs.enable()
        agent = self._agent(deadline=3600.0)
        assert agent._node_age(0) == 0.0  # transient blip still reads fresh
        assert not agent.store_lost
        assert obs.snapshot()["elastic.store_read_failures_total"] == 1

    def test_store_declared_lost_after_deadline(self, caplog):
        import logging
        import time
        obs.enable()
        agent = self._agent(deadline=0.0)
        with caplog.at_level(
                logging.ERROR,
                logger="paddle_tpu.distributed.fleet.elastic.manager"):
            agent._node_age(0)
            time.sleep(0.01)
            agent._node_age(0)  # second consecutive failure, past deadline
        assert agent.store_lost
        assert obs.snapshot()["elastic.store_read_failures_total"] == 2
        assert any("LOST" in r.message for r in caplog.records)

    def test_single_unreadable_lease_reads_lost_after_deadline(self):
        import time

        agent = self._agent(deadline=0.05)
        # other nodes read fine: global window keeps resetting, but node
        # 3's per-node window persists and eventually reads as lost
        assert agent._node_age(3) == 0.0  # fresh within deadline
        agent._store_read_ok()            # a healthy sibling read
        time.sleep(0.06)
        assert agent._node_age(3) is None  # unreadable lease == lost lease
        assert not agent.store_lost  # the STORE is not declared lost

    def test_unreadable_coordination_key_escalates_to_store_lost(self):
        import time

        # node leases read fine (resetting the global window) but the
        # fault flag is permanently unreadable: coordination is broken,
        # so the per-key deadline must still trip store-LOST
        agent = self._agent(deadline=0.03)

        class FaultDeadStore:
            def check(self, k):
                if "fault" in k:
                    raise TimeoutError("key timeout")
                return False
        agent.store = FaultDeadStore()
        assert agent._fault_epoch(2) == -1
        agent._node_age(0)  # healthy lease read resets the GLOBAL window
        time.sleep(0.04)
        agent._fault_epoch(2)
        assert agent.store_lost

    def test_success_resets_failure_window(self):
        agent = self._agent(deadline=0.0)
        agent._node_age(0)

        class _OkStore:
            def check(self, key):
                return False
        agent.store = _OkStore()
        assert agent._node_age(0) is None  # never leased
        assert agent._store_fail_first is None
        assert agent._store_fail_count == 0


class TestPipelineSegMethodWarning:
    def _entries(self, names):
        classes = {}
        out = []
        for n in names:
            cls = classes.setdefault(n, type(n, (), {}))
            out.append((cls(), None))
        return out

    def test_too_few_named_blocks_warns_and_counts(self):
        from paddle_tpu.distributed.fleet.tpu_pipeline import \
            _refine_run_bounds

        obs.enable()
        entries = self._entries(["Embed", "Block", "Head"])
        keys = ["k0", "k1", "k2"]
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            lo, hi = _refine_run_bounds(entries, keys, 0, 3, 2,
                                        "layer:Block")
        assert any("seg_method" in str(x.message) for x in w)
        assert obs.snapshot()["pipeline.seg_method_fallbacks_total"] == 1
        assert (lo, hi) == (0, 3)  # heuristic kept the whole run (no
        #                            repeating inward neighbor to trim to)

    def test_enough_named_blocks_bound_the_run_silently(self):
        from paddle_tpu.distributed.fleet.tpu_pipeline import \
            _refine_run_bounds

        entries = self._entries(["Embed", "Block", "Block", "Head"])
        keys = ["k0", "k1", "k1", "k2"]
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            lo, hi = _refine_run_bounds(entries, keys, 0, 4, 2,
                                        "layer:Block")
        assert not w
        assert (lo, hi) == (1, 3)


class TestNamingConvention:
    def test_builtin_families_follow_convention(self):
        # counters end in _total; histograms in _seconds; all are
        # subsystem.name shaped (README "metric naming convention")
        for m in obs.default_registry().families():
            assert "." in m.name, m.name
            if isinstance(m, Counter):
                assert m.name.endswith("_total"), m.name
            elif isinstance(m, Histogram):
                assert m.name.endswith("_seconds"), m.name
