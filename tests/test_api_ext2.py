"""Tests for API batch 6: comm p2p aliases, nn.quant, class_center_sample,
sparse_attention, tensor method tail, global initializer, jit fills."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn


class TestCommAliases:
    def test_backend_and_p2p_types(self):
        assert dist.get_backend() == "XLA"
        assert hasattr(dist, "P2POp") and hasattr(dist, "batch_isend_irecv")

    def test_all_gather_into_tensor(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1))
        out = paddle.zeros([8, 1])
        dist.all_gather_into_tensor(out, x)
        # single-group gather over the 8-dev mesh concatenates the shards
        assert out.shape[0] == 8

    def test_monitored_barrier_and_destroy(self):
        dist.monitored_barrier()
        dist.destroy_process_group()
        from paddle_tpu.distributed import env
        assert not env.is_initialized()
        env.init_parallel_env()


class TestQuant:
    def test_quantize_dequantize_roundtrip(self):
        w = np.random.randn(8, 16).astype("float32")
        q, s = nn.quant.weight_quantize(paddle.to_tensor(w))
        assert str(q.dtype) == "int8"
        wd = nn.quant.weight_dequantize(q, s)
        assert np.abs(wd.numpy() - w).max() < np.abs(w).max() / 100

    def test_weight_only_linear(self):
        w = np.random.randn(8, 16).astype("float32")
        x = np.random.randn(3, 8).astype("float32")
        q, s = nn.quant.weight_quantize(paddle.to_tensor(w))
        out = nn.quant.weight_only_linear(paddle.to_tensor(x), q,
                                          weight_scale=s)
        wd = nn.quant.weight_dequantize(q, s).numpy()
        np.testing.assert_allclose(out.numpy(), x @ wd, atol=1e-4)

    def test_int4(self):
        w = np.random.randn(4, 4).astype("float32")
        q, s = nn.quant.weight_quantize(paddle.to_tensor(w),
                                        algo="weight_only_int4")
        assert np.abs(np.asarray(q.numpy())).max() <= 7


class TestClassCenterSample:
    def test_positives_always_kept(self):
        lab = paddle.to_tensor(np.array([1, 5, 9, 5], "int32"))
        remapped, sampled = nn.functional.class_center_sample(lab, 20, 8)
        sarr = np.asarray(sampled.numpy())
        rarr = np.asarray(remapped.numpy())
        assert sampled.shape == [8]
        for orig, r in zip([1, 5, 9, 5], rarr):
            assert sarr[r] == orig


class TestSparseAttention:
    def test_dense_pattern_matches_sdpa(self):
        qv = paddle.to_tensor(np.random.randn(1, 2, 4, 8).astype("float32"))
        off = paddle.to_tensor(
            np.tile(np.arange(0, 17, 4, dtype=np.int32), (1, 2, 1)))
        cols = paddle.to_tensor(
            np.tile(np.tile(np.arange(4, dtype=np.int32), 4), (1, 2, 1)))
        out = nn.functional.sparse_attention(qv, qv, qv, off, cols)
        ref = nn.functional.scaled_dot_product_attention(
            qv.transpose([0, 2, 1, 3]), qv.transpose([0, 2, 1, 3]),
            qv.transpose([0, 2, 1, 3])).transpose([0, 2, 1, 3])
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-4)

    def test_banded_pattern_masks(self):
        # each query attends only to its own key
        qv = paddle.to_tensor(np.random.randn(1, 1, 4, 8).astype("float32"))
        off = paddle.to_tensor(np.arange(5, dtype=np.int32).reshape(1, 1, 5))
        cols = paddle.to_tensor(np.arange(4, dtype=np.int32).reshape(1, 1, 4))
        out = nn.functional.sparse_attention(qv, qv, qv, off, cols)
        # diagonal pattern -> output equals value rows exactly
        np.testing.assert_allclose(out.numpy(), qv.numpy(), atol=1e-5)


class TestTensorTail:
    def test_random_fills(self):
        t = paddle.zeros([200])
        t.exponential_(2.0)
        assert 0.2 < float(t.numpy().mean()) < 1.0  # mean 1/lambda = 0.5
        t2 = paddle.zeros([50])
        t2.log_normal_(0.0, 0.25)
        assert (t2.numpy() > 0).all()
        t3 = paddle.zeros([50])
        t3.cauchy_()
        assert np.isfinite(t3.numpy()).all()
        t4 = paddle.zeros([50])
        t4.geometric_(0.5)
        assert (t4.numpy() >= 1).all()

    def test_index_fill_masked_scatter(self):
        t = paddle.to_tensor(np.zeros((3, 3), "float32"))
        out = t.index_fill(paddle.to_tensor(np.array([1])), 1, 9.0)
        assert out.numpy()[0, 1] == 9.0 and out.numpy()[0, 0] == 0.0
        m = paddle.to_tensor(np.array([True, False, True]))
        src = paddle.to_tensor(np.array([7.0, 8.0, 9.0], "float32"))
        ms = paddle.zeros([3]).masked_scatter(m, src)
        assert ms.numpy().tolist() == [7.0, 0.0, 8.0]

    def test_apply_and_meta(self):
        t = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        out = t.apply(lambda v: v * 10)
        assert out.numpy().tolist() == [10.0, 20.0]
        t.apply_(lambda v: v + 1)
        assert t.numpy().tolist() == [2.0, 3.0]
        assert t.nbytes == 8 and t.itemsize == 4
        assert isinstance(t.data_ptr(), int)
        assert not t.is_sparse()

    def test_sparse_bridge(self):
        d = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 2.0]], "float32"))
        sp = d.to_sparse_coo()
        assert type(sp).__name__ == "SparseCooTensor"
        with pytest.raises(ValueError):
            d.values()
        with pytest.raises(ValueError):
            d.indices()
        assert d.coalesce() is d


class TestGlobalInitializer:
    def test_set_and_reset(self):
        nn.initializer.set_global_initializer(nn.initializer.Constant(0.5),
                                              nn.initializer.Constant(0.1))
        try:
            lin = nn.Linear(3, 3)
            np.testing.assert_allclose(lin.weight.numpy(), 0.5)
            np.testing.assert_allclose(lin.bias.numpy(), 0.1)
        finally:
            nn.initializer.set_global_initializer(None, None)
        lin2 = nn.Linear(3, 3)
        assert not np.allclose(lin2.weight.numpy(), 0.5)

    def test_param_attr_beats_global(self):
        nn.initializer.set_global_initializer(nn.initializer.Constant(0.5))
        try:
            lin = nn.Linear(3, 3, weight_attr=nn.ParamAttr(
                initializer=nn.initializer.Constant(2.0)))
            np.testing.assert_allclose(lin.weight.numpy(), 2.0)
        finally:
            nn.initializer.set_global_initializer(None, None)


class TestJitFills:
    def test_traced_layer(self):
        layer = nn.Linear(4, 4)
        x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
        outs, traced = paddle.jit.TracedLayer.trace(layer, x)
        out2 = traced(paddle.to_tensor(np.random.randn(2, 4)
                                       .astype("float32")))
        assert out2.shape == [2, 4]

    def test_levels(self):
        paddle.jit.set_code_level(42)
        paddle.jit.set_verbosity(3)


class TestTopLevelFills:
    def test_printoptions_and_signal(self):
        paddle.set_printoptions(precision=3, sci_mode=False)
        paddle.disable_signal_handler()

    def test_subset_random_sampler(self):
        s = paddle.io.SubsetRandomSampler([5, 3, 8])
        assert sorted(list(s)) == [3, 5, 8]
        assert len(s) == 3


class TestReviewFixes6:
    def test_is_sparse_callable(self):
        t = paddle.zeros([2])
        assert t.is_sparse() is False
        sp = paddle.to_tensor(np.eye(2, dtype="float32")).to_sparse_coo()
        assert sp.is_sparse() is True

    def test_class_center_sample_overflow_raises(self):
        lab = paddle.to_tensor(np.arange(10, dtype="int32"))
        with pytest.raises(ValueError, match="distinct positive"):
            nn.functional.class_center_sample(lab, 20, 4)

    def test_masked_scatter_insufficient_raises(self):
        m = paddle.to_tensor(np.array([True, True, True]))
        with pytest.raises(ValueError, match="masked_scatter"):
            paddle.zeros([3]).masked_scatter(
                m, paddle.to_tensor(np.array([1.0], "float32")))

    def test_affine_transform_grads_flow(self):
        from paddle_tpu.distribution import AffineTransform
        loc = paddle.to_tensor(np.array([3.0], "float32"),
                               stop_gradient=False)
        scale = paddle.to_tensor(np.array([2.0], "float32"),
                                 stop_gradient=False)
        t = AffineTransform(loc, scale)
        x = paddle.to_tensor(np.array([1.5], "float32"))
        t.forward(x).sum().backward()
        np.testing.assert_allclose(np.asarray(loc.grad.numpy()), [1.0])
        np.testing.assert_allclose(np.asarray(scale.grad.numpy()), [1.5])

    def test_sparse_attention_empty_row_zero(self):
        qv = paddle.to_tensor(np.random.randn(1, 1, 3, 4).astype("float32"))
        # row 1 empty: offsets [0, 1, 1, 2], cols [0, 2]
        off = paddle.to_tensor(np.array([[[0, 1, 1, 2]]], "int32"))
        cols = paddle.to_tensor(np.array([[[0, 2]]], "int32"))
        out = nn.functional.sparse_attention(qv, qv, qv, off, cols)
        np.testing.assert_allclose(np.asarray(out.numpy())[0, 0, 1], 0.0,
                                   atol=1e-6)

    def test_transformed_empty_transforms(self):
        from paddle_tpu.distribution import Normal, TransformedDistribution
        d = TransformedDistribution(Normal(0.0, 1.0), [])
        v = paddle.to_tensor(np.array([0.3], "float32"))
        base = Normal(0.0, 1.0).log_prob(v)
        np.testing.assert_allclose(d.log_prob(v).numpy(), base.numpy())
