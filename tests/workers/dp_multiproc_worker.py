"""2-process data-parallel worker (SURVEY §4 TestDistBase pattern).

Launched by tests/test_multiprocess.py via paddle_tpu.distributed.launch.
Each process owns ONE cpu device; init_parallel_env bootstraps
jax.distributed from the launcher's env contract; the train step runs as a
pjit program over the 2-device global mesh, with the batch assembled from
per-process local shards. Rank 0 prints the loss trajectory for the parity
check against a single-process run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import paddle_tpu as paddle

paddle.device.force_platform("cpu", 1)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def main():
    paddle.distributed.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = jax.process_count()

    devs = jax.devices()
    assert len(devs) == world, devs
    mesh = Mesh(np.array(devs), ("dp",))
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("dp"))

    # cross-process collective sanity: psum of (rank+1) over dp == 3
    local = np.full((1, 4), float(rank + 1), np.float32)
    garr = jax.make_array_from_process_local_data(row, local)
    total = jax.jit(lambda a: jnp.sum(a[:, 0]),
                    out_shardings=repl)(garr)
    np.testing.assert_allclose(np.asarray(total), 3.0)
    if rank == 0:
        print("allreduce_ok 3.0", flush=True)

    # DP train step parity: global batch 4, each process feeds its half
    D = 8
    rng = np.random.default_rng(0)
    x_np = rng.normal(0, 1, (4, D)).astype(np.float32)
    y_np = rng.normal(0, 1, (4, 1)).astype(np.float32)
    w0 = (np.arange(D, dtype=np.float32).reshape(D, 1) / D) - 0.5

    half = slice(rank * 2, rank * 2 + 2)
    x = jax.make_array_from_process_local_data(row, x_np[half])
    y = jax.make_array_from_process_local_data(row, y_np[half])
    w = jax.device_put(w0, repl)

    @jax.jit
    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.1 * g, loss

    losses = []
    for _ in range(5):
        w, loss = step(w, x, y)
        losses.append(float(jax.device_get(
            jax.device_put(loss, repl))))
    if rank == 0:
        print("losses " + " ".join(f"{v:.6f}" for v in losses), flush=True)


if __name__ == "__main__":
    main()
