"""4-process x 2-device hybrid (dp2 x mp4) worker (SURVEY §4 TestDistBase).

Launched by tests/test_multiprocess.py. Each process owns TWO cpu devices;
the four processes form the 8-device global mesh (dp=2, mp=4). The train
step is ONE pjit program with megatron-style TP (column-parallel w1,
row-parallel w2) over ``mp`` and the batch sharded over ``dp`` — XLA
inserts the cross-process collectives. Rank 0 prints the loss trajectory;
at the end every process participates in a distributed checkpoint save
(per-process shards via orbax), which the test then loads SINGLE-process
on a different topology (reshard-on-load across process counts).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import paddle_tpu as paddle

paddle.device.force_platform("cpu", 2)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

B, D, H = 8, 16, 32


def main():
    out_dir = sys.argv[1]
    paddle.distributed.init_parallel_env()
    assert jax.process_count() == 4, jax.process_count()
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    devs = jax.devices()
    assert len(devs) == 8, devs

    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "mp"))
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P("dp"))
    col_sh = NamedSharding(mesh, P(None, "mp"))   # w1: (D, H) col-parallel
    row_sh = NamedSharding(mesh, P("mp", None))   # w2: (H, 1) row-parallel

    rng = np.random.default_rng(0)
    x_np = rng.normal(0, 1, (B, D)).astype(np.float32)
    y_np = rng.normal(0, 1, (B, 1)).astype(np.float32)
    w1_np = rng.normal(0, 0.3, (D, H)).astype(np.float32)
    w2_np = rng.normal(0, 0.3, (H, 1)).astype(np.float32)

    def make(sharding, host):
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    x = make(batch_sh, x_np)
    y = make(batch_sh, y_np)
    w1 = make(col_sh, w1_np)
    w2 = make(row_sh, w2_np)

    @jax.jit
    def step(w1, w2, x, y):
        def loss_fn(w1, w2):
            h = jnp.tanh(x @ w1)      # col-parallel: h sharded over mp
            pred = h @ w2             # row-parallel: psum over mp by XLA
            return jnp.mean((pred - y) ** 2)
        loss, (g1, g2) = jax.value_and_grad(loss_fn, argnums=(0, 1))(w1, w2)
        return w1 - 0.1 * g1, w2 - 0.1 * g2, loss

    losses = []
    for _ in range(4):
        w1, w2, loss = step(w1, w2, x, y)
        losses.append(float(jax.device_get(jax.device_put(loss, repl))))
    if rank == 0:
        print("losses " + " ".join(f"{v:.6f}" for v in losses), flush=True)

    # distributed checkpoint: every process saves only its addressable
    # shards; the test reloads single-process on a DIFFERENT topology
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.checkpoint import save_state_dict
    state = {"model": {"w1": Tensor(w1), "w2": Tensor(w2)},
             "meta": {"steps": Tensor(jnp.asarray(4.0))}}
    save_state_dict(state, out_dir)
    if rank == 0:
        print("ckpt_saved", flush=True)


if __name__ == "__main__":
    main()
