"""Engine factory the fleet worker processes load (ISSUE 20 tests).

A self-contained copy of ``tests/test_serving.py``'s toy LM — the fleet
worker imports this by name (``fleet_toy_factory:make_engine``) in a
FRESH process, so it cannot reach into the pytest module; the two copies
must stay numerically identical (the parity test in ``test_fleet.py``
compares streamed tokens against the in-process ``dense_reference``).

Greedy argmax over a cache-dependent, position-weighted readout: paging
or streaming mistakes change the decoded SEQUENCE, not just some hidden
state — bit-identical token streams across the process boundary are the
proof the wire protocol is transparent.
"""

import numpy as np

import jax
import jax.numpy as jnp

# the worker process runs headless: pin the backend the same way the
# pytest conftest does for the parent
jax.config.update("jax_platforms", "cpu")

from paddle_tpu import serving                              # noqa: E402
from paddle_tpu.core.tensor import Tensor as T              # noqa: E402

V = 31
L, H, D, M = 2, 2, 4, 64

_W = jnp.asarray(np.linspace(-1.0, 1.0, D * V).reshape(D, V)
                 .astype(np.float32))
_POSW = (jnp.arange(M, dtype=jnp.float32) + 1.0) / M


def _kv_of(tok_f):
    ramp_d = (jnp.arange(D, dtype=jnp.float32) + 1.0) / D
    ramp_h = (jnp.arange(H, dtype=jnp.float32) + 1.0) / H
    base = (tok_f[..., None, None] + 1.0) / V
    return base * ramp_h[:, None] * ramp_d[None, :]


def _readout(cache00, valid):
    feat = jnp.einsum("...hmd,...m,m->...d", cache00.astype(jnp.float32),
                      valid.astype(jnp.float32), _POSW)
    return feat @ _W


def toy_step(tok, cache, t):
    tok_d, c, td = tok._data, cache._data, t._data.astype(jnp.int32)
    kv = _kv_of(tok_d[:, 0].astype(jnp.float32))

    def wr(cb, kvb, tb):
        page = jnp.broadcast_to(kvb[None, None, :, None, :],
                                (L, 2, H, 1, D)).astype(cb.dtype)
        return jax.lax.dynamic_update_slice(cb, page, (0, 0, 0, tb, 0))

    c2 = jax.vmap(wr, in_axes=(2, 0, 0), out_axes=2)(c, kv, td)
    valid = jnp.arange(M)[None, :] <= td[:, None]
    logits = _readout(c2[0, 0], valid)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    return T(nxt), T(c2)


def toy_prefill(ids, cache):
    idsd, c = ids._data, cache._data
    lp = idsd.shape[1]
    kv = jnp.transpose(_kv_of(idsd[0].astype(jnp.float32)), (1, 0, 2))
    c = c.at[:, :, 0, :, :lp, :].set(
        jnp.broadcast_to(kv, (L, 2, H, lp, D)).astype(c.dtype))
    valid = (jnp.arange(M) < lp)[None, :]
    logits = _readout(c[0, 0], valid)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    return T(nxt), T(c)


def dense_reference(prompt, n_new):
    """The bs=1 dense loop — same callables, no paging. Greedy oracle the
    parent-side parity tests compare the streamed tokens against."""
    cache = T(jnp.zeros((L, 2, 1, H, M, D), jnp.float32))
    tok, cache = toy_prefill(T(jnp.asarray(prompt[None, :], jnp.int32)),
                             cache)
    toks = [int(np.asarray(tok._data)[0, 0])]
    t = int(prompt.size)
    for _ in range(n_new - 1):
        tok, cache = toy_step(tok, cache, T(jnp.asarray([t], jnp.int32)))
        toks.append(int(np.asarray(tok._data)[0, 0]))
        t += 1
    return toks


def make_engine(max_batch=4, page_size=16, kv_dtype="native", **kw):
    cfg = serving.ServingConfig(
        num_layers=L, num_heads=H, head_dim=D, max_len=M,
        max_batch=max_batch,
        buckets=tuple(b for b in (1, 4, 16) if b <= max_batch)
        or (max_batch,),
        page_size=page_size, kv_dtype=kv_dtype, **kw)
    return serving.Engine(toy_prefill, toy_step, cfg)
