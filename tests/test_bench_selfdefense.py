"""The benchmark of record must defend its own capture (VERDICT r4 #1).

Pins the pure logic bench.py uses: last-known-good parsing out of
RESULTS.md and the anomaly classifier that decides when a run retries and
when it publishes ``"suspect": true``.
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def test_lkg_record_parses_from_results_md():
    rec = bench._read_lkg("llama_train_tokens_per_sec_per_chip")
    assert rec is not None, "RESULTS.md must carry an LKG record"
    assert isinstance(rec["value"], (int, float)) and rec["value"] > 0
    assert "device" in rec


def test_lkg_unknown_metric_is_none():
    assert bench._read_lkg("no_such_metric") is None


def test_lkg_skips_malformed_value(tmp_path, monkeypatch, capsys):
    # a hand-edited record with a string value must disable the guard,
    # not crash the bench
    fake = tmp_path / "benchmarks"
    fake.mkdir()
    (fake / "RESULTS.md").write_text(
        '<!-- LKG {"metric": "m", "value": "10252"} -->\n')
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    assert bench._read_lkg("m") is None


def test_anomaly_flags_throughput_collapse():
    lkg = {"metric": "m", "value": 10252.0}
    reasons = bench._anomaly_reasons(2713.0, [100.0] * 6, lkg)
    assert any("last-known-good" in r for r in reasons)


def test_anomaly_flags_step_time_skew():
    reasons = bench._anomaly_reasons(10000.0, [100, 100, 100, 100, 100, 900],
                                     None)
    assert any("p90" in r for r in reasons)


def test_healthy_run_is_clean():
    lkg = {"metric": "m", "value": 10252.0}
    assert bench._anomaly_reasons(9800.0, [101, 100, 99, 100, 102, 100],
                                  lkg) == []


def test_no_lkg_disables_throughput_guard_only():
    assert bench._anomaly_reasons(10.0, [100.0] * 6, None) == []


def test_telemetry_detail_is_schema_stable():
    # every bench JSON row must carry the full telemetry field set, zeros
    # included, so BENCH_r0*.json stays diffable across rounds
    detail = bench._telemetry_detail({})
    assert set(detail) == set(bench.TELEMETRY_FIELDS)
    assert all(v == 0 for v in detail.values())
    assert "dispatch.ops_total" in detail and "jit.compiles_total" in detail


def test_telemetry_detail_selects_counters():
    snap = {"dispatch.ops_total": 123.0, "jit.compiles_total": 2.0,
            "dispatch.latency_seconds": {"count": 123},  # ignored: not selected
            "jit.cache_hits_total": 7.0}
    detail = bench._telemetry_detail(snap)
    assert detail["dispatch.ops_total"] == 123
    assert detail["jit.compiles_total"] == 2
    assert detail["jit.cache_hits_total"] == 7
    assert detail["jit.graph_breaks_total"] == 0


def test_bench_main_emits_telemetry():
    # main() must wire _telemetry_detail into the JSON "detail" block (the
    # full main() needs a device-sized run; pin the wiring statically)
    import inspect
    src = inspect.getsource(bench.main)
    assert "_telemetry_detail" in src and '"telemetry"' in src
    assert "obs.enable()" in src


# ---------------------------------------------------------------------------
# training-under-fire counter block (ISSUE 10)
# ---------------------------------------------------------------------------

def test_train_resilience_detail_is_schema_stable():
    # the row of record pins the train.* recovery counters; all-zero on a
    # healthy run IS the claim — a nonzero diff means the measured run
    # itself retried/skipped/rolled back
    detail = bench._train_resilience_detail({})
    assert set(detail) == set(bench.TRAIN_RESILIENCE_FIELDS)
    assert set(bench.TRAIN_RESILIENCE_FIELDS) == {
        "retries", "restarts", "skipped_batches", "watchdog_trips"}
    assert all(v == 0 for v in detail.values())


def test_train_resilience_detail_sums_labeled_families():
    # train.retries_total carries a site label and the watchdog a kind
    # label — the bench block reports family totals
    snap = {"train.retries_total": {"site=train.step": 2.0,
                                    "site=train.data": 1.0},
            "train.restarts_total": 1.0,
            "train.watchdog_trips_total": {"kind=hung": 1.0}}
    detail = bench._train_resilience_detail(snap)
    assert detail["retries"] == 3
    assert detail["restarts"] == 1
    assert detail["watchdog_trips"] == 1
    assert detail["skipped_batches"] == 0


def test_bench_main_emits_train_resilience():
    import inspect
    src = inspect.getsource(bench.main)
    assert "_train_resilience_detail" in src and '"train_resilience"' in src
    assert "TRAIN_RESILIENCE_FIELDS" in src


# ---------------------------------------------------------------------------
# whole-step capture block (ISSUE 11)
# ---------------------------------------------------------------------------

def test_step_capture_detail_is_schema_stable():
    # the row of record pins the train.capture_* counters; hits > 0 with
    # zero bypasses on a healthy run IS the claim — all-bypass means the
    # measured run was the eager debug tier, not the compiled step
    detail = bench._step_capture_detail({}, "auto")
    assert set(detail) == set(bench.STEP_CAPTURE_FIELDS)
    assert set(bench.STEP_CAPTURE_FIELDS) == {
        "mode", "hits", "retraces", "bypasses", "donated_bytes"}
    assert detail["mode"] == "auto"
    assert detail["hits"] == 0 and detail["donated_bytes"] == 0


def test_step_capture_detail_sums_labeled_bypasses():
    snap = {"train.capture_hits_total": 20.0,
            "train.capture_retraces_total": 1.0,
            "train.capture_bypasses_total": {"reason=capture_seam": 2.0,
                                             "reason=untraceable": 1.0},
            "train.capture_donated_bytes": 7383052.0}
    detail = bench._step_capture_detail(snap, "auto")
    assert detail["hits"] == 20
    assert detail["retraces"] == 1
    assert detail["bypasses"] == 3
    assert detail["donated_bytes"] == 7383052


def test_all_bypass_run_is_suspect():
    cap = {"mode": "auto", "hits": 0, "retraces": 0, "bypasses": 6,
           "donated_bytes": 0}
    reasons = bench._capture_suspect_reasons(cap)
    assert reasons and "bypassed" in reasons[0]


def test_capture_off_run_is_suspect_and_healthy_is_clean():
    # mode=off means the number of record measured the eager debug tier —
    # e.g. the test suite's PADDLE_TPU_STEP_CAPTURE=off leaking into the
    # bench environment — which must read as suspect, not silently stand
    reasons = bench._capture_suspect_reasons(
        {"mode": "off", "hits": 0, "retraces": 0, "bypasses": 0,
         "donated_bytes": 0})
    assert reasons and "eager debug tier" in reasons[0]
    assert bench._capture_suspect_reasons(
        {"mode": "auto", "hits": 5, "retraces": 1, "bypasses": 0,
         "donated_bytes": 123}) == []


def test_bench_main_emits_step_capture_and_warm_compile():
    # main() must route the train step over capture_step, report the
    # step-capture counter block, and pin cold vs warm compile seconds
    # (the persistent-compilation-cache win of record)
    import inspect
    src = inspect.getsource(bench.main)
    assert "capture_step" in src
    assert "_step_capture_detail" in src and '"step_capture"' in src
    assert "_capture_suspect_reasons" in src
    assert '"compile_warm_s"' in src and '"compile_s"' in src
    assert "PADDLE_TPU_COMPILE_CACHE_DIR" in src
    assert '"step_ms_p50"' in src  # the structural perf pin stays


def test_compile_cache_is_wired_at_init():
    # PADDLE_TPU_COMPILE_CACHE_DIR reaches jax's persistent compilation
    # cache at import (ROADMAP 3b) — pinned structurally
    import inspect

    import paddle_tpu
    src = inspect.getsource(paddle_tpu._wire_compile_cache)
    assert "PADDLE_TPU_COMPILE_CACHE_DIR" in src
    assert "jax_compilation_cache_dir" in src


def test_cross_host_sync_roots_cover_captured_step():
    # the captured-step entry joins the dispatch fast-path reachability
    # roots: a .item()/.numpy() anywhere a captured call can reach is a
    # per-STEP stall now, flagged by the same whole-program rule
    import sys
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.engine import DEFAULT_CONFIG
    assert "paddle_tpu/core/step_capture.py::__call__" in \
        DEFAULT_CONFIG["fast_path_roots"]


def test_eager_dispatch_bench_pins_captured_leg():
    mod = _load_bench_eager_dispatch()
    assert {"captured_step_ms", "captured_dispatches_per_step",
            "captured_speedup_x"} <= set(mod.RESULT_FIELDS)
    import inspect
    src = inspect.getsource(mod.main)
    assert "--captured-step" in src and "_captured_leg" in src


# ---------------------------------------------------------------------------
# tracing-overhead block (ISSUE 12)
# ---------------------------------------------------------------------------

def test_trace_overhead_detail_is_schema_stable():
    # the row of record pins the off/flight/on captured-step p50s: the
    # always-on flight recorder must be near-free on the hot path
    block = bench._trace_overhead_detail(10.0, 10.1, 10.5)
    assert set(block) == set(bench.TRACE_OVERHEAD_FIELDS)
    assert set(bench.TRACE_OVERHEAD_FIELDS) == {
        "step_ms_p50_off", "step_ms_p50_flight", "step_ms_p50_on",
        "flight_overhead_pct", "on_overhead_pct"}
    assert block["flight_overhead_pct"] == 1.0
    assert block["on_overhead_pct"] == 5.0


def test_trace_overhead_zero_off_p50_is_safe():
    block = bench._trace_overhead_detail(0.0, 0.0, 0.0)
    assert block["flight_overhead_pct"] == 0.0


def test_flight_overhead_over_two_percent_is_suspect():
    # >2% flight-vs-off p50 delta disqualifies the run: every number of
    # record ships with the recorder on, so its cost must stay invisible
    bad = bench._trace_overhead_detail(10.0, 10.3, 10.3)
    reasons = bench._trace_suspect_reasons(bad)
    assert reasons and "flight-recorder" in reasons[0]
    good = bench._trace_overhead_detail(10.0, 10.1, 12.0)
    assert bench._trace_suspect_reasons(good) == []   # "on" is debug tier


def test_bench_main_emits_trace_overhead():
    import inspect
    src = inspect.getsource(bench.main)
    assert "_trace_overhead_detail" in src and '"trace_overhead"' in src
    assert "_trace_suspect_reasons" in src
    assert "set_mode" in src      # measured under real mode switches
    for m in ('"off"', '"flight"', '"on"'):
        assert m in src, m


# ---------------------------------------------------------------------------
# eager-dispatch bench schema + dispatch fast-path hygiene (ISSUE 2)
# ---------------------------------------------------------------------------

def _load_bench_eager_dispatch():
    spec = importlib.util.spec_from_file_location(
        "bench_eager_dispatch",
        os.path.join(REPO, "benchmarks", "bench_eager_dispatch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_eager_dispatch_bench_pins_cache_fields():
    # the JSON row of record must carry the cache-vs-cold comparison; these
    # names are what RESULTS.md / BENCH_r0*.json diffs key on
    mod = _load_bench_eager_dispatch()
    assert {"cached_ms", "cold_ms", "hit_rate", "speedup_x"} <= \
        set(mod.RESULT_FIELDS)
    import inspect
    src = inspect.getsource(mod.main)
    # main() must build the row from exactly the pinned schema
    assert "RESULT_FIELDS" in src
    for field in mod.RESULT_FIELDS:
        assert f'"{field}"' in src, field


def test_dispatch_fast_path_has_no_per_call_imports():
    # bridge: the per-call-import ban is graft-lint's ``hot-path-import``
    # rule now (tools/lint/rules/hot_path_import.py), configured over the
    # whole core/{tensor,dispatch_cache,autograd}.py set instead of three
    # hardcoded functions. core/tensor.py must stay at ZERO findings with
    # no baseline allowance — the dispatch fast path pays that import per
    # op, not per backward walk.
    import ast
    import sys
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint import run_lint
    result = run_lint(paths=["paddle_tpu/core/tensor.py",
                             "paddle_tpu/core/dispatch_cache.py"],
                      rules=["hot-path-import"])
    assert [f.text() for f in result.new] == []
    # structural pin: the fast-path functions this protects still exist
    with open(os.path.join(REPO, "paddle_tpu", "core", "tensor.py")) as f:
        tree = ast.parse(f.read())
    names = {n.name for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)}
    assert {"apply", "_apply_impl", "_apply_cached"} <= names


# ---------------------------------------------------------------------------
# graft-lint machine formats: --format=json (PR 3) + --format=sarif
# (ISSUE 14) — CI consumers key on these schemas
# ---------------------------------------------------------------------------

def _lint_cli_doc(tmp_path, fmt):
    import io
    import contextlib
    import json
    import textwrap
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.cli import main
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "w.py").write_text(textwrap.dedent("""\
        import threading

        class Worker:
            def start(self):
                threading.Thread(target=self._a, daemon=True).start()
                threading.Thread(target=self._b, daemon=True).start()

            def _a(self):
                self.n = 1

            def _b(self):
                self.n = 2
        """))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main([str(pkg), f"--format={fmt}", "--no-baseline",
                   "--no-cache"])
    return rc, json.loads(buf.getvalue())


def test_lint_json_format_schema_pin(tmp_path):
    rc, doc = _lint_cli_doc(tmp_path, "json")
    assert rc == 1 and doc["clean"] is False
    assert {"files_checked", "findings", "counts_by_rule", "cache",
            "run_seconds", "errors"} <= set(doc)
    assert doc["counts_by_rule"] == {"shared-state-race": 1}
    # ISSUE 18: witness chains ride along in the JSON rows too
    assert set(doc["findings"][0]) == {"path", "line", "rule", "message",
                                       "related"}


def test_lint_sarif_format_schema_pin(tmp_path):
    # GitHub code scanning loads exactly this shape: version 2.1.0, one
    # run, driver rule metadata for EVERY registered rule, results with
    # ruleId/message/locations, witness paths as relatedLocations
    from tools.lint import RULES
    from tools.lint.cli import SARIF_VERSION
    rc, doc = _lint_cli_doc(tmp_path, "sarif")
    assert rc == 1
    assert doc["version"] == SARIF_VERSION == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graft-lint"
    assert [r["id"] for r in driver["rules"]] == sorted(RULES)
    assert all({"id", "shortDescription", "defaultConfiguration"}
               <= set(r) for r in driver["rules"])
    (res,) = run["results"]
    assert res["ruleId"] == "shared-state-race"
    assert res["ruleIndex"] == sorted(RULES).index("shared-state-race")
    assert res["level"] == "warning" and res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("pkg/w.py")
    assert loc["region"]["startLine"] > 0
    # the race finding's witness chain (root -> ... -> access), per side
    rel = res["relatedLocations"]
    assert len(rel) >= 2
    for r in rel:
        assert r["message"]["text"].startswith("witness:")
        assert r["physicalLocation"]["region"]["startLine"] > 0


def test_lint_sarif_clean_run_has_empty_results(tmp_path):
    import io
    import contextlib
    import json
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.cli import main
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main([str(f), "--format=sarif", "--no-baseline", "--no-cache"])
    doc = json.loads(buf.getvalue())
    assert rc == 0 and doc["runs"][0]["results"] == []


# ---------------------------------------------------------------------------
# graft-lint 4.0 (ISSUE 18): the CFG rules in the machine formats —
# exception-contract and resource-discipline ship witness paths, and the
# DEFAULT_CONFIG breaker-probe pair is live even outside the repo tree
# ---------------------------------------------------------------------------

def _probe_leak_pkg(tmp_path):
    import textwrap
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    # DEFAULT_CONFIG's handleless breaker-probe pair: before_call() takes
    # the half-open probe, nothing ever returns it
    (pkg / "c.py").write_text(textwrap.dedent("""\
        class Client:
            def call(self, breaker, srv):
                breaker.before_call()
                return srv.send()
        """))
    return pkg


def test_lint_json_resource_discipline_carries_witnesses(tmp_path):
    import io
    import contextlib
    import json
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.cli import main
    pkg = _probe_leak_pkg(tmp_path)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main([str(pkg), "--format=json", "--no-baseline", "--no-cache"])
    doc = json.loads(buf.getvalue())
    assert rc == 1
    assert doc["counts_by_rule"] == {"resource-discipline": 1}
    (f,) = doc["findings"]
    assert set(f) == {"path", "line", "rule", "message", "related"}
    assert "'breaker-probe'" in f["message"]
    msgs = [r["message"] for r in f["related"]]
    assert any("acquired here" in m for m in msgs)
    assert all(m.startswith("witness:") for m in msgs)
    assert all(r["line"] > 0 for r in f["related"])


def test_lint_sarif_resource_discipline_related_locations(tmp_path):
    import io
    import contextlib
    import json
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint import RULES
    from tools.lint.cli import main
    pkg = _probe_leak_pkg(tmp_path)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main([str(pkg), "--format=sarif", "--no-baseline", "--no-cache"])
    doc = json.loads(buf.getvalue())
    assert rc == 1
    (run,) = doc["runs"]
    # both CFG rules ship driver metadata (the sorted-RULES pin above
    # covers this implicitly; keep the names explicit here)
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert "exception-contract" in ids and "resource-discipline" in ids
    (res,) = run["results"]
    assert res["ruleId"] == "resource-discipline"
    assert res["ruleIndex"] == sorted(RULES).index("resource-discipline")
    rel = res["relatedLocations"]
    assert rel and all(
        r["message"]["text"].startswith("witness:") and
        r["physicalLocation"]["region"]["startLine"] > 0 for r in rel)


def test_lint_sarif_exception_contract_witness_chain(tmp_path):
    # exception-contract is path-scoped in DEFAULT_CONFIG, so drive
    # sarif_report() off a run with an explicit contract table
    import textwrap
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint import run_lint
    from tools.lint.cli import sarif_report
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "entry.py").write_text(textwrap.dedent("""\
        def work():
            raise KeyError("missing")

        class Door:
            def do_call(self, req):
                return work()
        """))
    res = run_lint(paths=["."], rules=["exception-contract"],
                   root=str(tmp_path),
                   config={"exception_contracts": {
                       "pkg/entry.py": {"Door.do_call": ["ValueError"]}}})
    (f,) = res.new
    assert f.rule == "exception-contract" and "KeyError" in f.message
    doc = sarif_report(res)
    (sres,) = doc["runs"][0]["results"]
    assert sres["ruleId"] == "exception-contract"
    rel = sres["relatedLocations"]
    # the witness chain walks root -> raising function, each hop named
    assert [r["message"]["text"] for r in rel] == \
        ["witness: 'Door.do_call'", "witness: 'work'"]
    assert rel[-1]["physicalLocation"]["region"]["startLine"] == 2


# ---------------------------------------------------------------------------
# graft-lint 5.0 (ISSUE 19): the blocking rules in the machine formats —
# witness chains name the root, the acquire site, and the blocking call,
# and the latency-invariant config tables are pinned against silent edits
# ---------------------------------------------------------------------------

def test_lint_json_blocking_under_lock_carries_witnesses(tmp_path):
    import io
    import contextlib
    import json
    import textwrap
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.cli import main
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "w.py").write_text(textwrap.dedent("""\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.jobs = None

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    return self.jobs.get()
        """))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main([str(pkg), "--format=json", "--no-baseline", "--no-cache"])
    doc = json.loads(buf.getvalue())
    assert rc == 1
    assert doc["counts_by_rule"] == {"blocking-under-lock": 1}
    (f,) = doc["findings"]
    assert set(f) == {"path", "line", "rule", "message", "related"}
    assert "while holding" in f["message"]
    msgs = [r["message"] for r in f["related"]]
    # root -> ... witness hops, then the acquire site, then the block
    assert msgs[0].startswith("witness:")
    assert any(m.startswith("acquires") for m in msgs)
    assert msgs[-1].startswith("blocks: queue")
    assert all(r["line"] > 0 for r in f["related"])


def test_lint_sarif_unbounded_wait_related_locations(tmp_path):
    # unbounded-wait is config-scoped, so drive sarif_report() off a
    # run with explicit bounded_wait tables
    import textwrap
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint import run_lint
    from tools.lint.cli import sarif_report
    pkg = tmp_path / "pkg" / "srv"
    pkg.mkdir(parents=True)
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "loop.py").write_text(textwrap.dedent("""\
        class Pump:
            def __init__(self, q):
                self.jobs = q

            def _poll_loop(self):
                return self._pull()

            def _pull(self):
                return self.jobs.get()
        """))
    res = run_lint(paths=["."], rules=["unbounded-wait"],
                   root=str(tmp_path),
                   config={"bounded_wait_paths": ["pkg/srv"],
                           "bounded_wait_roots": {
                               "pkg/srv/loop.py": ["Pump._poll_loop"]}})
    (f,) = res.new
    assert f.rule == "unbounded-wait" and "poll thread" in f.message
    doc = sarif_report(res)
    (sres,) = doc["runs"][0]["results"]
    assert sres["ruleId"] == "unbounded-wait"
    rel = sres["relatedLocations"]
    # the chain walks root -> waiting function, then names the wait
    assert [r["message"]["text"] for r in rel] == \
        ["witness: 'Pump._poll_loop'", "witness: 'Pump._pull'",
         "waits: queue 'self.jobs.get'"]
    assert rel[-1]["physicalLocation"]["region"]["startLine"] == 9


def test_lint_sarif_hot_path_stall_related_locations(tmp_path):
    import textwrap
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint import run_lint
    from tools.lint.cli import sarif_report
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "hot.py").write_text(textwrap.dedent("""\
        import time

        def dispatch(x):
            return _helper(x)

        def _helper(x):
            time.sleep(0.01)
            return x
        """))
    res = run_lint(paths=["."], rules=["hot-path-stall"],
                   root=str(tmp_path),
                   config={"fast_path_roots": ["pkg/hot.py::dispatch"]})
    (f,) = res.new
    assert f.rule == "hot-path-stall"
    doc = sarif_report(res)
    (sres,) = doc["runs"][0]["results"]
    assert sres["ruleId"] == "hot-path-stall"
    rel = sres["relatedLocations"]
    assert [r["message"]["text"] for r in rel] == \
        ["witness: 'dispatch'", "witness: '_helper'",
         "stalls: sleep 'time.sleep'"]
    assert rel[-1]["physicalLocation"]["region"]["startLine"] == 7


def test_default_config_pins_latency_invariant_tables():
    # MIGRATING "Latency invariants": the strict bounded-wait tier and
    # the reviewed fast-path lock exemptions are part of the contract of
    # record — membership drift must be a conscious, reviewed edit here
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.engine import DEFAULT_CONFIG
    assert {"paddle_tpu/serving", "paddle_tpu/serving/http.py",
            "paddle_tpu/serving/router.py",
            "paddle_tpu/resilience/watchdog.py",
            "paddle_tpu/resilience/trainer.py",
            "paddle_tpu/distributed/ps_service.py"} <= \
        set(DEFAULT_CONFIG["bounded_wait_paths"])
    # the bounded-wait poll roots name real long-lived threads
    roots = DEFAULT_CONFIG["bounded_wait_roots"]
    assert roots["paddle_tpu/serving/router.py"] == ["Router._poll_loop"]
    assert roots["paddle_tpu/resilience/watchdog.py"] == \
        ["StepWatchdog._loop"]
    # every fast-path lock exemption is a reviewed short-critical-section
    # lock, spelled as the analysis' dotted lock id
    exempt = DEFAULT_CONFIG["hot_path_lock_exempt"]
    assert {"paddle_tpu.core.dispatch_cache._LOCK",
            "paddle_tpu.core.fallback._LOCK"} <= set(exempt)
    assert all(e.split(".")[-1].startswith("_") for e in exempt)
    # and the strict wait tier rides the SAME modules the poll-loop tier
    # already guards — the two latency tiers cannot silently diverge
    poll = set(DEFAULT_CONFIG["poll_loop_paths"])
    assert {"paddle_tpu/serving", "paddle_tpu/resilience/watchdog.py",
            "paddle_tpu/resilience/trainer.py"} <= poll


# ---------------------------------------------------------------------------
# serving bench schema (ISSUE 7)
# ---------------------------------------------------------------------------

def _load_bench_generation():
    spec = importlib.util.spec_from_file_location(
        "bench_generation",
        os.path.join(REPO, "benchmarks", "bench_generation.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_bench_pins_schema():
    # the --serving JSON row of record: per-batch rows + the aggregate
    # payload RESULTS.md keys on; drift must fail here, not in a diff
    mod = _load_bench_generation()
    # queue_wait_ms joined in ISSUE 12 (the SLO-bucketed histogram the
    # front door scrapes, surfaced per batch row)
    assert set(mod.SERVING_ROW_FIELDS) == {
        "aggregate_tokens_per_sec", "ttft_ms", "tpot_ms", "queue_wait_ms",
        "scan_greedy_parity", "match_frac", "batch_utilization"}
    assert {"benchmark", "kv_dtype", "page_size",
            "single_stream_tokens_per_sec", "serving", "resilience",
            "speedup_vs_single_stream", "device"} <= \
        set(mod.SERVING_RESULT_FIELDS)
    # the serving-under-fire counters (ISSUE 8): shed/deadline/watchdog
    # visibility is part of the row of record — a bench diff showing
    # nonzero here means the run itself degraded
    assert set(mod.SERVING_RESILIENCE_FIELDS) == {
        "rejected_queue_full", "rejected_deadline", "rejected_shed",
        "watchdog_trips", "replays"}
    import inspect
    src = inspect.getsource(mod._run_serving)
    # rows/payload are asserted against the pinned schema at emit time
    assert "SERVING_ROW_FIELDS" in src and "SERVING_RESULT_FIELDS" in src
    assert "SERVING_RESILIENCE_FIELDS" in src
    for field in (mod.SERVING_ROW_FIELDS + mod.SERVING_RESULT_FIELDS
                  + mod.SERVING_RESILIENCE_FIELDS):
        assert f'"{field}"' in src, field
    # greedy-parity failure is a hard exit: no numbers without the gate
    assert "sys.exit(1)" in src


def test_serving_bench_wired_into_main():
    mod = _load_bench_generation()
    import inspect
    src = inspect.getsource(mod.main)
    assert "--serving" in src and "_run_serving" in src
    assert "--kv-dtype" in src        # the int8 leg is reachable from CLI
    assert "--context-sweep" in src   # the long-context leg (ISSUE 13)
    assert "--http" in src            # the front-door leg (ISSUE 15)
    assert "--fleet" in src           # the fleet-tier leg (ISSUE 20)


def test_http_bench_pins_schema():
    # the --serving --http front-door leg (ISSUE 15): e2e latency through
    # the router + streaming HTTP tier vs in-process submit(), with the
    # router's resilience counters — all-zero-on-healthy is the claim of
    # record, so a bench diff showing retries/failovers/hedges/rejections
    # means the measured run itself degraded
    mod = _load_bench_generation()
    assert set(mod.HTTP_RESULT_FIELDS) == {
        "replicas", "requests", "clients", "aggregate_tokens_per_sec",
        "e2e_p50_ms", "e2e_p99_ms", "inproc_p50_ms", "overhead_p50_ms",
        "router"}
    assert set(mod.HTTP_ROUTER_FIELDS) == {
        "retries", "failovers", "hedges", "rejected"}
    assert "http" in mod.SERVING_RESULT_FIELDS
    import inspect
    src = inspect.getsource(mod._run_http)
    # the block is asserted against the pinned schema at emit time, and
    # every pinned field is actually emitted
    assert "HTTP_RESULT_FIELDS" in src and "HTTP_ROUTER_FIELDS" in src
    for field in mod.HTTP_RESULT_FIELDS + mod.HTTP_ROUTER_FIELDS:
        assert f'"{field}"' in src, field
    # the front-door overhead is DERIVED from the two measured p50s, and
    # the leg measures both paths over the same router + prompts
    assert "overhead_p50_ms" in src and "inproc" in src
    assert "FrontDoor" in src and "Router" in src
    # wired: _run_serving emits the block (None without --http)
    serving_src = inspect.getsource(mod._run_serving)
    assert "_run_http" in serving_src and "args.http" in serving_src


def test_fleet_bench_pins_schema():
    # the --serving --fleet leg (ISSUE 20): e2e latency through a
    # 2-worker OUT-OF-PROCESS FleetSupervisor vs in-process submit(),
    # with the supervisor's crash counters — all-zero-on-healthy is the
    # claim of record, so a bench diff showing respawns/worker_deaths/
    # failovers/rejections means the measured run itself degraded (a
    # worker died and was respawned mid-measurement)
    mod = _load_bench_generation()
    assert set(mod.FLEET_RESULT_FIELDS) == {
        "workers", "requests", "clients", "aggregate_tokens_per_sec",
        "e2e_p50_ms", "e2e_p99_ms", "inproc_p50_ms", "overhead_p50_ms",
        "supervisor"}
    assert set(mod.FLEET_SUPERVISOR_FIELDS) == {
        "respawns", "worker_deaths", "failovers", "rejected"}
    assert "fleet" in mod.SERVING_RESULT_FIELDS
    import inspect
    src = inspect.getsource(mod._run_fleet)
    # the block is asserted against the pinned schema at emit time, and
    # every pinned field is actually emitted
    assert "FLEET_RESULT_FIELDS" in src and "FLEET_SUPERVISOR_FIELDS" in src
    for field in mod.FLEET_RESULT_FIELDS + mod.FLEET_SUPERVISOR_FIELDS:
        assert f'"{field}"' in src, field
    # the overhead is DERIVED from the two measured p50s over the same
    # prompts, and the fleet path really is the out-of-process tier
    assert "overhead_p50_ms" in src and "inproc" in src
    assert "FleetSupervisor" in src and "FleetWorkerSpec" in src
    # a degraded leg (short response, dead worker) fails the bench run
    # instead of printing numbers
    assert "degraded" in src
    # the worker factory ships in the bench module itself, importable as
    # bench_generation:make_fleet_engine by the worker process, and
    # rebuilds under the parent's seed so weights are bit-identical
    factory_src = inspect.getsource(mod.make_fleet_engine)
    assert "seed(0)" in factory_src and "ServingConfig" in factory_src
    # wired: _run_serving emits the block (None without --fleet)
    serving_src = inspect.getsource(mod._run_serving)
    assert "_run_fleet" in serving_src and "args.fleet" in serving_src


# ---------------------------------------------------------------------------
# paged-attention block + context sweep (ISSUE 13)
# ---------------------------------------------------------------------------

def test_paged_attention_block_schema():
    mod = _load_bench_generation()
    assert set(mod.PAGED_ATTENTION_FIELDS) == {
        "mode", "kernel_steps", "dense_steps", "attn_bytes_per_token_live",
        "attn_bytes_per_token_dense", "attn_bytes_source",
        "suspect_reasons"}
    assert set(mod.CONTEXT_SWEEP_FIELDS) == {
        "context", "decode_tokens_per_sec", "attn_bytes_per_token_live",
        "attn_bytes_per_token_dense"}
    # the paged block lands in the payload of record
    assert "paged_attention" in mod.SERVING_RESULT_FIELDS
    assert "context_sweep" in mod.SERVING_RESULT_FIELDS
    import inspect
    src = inspect.getsource(mod._run_serving)
    assert "PAGED_ATTENTION_FIELDS" in src and "_paged_suspect_reasons" \
        in src


def test_paged_bytes_model_tracks_live_pages_not_max_len():
    # the acceptance claim in miniature: the modeled kernel traffic grows
    # with the CONTEXT, the dense traffic with max_len — at a short
    # context in a long cache the two must diverge by ~max_len/context
    mod = _load_bench_generation()
    kw = dict(layers=2, heads=4, head_dim=64, page_size=64,
              storage_bytes=2, n_new=8)
    live_short, dense_short = mod._paged_attn_bytes_per_token(
        max_len=8192, prompt=256, **kw)
    live_long, dense_long = mod._paged_attn_bytes_per_token(
        max_len=8192, prompt=4096, **kw)
    assert dense_short == dense_long          # max_len-bound, context-blind
    assert live_long > live_short * 10        # context-bound
    assert live_short < dense_short / 10      # the short-context win
    # at full context the kernel converges to the dense bound, never above
    live_full, dense_full = mod._paged_attn_bytes_per_token(
        max_len=8192, prompt=8192 - 9, **kw)
    assert live_full <= dense_full


def test_all_dense_on_tpu_is_suspect():
    mod = _load_bench_generation()
    block = {"mode": "auto", "kernel_steps": 0, "dense_steps": 40,
             "attn_bytes_per_token_live": 1, "attn_bytes_per_token_dense": 2}
    reasons = mod._paged_suspect_reasons(block, on_tpu=True)
    assert reasons and "dense" in reasons[0]
    # the same counters are healthy on CPU (auto = dense tier there), when
    # the kernel actually ran, and when the operator forced mode=off
    assert mod._paged_suspect_reasons(block, on_tpu=False) == []
    assert mod._paged_suspect_reasons(
        dict(block, kernel_steps=40, dense_steps=0), on_tpu=True) == []
    assert mod._paged_suspect_reasons(
        dict(block, mode="off"), on_tpu=True) == []


def test_paged_measured_bytes_come_from_cost_registry():
    # ISSUE 16: the tier that ran reports the cost registry's measured
    # per-token bytes (largest warmed bucket's bytes_accessed / bucket);
    # no measured record -> None -> the block stays on the model
    mod = _load_bench_generation()
    recs = {1: {"bytes_accessed": 1000.0}, 4: {"bytes_accessed": 8000.0}}
    assert mod._measured_decode_bytes_per_token(recs) == 2000
    assert mod._measured_decode_bytes_per_token({}) is None
    assert mod._measured_decode_bytes_per_token(
        {4: {"bytes_accessed": None}}) is None
    import inspect
    src = inspect.getsource(mod._run_serving)
    assert "_measured_decode_bytes_per_token" in src
    assert "decode_bucket_records" in src and '"attn_bytes_source"' in src


def test_paged_formula_cross_checks_measurement():
    # one-sided 10% cross-check: the modeled attention-only bytes of the
    # tier that ran must not exceed the measured whole-program traffic
    mod = _load_bench_generation()
    base = {"mode": "auto", "kernel_steps": 0, "dense_steps": 40,
            "attn_bytes_per_token_live": 100,
            "attn_bytes_per_token_dense": 5000,
            "attn_bytes_source": "measured"}
    # formula (6000) > measured dense (5000) * 1.10 -> flagged
    reasons = mod._paged_suspect_reasons(base, on_tpu=False,
                                         formula_live=100,
                                         formula_dense=6000)
    assert reasons and "disagree" in reasons[0]
    # formula within the one-sided envelope -> clean
    assert mod._paged_suspect_reasons(base, on_tpu=False, formula_live=100,
                                      formula_dense=4000) == []
    # source=model (no measurement): no cross-check to run
    assert mod._paged_suspect_reasons(
        dict(base, attn_bytes_source="model"), on_tpu=False,
        formula_live=100, formula_dense=6000) == []
    # kernel tier ran -> the live formula is the one checked
    kblock = dict(base, kernel_steps=40, dense_steps=0,
                  attn_bytes_per_token_live=5000)
    assert mod._paged_suspect_reasons(kblock, on_tpu=False,
                                      formula_live=6000,
                                      formula_dense=100) != []


# ---------------------------------------------------------------------------
# program cost accounting block (ISSUE 16)
# ---------------------------------------------------------------------------

def test_cost_detail_is_schema_stable():
    # the row of record pins the cost block: XLA's modeled step
    # flops/bytes, the modeled MFU from the measured step time, and the
    # HBM ledger's peak/headroom
    assert set(bench.COST_FIELDS) == {
        "model_source", "step_flops", "step_bytes", "mfu_modeled",
        "peak_hbm_bytes", "hbm_headroom_bytes"}
    doc = {"records": [
        {"site": "dispatch", "flops": 1.0, "bytes_accessed": 2.0,
         "model_source": "xla"},
        {"site": "train.step", "flops": 2e12, "bytes_accessed": 1e10,
         "model_source": "xla"}],
        "hbm": {"peak_hbm_bytes": 8 << 30, "headroom_bytes": 8 << 30}}
    block = bench._cost_detail(doc, analytic_step_flops=9e9,
                               step_seconds=0.5, peak_flops=1e13)
    assert set(block) == set(bench.COST_FIELDS)
    assert block["model_source"] == "xla"
    assert block["step_flops"] == 2e12 and block["step_bytes"] == 1e10
    # mfu = flops / (seconds * peak): 2e12 / (0.5 * 1e13) = 0.4
    assert block["mfu_modeled"] == 0.4
    assert block["peak_hbm_bytes"] == 8 << 30


def test_cost_detail_analytic_fallback_and_all_null_suspect():
    # no train.step record -> the analytic flops estimate stands in,
    # labeled as such; nothing at all -> all-null block -> suspect
    block = bench._cost_detail({"records": [], "hbm": {}},
                               analytic_step_flops=1e12,
                               step_seconds=0.5, peak_flops=1e13)
    assert block["model_source"] == "analytic"
    assert block["step_flops"] == 1e12 and block["step_bytes"] is None
    assert block["mfu_modeled"] == 0.2
    assert bench._cost_suspect_reasons(block) == []

    empty = bench._cost_detail({"records": [], "hbm": {}},
                               analytic_step_flops=0.0,
                               step_seconds=0.5, peak_flops=1e13)
    assert empty["model_source"] == "none"
    assert all(empty[k] is None for k in
               ("step_flops", "step_bytes", "mfu_modeled",
                "peak_hbm_bytes", "hbm_headroom_bytes"))
    reasons = bench._cost_suspect_reasons(empty)
    assert reasons and "cost accounting empty" in reasons[0]


def test_bench_main_emits_cost_block():
    import inspect
    src = inspect.getsource(bench.main)
    assert "_cost_detail" in src and '"cost"' in src
    assert "_cost_suspect_reasons" in src
    assert "debug_doc" in src


def test_cross_host_sync_roots_cover_cost_hooks():
    # the cost hook call-sites join the fast-path reachability roots: a
    # host sync reachable from capture would stall every dispatch/compile
    import sys
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint.engine import DEFAULT_CONFIG
    roots = DEFAULT_CONFIG["fast_path_roots"]
    assert "paddle_tpu/observability/cost.py::_on_static_build" in roots
    assert "paddle_tpu/observability/cost.py::_on_dispatch_event" in roots
    assert "paddle_tpu/observability/cost.py" in \
        DEFAULT_CONFIG["span_hot_modules"]


def test_prefix_sharing_block_schema():
    # the --prompt-overlap leg (ISSUE 17): prefill-savings-of-record for
    # refcounted COW page sharing; schema drift must fail here
    mod = _load_bench_generation()
    assert "prefix_sharing" in mod.SERVING_RESULT_FIELDS
    assert set(mod.PREFIX_SHARING_FIELDS) == {
        "page_size", "prompt", "tokens", "requests", "legs",
        "suspect_reasons"}
    assert set(mod.PREFIX_SHARING_LEG_FIELDS) == {
        "overlap_pct", "shared_prefix_tokens",
        "aggregate_tokens_per_sec", "baseline_tokens_per_sec",
        "ttft_ms_p50", "ttft_ms_p99",
        "prefill_tokens_requested", "prefill_tokens_computed",
        "pages_shared_ratio", "prefix_hit_rate", "transcripts_match"}
    import inspect
    src = inspect.getsource(mod._run_prefix_sharing)
    assert "PREFIX_SHARING_FIELDS" in src
    assert "PREFIX_SHARING_LEG_FIELDS" in src
    for field in mod.PREFIX_SHARING_FIELDS + mod.PREFIX_SHARING_LEG_FIELDS:
        assert f'"{field}"' in src, field
    # the leg must compare bit-exact transcripts between sharing modes
    assert "_prefix_suspect_reasons" in src


def test_prefix_sharing_zero_sharing_at_90_is_suspect():
    mod = _load_bench_generation()
    healthy = {"overlap_pct": 90, "pages_shared_ratio": 0.7,
               "transcripts_match": True}
    legs = {"overlap0": dict(healthy, overlap_pct=0, pages_shared_ratio=0),
            "overlap90": dict(healthy)}
    assert mod._prefix_suspect_reasons(legs) == []
    # all-zero sharing at 90% overlap = the feature never ran: suspect
    broken = dict(legs, overlap90=dict(healthy, pages_shared_ratio=0))
    reasons = mod._prefix_suspect_reasons(broken)
    assert reasons and "ZERO pages" in reasons[0]
    # a transcript mismatch on ANY leg means COW leaked K/V: suspect
    leaked = dict(legs, overlap0=dict(
        healthy, overlap_pct=0, pages_shared_ratio=0,
        transcripts_match=False))
    reasons = mod._prefix_suspect_reasons(leaked)
    assert reasons and "COW" in reasons[0]


def test_prefix_sharing_wired_into_main():
    mod = _load_bench_generation()
    import inspect
    assert "--prompt-overlap" in inspect.getsource(mod.main)
    src = inspect.getsource(mod._run_serving)
    assert "_run_prefix_sharing" in src and "prompt_overlap" in src
    # a suspect prefix-sharing block is a hard exit, like greedy parity
    assert "PREFIX SHARING SUSPECT" in src and "sys.exit(1)" in src
