"""Ring attention / Ulysses / flash attention / MoE tests (8-dev CPU mesh)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


@pytest.fixture(autouse=True)
def _reset_topology():
    import paddle_tpu.distributed.topology as topo
    import paddle_tpu.distributed.fleet as fleet_mod
    saved = topo._hcg
    yield
    topo._hcg = saved
    fleet_mod._fleet_initialized = False


def _sep_mesh(sep=8):
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "sep_degree": sep}
    fleet.init(strategy=strategy)


def _ref_attention(q, k, v, causal):
    import jax, jax.numpy as jnp
    qh = np.swapaxes(q, 1, 2).astype(np.float32)
    kh = np.swapaxes(k, 1, 2).astype(np.float32)
    vh = np.swapaxes(v, 1, 2).astype(np.float32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = np.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        L = logits.shape[-1]
        mask = np.tril(np.ones((L, L), bool))
        logits = np.where(mask, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vh)
    return np.swapaxes(out, 1, 2)


def test_flash_attention_matches_reference():
    paddle.seed(0)
    B, L, H, D = 2, 128, 2, 16
    q = paddle.randn([B, L, H, D])
    k = paddle.randn([B, L, H, D])
    v = paddle.randn([B, L, H, D])
    for causal in (False, True):
        out = nn.functional.flash_attention(q, k, v, causal=causal)
        ref = _ref_attention(q.numpy(), k.numpy(), v.numpy(), causal)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def _ref_attention_seg(q, k, v, causal, q_segs, kv_segs):
    """Masked reference: rows attend only same-segment keys (flash
    convention: fully-masked rows emit 0)."""
    qh = np.swapaxes(q, 1, 2).astype(np.float64)
    kh = np.swapaxes(k, 1, 2).astype(np.float64)
    vh = np.swapaxes(v, 1, 2).astype(np.float64)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(q.shape[-1])
    keep = (q_segs[:, None, :, None] == kv_segs[:, None, None, :])
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        keep = keep & np.tril(np.ones((lq, lk), bool), k=lk - lq)
    s = np.where(keep, s, -1e30)
    e = np.exp(s - s.max(-1, keepdims=True))
    denom = e.sum(-1, keepdims=True)
    p = np.where(keep.any(-1, keepdims=True), e / np.maximum(denom, 1e-300), 0.0)
    out = np.einsum("bhqk,bhkd->bhqd", p, vh)
    return np.swapaxes(out, 1, 2).astype(np.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_segment_mask_matches_reference(causal):
    """Padding/packed masks via segment ids stay on the flash kernel
    (interpret mode on CPU) and match the masked softmax reference —
    forward AND gradients (VERDICT r3 item 3)."""
    paddle.seed(1)
    B, L, H, D = 2, 256, 2, 16
    rng = np.random.default_rng(3)
    qn = rng.normal(0, 1, (B, L, H, D)).astype(np.float32)
    kn = rng.normal(0, 1, (B, L, H, D)).astype(np.float32)
    vn = rng.normal(0, 1, (B, L, H, D)).astype(np.float32)
    # batch 0: two packed sequences; batch 1: one sequence + padding tail
    segs = np.zeros((B, L), np.int32)
    segs[0, : L // 2] = 1
    segs[0, L // 2:] = 2
    segs[1, : 3 * L // 4] = 1
    segs[1, 3 * L // 4:] = 0  # padding id (q rows there are don't-care)

    q = paddle.to_tensor(qn); q.stop_gradient = False
    k = paddle.to_tensor(kn); k.stop_gradient = False
    v = paddle.to_tensor(vn); v.stop_gradient = False
    st = paddle.to_tensor(segs)
    out = nn.functional.flash_attention(q, k, v, causal=causal,
                                        q_segment_ids=st, kv_segment_ids=st)
    ref = _ref_attention_seg(qn, kn, vn, causal, segs, segs)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    # gradients: finite + match AD through the masked XLA reference
    out.sum().backward()
    import jax.numpy as jnp
    import jax

    def ref_jax(qa, ka, va):
        from paddle_tpu.ops.flash_attention import _xla_attention
        o = _xla_attention(jnp.swapaxes(qa, 1, 2), jnp.swapaxes(ka, 1, 2),
                           jnp.swapaxes(va, 1, 2), causal,
                           1.0 / np.sqrt(D), jnp.asarray(segs),
                           jnp.asarray(segs))
        return jnp.swapaxes(o, 1, 2).sum()

    gq, gk, gv = jax.grad(ref_jax, argnums=(0, 1, 2))(qn, kn, vn)
    np.testing.assert_allclose(q.grad.numpy(), np.asarray(gq), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(k.grad.numpy(), np.asarray(gk), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(v.grad.numpy(), np.asarray(gv), rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attn_unpadded_packed_sequences(causal):
    """flash_attn_unpadded: packed (total, H, D) + cu_seqlens == looping the
    per-sequence attention (the upstream varlen contract)."""
    paddle.seed(2)
    H, D = 2, 16
    lens = [128, 256, 128]  # 128-aligned total keeps the kernel path
    total = sum(lens)
    rng = np.random.default_rng(4)
    qn = rng.normal(0, 1, (total, H, D)).astype(np.float32)
    kn = rng.normal(0, 1, (total, H, D)).astype(np.float32)
    vn = rng.normal(0, 1, (total, H, D)).astype(np.float32)
    cu = np.cumsum([0] + lens).astype(np.int32)

    out = nn.functional.flash_attn_unpadded(
        paddle.to_tensor(qn), paddle.to_tensor(kn), paddle.to_tensor(vn),
        paddle.to_tensor(cu), paddle.to_tensor(cu), max(lens), max(lens),
        causal=causal)
    got = out.numpy()

    for i in range(len(lens)):
        s, e = cu[i], cu[i + 1]
        ref = _ref_attention(qn[None, s:e], kn[None, s:e], vn[None, s:e],
                             causal)[0]
        np.testing.assert_allclose(got[s:e], ref, rtol=1e-4, atol=1e-5,
                                   err_msg=f"sequence {i}")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.requires_shard_map
def test_ring_attention_matches_serial(causal):
    from paddle_tpu.distributed.fleet.context_parallel import ring_flash_attention
    _sep_mesh(8)
    paddle.seed(1)
    B, L, H, D = 1, 64, 2, 16  # L=64 over 8 devices -> 8 per shard
    q = paddle.randn([B, L, H, D])
    k = paddle.randn([B, L, H, D])
    v = paddle.randn([B, L, H, D])
    out = ring_flash_attention(q, k, v, causal=causal)
    ref = _ref_attention(q.numpy(), k.numpy(), v.numpy(), causal)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_grad_flows():
    from paddle_tpu.distributed.fleet.context_parallel import ring_flash_attention
    _sep_mesh(8)
    paddle.seed(2)
    q = paddle.randn([1, 32, 2, 8])
    q.stop_gradient = False
    k = paddle.randn([1, 32, 2, 8])
    v = paddle.randn([1, 32, 2, 8])
    out = ring_flash_attention(q, k, v, causal=True)
    out.sum().backward()
    assert q.grad is not None
    assert np.isfinite(q.grad.numpy()).all()


def test_ulysses_matches_serial():
    from paddle_tpu.distributed.fleet.context_parallel import ulysses_attention
    _sep_mesh(8)
    paddle.seed(3)
    B, L, H, D = 1, 64, 8, 16  # H=8 divisible by sep=8
    q = paddle.randn([B, L, H, D])
    k = paddle.randn([B, L, H, D])
    v = paddle.randn([B, L, H, D])
    out = ulysses_attention(q, k, v, causal=True)
    ref = _ref_attention(q.numpy(), k.numpy(), v.numpy(), True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_moe_forward_and_grad():
    paddle.seed(4)
    from paddle_tpu.incubate.moe import MoELayer
    d = 16
    experts = [nn.Sequential(nn.Linear(d, 32), nn.ReLU(), nn.Linear(32, d))
               for _ in range(4)]
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "gshard", "top_k": 2})
    x = paddle.randn([2, 8, d])
    x.stop_gradient = False
    out = moe(x)
    assert out.shape == [2, 8, d]
    loss = out.sum() + 0.01 * moe.l_aux
    loss.backward()
    assert x.grad is not None
    # gate weights learn
    assert moe.gate.gate_proj.weight.grad is not None
    # most tokens routed (combine weights not all zero)
    assert float(paddle.abs(out).sum()) > 0


@pytest.mark.slow
def test_moe_switch_gate():
    paddle.seed(5)
    from paddle_tpu.incubate.moe import MoELayer
    d = 8
    experts = [nn.Linear(d, d) for _ in range(2)]
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "switch"})
    out = moe(paddle.randn([4, 4, d]))
    assert out.shape == [4, 4, d]


@pytest.mark.slow
class TestFlashBackwardKernel:
    """The dedicated Pallas dq/dkv backward (recompute-from-lse) must match
    the XLA attention vjp exactly (reference invariant: flash_attn_grad
    kernels vs softmax attention AD)."""

    @pytest.mark.parametrize("lq,lk,causal", [(256, 256, True),
                                              (256, 256, False),
                                              (128, 256, True),
                                              (512, 512, True)])
    def test_bwd_matches_xla(self, lq, lk, causal):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.flash_attention import (_flash_core,
                                                    _xla_attention)
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(2, 3, lq, 64)).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.normal(size=(2, 3, lk, 64)).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.normal(size=(2, 3, lk, 64)).astype(np.float32) * 0.3)
        g = jnp.asarray(rng.normal(size=(2, 3, lq, 64)).astype(np.float32))
        sm = 1.0 / 8.0
        out_r, vjp_r = jax.vjp(
            lambda a, b, c: _xla_attention(a, b, c, causal, sm), q, k, v)
        out, vjp = jax.vjp(
            lambda a, b, c: _flash_core(a, b, c, causal, sm), q, k, v)
        assert float(jnp.abs(out - out_r).max()) < 1e-5
        for got, ref in zip(vjp(g), vjp_r(g)):
            assert float(jnp.abs(got - ref).max()) < 1e-4


class TestFlashTileFitting:
    def test_fit_block_divisors(self):
        from paddle_tpu.ops.flash_attention import _fit_block, _pallas_tileable
        assert _fit_block(1024, 512) == 512
        assert _fit_block(768, 512) == 384   # largest 128-multiple divisor
        assert _fit_block(1280, 512) == 256
        assert _fit_block(256, 512) == 256   # short seq: one full block
        # sub-128 sequences are NOT pallas-tileable: the backward kernels
        # slice lse/delta along the lane dim, which real-TPU Mosaic
        # requires 128-aligned (found on-chip by bench --smoke)
        assert _fit_block(96, 512) is None
        # unaligned lengths stay off the Pallas path (XLA fallback)
        assert _fit_block(1000, 512) is None
        assert _fit_block(1001, 512) is None
        assert _pallas_tileable(768, 768, 64, 512, 512)
        assert not _pallas_tileable(1000, 1000, 64, 512, 512)

    @pytest.mark.slow
    def test_mid_range_length_matches_xla(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        np.random.seed(0)
        q = paddle.to_tensor(np.random.randn(1, 768, 4, 16).astype("float32"),
                             stop_gradient=False)
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        out.sum().backward()
        g_flash = np.asarray(q.grad.numpy()).copy()
        q2 = paddle.to_tensor(q.numpy(), stop_gradient=False)
        paddle.set_flags({"FLAGS_flash_impl": "xla"})
        try:
            out2 = F.scaled_dot_product_attention(q2, q2, q2, is_causal=True)
            out2.sum().backward()
        finally:
            paddle.set_flags({"FLAGS_flash_impl": "pallas"})
        np.testing.assert_allclose(out.numpy(), out2.numpy(), atol=2e-3)
        np.testing.assert_allclose(g_flash, np.asarray(q2.grad.numpy()),
                                   atol=2e-3)


class TestFusedEcMoe:
    def test_expert_choice_forward_backward(self):
        import paddle_tpu.incubate.nn as inn

        paddle.seed(0)
        moe = inn.FusedEcMoe(16, 32, num_experts=4)
        gate_proj = paddle.nn.Linear(16, 4)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            0, 1, (2, 8, 16)).astype(np.float32))
        x.stop_gradient = False
        out = moe(x, gate_proj(x))  # upstream signature: (x, gate logits)
        assert out.shape == [2, 8, 16]
        out.sum().backward()
        assert moe.w0.grad is not None and x.grad is not None
        assert gate_proj.weight.grad is not None  # gate grads flow to caller
        # balanced by construction: every expert processes exactly
        # capacity = T/E tokens, so all expert weights receive gradient
        assert float(np.abs(moe.w1.grad.numpy()).sum(axis=(1, 2)).min()) > 0
        with pytest.raises(ValueError):
            inn.FusedEcMoe(16, 32, 4, bias_attr=False)

    def test_fused_dropout_add(self):
        import paddle_tpu.incubate.nn as inn

        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        da = inn.FusedDropoutAdd(p=0.0)
        np.testing.assert_allclose(da(x, x).numpy(), 2.0)
        da_train = inn.FusedDropoutAdd(p=0.5)
        da_train.train()
        y = da_train(x, x).numpy()
        # residual always survives; dropped positions equal 1.0 exactly
        assert set(np.round(np.unique(y), 4)).issubset({1.0, 3.0})


class TestFlashDropout:
    """Round 5: attention-prob dropout runs IN the Pallas kernels (keep
    mask = stateless hash of absolute coordinates, regenerated by the
    backward) instead of falling back to materialized XLA attention."""

    def _qkv(self, L=256, B=2, H=2, D=16):
        paddle.seed(7)
        return (paddle.randn([B, L, H, D]), paddle.randn([B, L, H, D]),
                paddle.randn([B, L, H, D]))

    @pytest.mark.slow
    def test_dropout_statistical_parity(self):
        """E[dropout attention] == no-dropout attention: average over many
        seeds converges to the clean output (unbiasedness of the
        normalized-prob dropout formulation)."""
        q, k, v = self._qkv()
        clean = nn.functional.flash_attention(q, k, v, causal=True).numpy()
        acc = np.zeros_like(clean, dtype=np.float64)
        n = 24
        for s in range(n):
            out = nn.functional.flash_attention(
                q, k, v, dropout=0.3, causal=True, training=True,
                fixed_seed_offset=paddle.to_tensor([1000 + s], dtype="int32"))
            acc += out.numpy().astype(np.float64)
        mean = acc / n
        # elementwise SEM is large for p=0.3, n=24; compare on aggregate
        err = np.abs(mean - clean).mean() / (np.abs(clean).mean() + 1e-9)
        assert err < 0.15, err

    def test_dropout_deterministic_in_seed(self):
        q, k, v = self._qkv()
        kw = dict(dropout=0.2, causal=True, training=True)
        a = nn.functional.flash_attention(
            q, k, v, fixed_seed_offset=paddle.to_tensor([5], "int32"), **kw)
        b = nn.functional.flash_attention(
            q, k, v, fixed_seed_offset=paddle.to_tensor([5], "int32"), **kw)
        c = nn.functional.flash_attention(
            q, k, v, fixed_seed_offset=paddle.to_tensor([6], "int32"), **kw)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        assert np.abs(a.numpy() - c.numpy()).max() > 0

    def test_dropout_actually_drops(self):
        """Output must differ from the clean path and zero out some
        contributions (not a silent no-op)."""
        q, k, v = self._qkv()
        clean = nn.functional.flash_attention(q, k, v, causal=False).numpy()
        out = nn.functional.flash_attention(
            q, k, v, dropout=0.5, causal=False, training=True,
            fixed_seed_offset=paddle.to_tensor([3], "int32")).numpy()
        assert np.abs(out - clean).max() > 1e-3
        # eval mode: dropout off regardless
        ev = nn.functional.flash_attention(
            q, k, v, dropout=0.5, causal=False, training=False).numpy()
        np.testing.assert_allclose(ev, clean, rtol=1e-5, atol=1e-6)

    def test_dropout_grad_flows_and_matches_fallback(self):
        """Gradients through the kernel dropout path match AD through the
        XLA fallback formulation with the SAME mask — the backward's
        regenerated mask is the forward's."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.flash_attention import (_flash_core_drop,
                                                    _keep_tile)

        rng = np.random.default_rng(3)
        B, H, L, D = 1, 2, 256, 16
        q = jnp.asarray(rng.normal(0, 1, (B, H, L, D)).astype(np.float32))
        k = jnp.asarray(rng.normal(0, 1, (B, H, L, D)).astype(np.float32))
        v = jnp.asarray(rng.normal(0, 1, (B, H, L, D)).astype(np.float32))
        segs = jnp.zeros((B, L), jnp.int32)
        seed = jnp.asarray([11], jnp.int32)
        p_drop, scale = 0.25, 1.0 / np.sqrt(D)

        def kernel_loss(q, k, v):
            out = _flash_core_drop(q, k, v, segs, segs, seed, True, scale,
                                   p_drop)
            return (out * out).sum()

        def ref_loss(q, k, v):
            # same math, dense: softmax then the SAME hash mask
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            mask = jnp.tril(jnp.ones((L, L), bool))
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            # bh index: the kernel grid maps (batch*head) to program_id(0)
            keeps = [
                _keep_tile(seed[0], bh, 0, 0, L, L, 1.0 - p_drop)
                for bh in range(B * H)]
            keep = jnp.stack(keeps).reshape(B, H, L, L)
            pd = jnp.where(keep, p / (1.0 - p_drop), 0.0)
            out = jnp.einsum("bhqk,bhkd->bhqd", pd, v)
            return (out * out).sum()

        lk, gk = jax.value_and_grad(kernel_loss, argnums=(0, 1, 2))(q, k, v)
        lr, gr = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(lk), float(lr), rtol=2e-4)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    def test_unpadded_dropout_stays_streaming(self):
        """flash_attn_unpadded with dropout routes the drop core (not the
        materializing parity path) and stays deterministic in the seed."""
        paddle.seed(1)
        total, H, D = 256, 2, 16
        q = paddle.randn([total, H, D])
        k = paddle.randn([total, H, D])
        v = paddle.randn([total, H, D])
        cu = paddle.to_tensor(np.array([0, 100, 256], np.int32))
        kw = dict(cu_seqlens_q=cu, cu_seqlens_k=cu, max_seqlen_q=156,
                  max_seqlen_k=156, dropout=0.2, causal=True, training=True)
        a = nn.functional.flash_attn_unpadded(
            q, k, v, fixed_seed_offset=paddle.to_tensor([9], "int32"), **kw)
        b = nn.functional.flash_attn_unpadded(
            q, k, v, fixed_seed_offset=paddle.to_tensor([9], "int32"), **kw)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        assert np.isfinite(a.numpy()).all()


class TestSDPADropoutRouting:
    """ISSUE 7 satellite (VERDICT r5 Weak #1): training-time dropout through
    ``scaled_dot_product_attention`` now stays on the flash kernel — the
    stale predicate that re-routed it to stored-probs XLA attention
    (re-materializing (Lq, Lk) probs, OOM at seq 8192) is gone."""

    def _qkv(self, B=2, L=128, H=2, D=16):
        paddle.seed(7)
        return (paddle.randn([B, L, H, D]), paddle.randn([B, L, H, D]),
                paddle.randn([B, L, H, D]))

    def _accel(self, monkeypatch):
        # SDPA keeps the fused XLA path on CPU hosts; flip only the ROUTING
        # predicate so the decision is exercised (the Pallas kernel itself
        # still runs in interpret mode here — same code path, same mask hash)
        import paddle_tpu.ops.nn_ops as nn_ops
        monkeypatch.setattr(nn_ops, "_sdpa_flash_backend_ok", lambda: True)

    def test_training_dropout_routes_to_flash_kernel(self, monkeypatch):
        """SDPA(dropout_p>0, training=True) == flash_attention(dropout=…)
        under the same generator state — only the in-kernel dropout path
        can reproduce the stateless coordinate-hash mask bit-exactly."""
        self._accel(monkeypatch)
        q, k, v = self._qkv()
        paddle.seed(123)
        out = nn.functional.scaled_dot_product_attention(
            q, k, v, dropout_p=0.25, is_causal=True, training=True)
        paddle.seed(123)
        ref = nn.functional.flash_attention(
            q, k, v, dropout=0.25, causal=True, training=True)
        np.testing.assert_array_equal(out.numpy(), ref.numpy())
        # and it actually dropped (not silently returning clean attention)
        clean = nn.functional.flash_attention(q, k, v, causal=True)
        assert np.abs(out.numpy() - clean.numpy()).max() > 1e-3

    def test_eval_mode_dropout_is_inert(self, monkeypatch):
        self._accel(monkeypatch)
        q, k, v = self._qkv()
        out = nn.functional.scaled_dot_product_attention(
            q, k, v, dropout_p=0.25, is_causal=True, training=False)
        ref = nn.functional.flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-6)

    def test_sdpa_dropout_grad_matches_dense_ad(self, monkeypatch):
        """Dense-AD parity THROUGH the public SDPA surface: backward of the
        routed kernel-dropout path equals jax AD through the dense softmax
        formulation with the SAME regenerated keep mask."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.flash_attention import (_dropout_seed,
                                                    _keep_tile)
        self._accel(monkeypatch)
        B, L, H, D = 1, 128, 2, 16
        rng = np.random.default_rng(5)
        qn = rng.normal(0, 1, (B, L, H, D)).astype(np.float32)
        kn = rng.normal(0, 1, (B, L, H, D)).astype(np.float32)
        vn = rng.normal(0, 1, (B, L, H, D)).astype(np.float32)
        p_drop, scale = 0.25, 1.0 / np.sqrt(D)

        # capture the seed SDPA will draw, then rewind the generator
        paddle.seed(77)
        seed = int(np.asarray(_dropout_seed(None)._data)[0])
        paddle.seed(77)

        q = paddle.to_tensor(qn, stop_gradient=False)
        k = paddle.to_tensor(kn, stop_gradient=False)
        v = paddle.to_tensor(vn, stop_gradient=False)
        out = nn.functional.scaled_dot_product_attention(
            q, k, v, dropout_p=p_drop, is_causal=True, training=True)
        (out * out).sum().backward()

        def ref_loss(qh, kh, vh):
            s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
            s = jnp.where(jnp.tril(jnp.ones((L, L), bool)), s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            keep = jnp.stack([
                _keep_tile(jnp.asarray(seed, jnp.int32), bh, 0, 0, L, L,
                           1.0 - p_drop)
                for bh in range(B * H)]).reshape(B, H, L, L)
            pd = jnp.where(keep, p / (1.0 - p_drop), 0.0)
            o = jnp.einsum("bhqk,bhkd->bhqd", pd, vh)
            return (o * o).sum()

        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(
            jnp.asarray(qn.transpose(0, 2, 1, 3)),
            jnp.asarray(kn.transpose(0, 2, 1, 3)),
            jnp.asarray(vn.transpose(0, 2, 1, 3)))
        for got, ref in zip((q.grad, k.grad, v.grad), gr):
            np.testing.assert_allclose(
                got.numpy(), np.asarray(ref).transpose(0, 2, 1, 3),
                rtol=2e-3, atol=2e-4)
