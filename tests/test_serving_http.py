"""The HTTP serving tier (ISSUE 15): front door + router, no chip.

Driven with the toy LM from ``test_serving`` over real loopback sockets.
Covers the tentpole acceptance surface outside the chaos storms (those
live in ``test_serving_http_chaos.py``):

* the exception → status mapping and the ``Retry-After`` derivation
  (EWMA drain interval from the detail the rejection carries);
* deadline/TTFT header semantics end to end (headers become
  ``GenerationRequest`` budgets; expiry answers 504, shed answers 429);
* SSE streaming parity: the streamed tokens are exactly the dense
  reference, terminated by exactly one typed terminal event;
* router placement (pick-2 by queue wait), per-replica breakers,
  at-most-once failover (never after a token was emitted), hedging
  (off by default, withdraw-proof when on);
* shutdown under load: ``stop(drain=...)`` with live HTTP streams ends
  every stream with a typed terminal event — no hung sockets, no
  stranded futures, no leaked pages — and a draining replica leaves the
  rotation BEFORE its drain begins.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (backend pin via conftest)
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.observability import trace
from paddle_tpu.resilience import DeadlineExceeded, faults
from paddle_tpu.resilience.breaker import BreakerOpen
from paddle_tpu.serving.http import retry_after_s, status_for

from test_serving import PROMPTS, dense_reference, make_engine

# the shared ``metrics`` fixture (fresh enabled obs registry) lives in
# tests/conftest.py


def make_router(k=2, max_batch=4, seed=0, hedge_s=0, poll_s=0.02,
                router_kw=None, **eng_kw):
    names = [chr(ord("a") + i) for i in range(k)]
    engines = [(n, make_engine(max_batch=max_batch, name=n, **eng_kw))
               for n in names]
    cfg = serving.RouterConfig(seed=seed, hedge_s=hedge_s, poll_s=poll_s,
                               **(router_kw or {}))
    return serving.Router(engines, cfg), dict(engines)


def post_generate(fd, prompt, *, max_new_tokens=4, stream=False,
                  headers=None, timeout=30.0, raw_body=None):
    """One POST /v1/generate over a real socket; returns the closed-over
    (status, headers, parsed-JSON-or-None, raw bytes)."""
    conn = http.client.HTTPConnection(fd.host, fd.port, timeout=timeout)
    try:
        body = raw_body if raw_body is not None else json.dumps({
            "prompt": np.asarray(prompt).tolist(),
            "max_new_tokens": max_new_tokens, "stream": stream}).encode()
        conn.request("POST", "/v1/generate", body=body,
                     headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        doc = None
        if resp.headers.get("Content-Type", "").startswith(
                "application/json"):
            doc = json.loads(raw)
        return resp.status, dict(resp.headers), doc, raw
    finally:
        conn.close()


def read_sse(raw: bytes):
    """Parse an SSE byte stream: returns (tokens, terminals) where each
    terminal is ("done"|"error", doc). EOF without a terminal yields
    ``terminals == []`` — the disconnect case the chaos suite probes."""
    tokens, terminals = [], []
    event = "message"
    for line in raw.decode("utf-8").splitlines():
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            doc = json.loads(line[len("data: "):])
            if event in ("done", "error"):
                terminals.append((event, doc))
            else:
                tokens.append(doc["token"])
        elif not line:
            event = "message"
    return tokens, terminals


def stream_generate(fd, prompt, *, max_new_tokens=4, headers=None,
                    timeout=30.0):
    status, hdrs, _doc, raw = post_generate(
        fd, prompt, max_new_tokens=max_new_tokens, stream=True,
        headers=headers, timeout=timeout)
    assert status == 200   # stream errors arrive as the terminal event
    return read_sse(raw)


# ---------------------------------------------------------------------------
# the mapping itself (pure units)
# ---------------------------------------------------------------------------

class TestStatusMapping:
    def test_typed_surface(self):
        assert status_for(serving.QueueFull("full")) == 429
        assert status_for(DeadlineExceeded("expired")) == 504
        assert status_for(serving.EngineStopped("draining")) == 503
        assert status_for(serving.DrainTimeout("evicted")) == 503
        assert status_for(serving.NoHealthyReplica("none")) == 503
        assert status_for(BreakerOpen("open")) == 503
        assert status_for(serving.WatchdogTimeout("hung")) == 503
        assert status_for(faults.FaultInjected("boom")) == 503
        assert status_for(ValueError("bad")) == 400
        assert status_for(RuntimeError("bug")) == 500

    def test_shed_on_arrival_is_backpressure_not_expiry(self):
        # the shed rejection carries the EWMA estimate -> 429 (try later);
        # a deadline that actually expired is 504 (the request is dead)
        shed = DeadlineExceeded("shed on arrival")
        shed.estimated_wait_s = 0.75
        shed.depth = 3
        shed.capacity = 8
        assert status_for(shed) == 429
        assert retry_after_s(shed) == pytest.approx(0.25)  # est / depth

    def test_retry_after_derivation(self):
        full = serving.QueueFull("full", depth=8, capacity=8,
                                 estimated_wait_s=2.0)
        assert retry_after_s(full) == pytest.approx(0.25)
        cold = serving.QueueFull("full", depth=8, capacity=8,
                                 estimated_wait_s=0.0)
        assert retry_after_s(cold) == 1.0          # cold EWMA fallback
        assert retry_after_s(DeadlineExceeded("expired")) is None
        assert retry_after_s(ValueError("bad")) is None


# ---------------------------------------------------------------------------
# front door over one engine
# ---------------------------------------------------------------------------

class TestFrontDoor:
    def test_unary_parity_and_metrics(self, metrics):
        eng = make_engine().warmup()
        fd = serving.FrontDoor(eng)
        eng.start()
        try:
            status, _h, doc, _raw = post_generate(fd, PROMPTS[0],
                                                  max_new_tokens=5)
            assert status == 200
            assert doc["tokens"] == dense_reference(PROMPTS[0], 5)
            assert doc["finish_reason"] in ("length", "eos")
            assert doc["ttft_s"] is not None
        finally:
            eng.stop(drain=True, timeout=10)
            fd.close()
        snap = obs.snapshot()
        assert snap["serving.http.requests_total"].get("status=200") == 1

    def test_stream_parity_single_terminal(self, metrics):
        eng = make_engine().warmup()
        fd = serving.FrontDoor(eng)
        eng.start()
        try:
            tokens, terminals = stream_generate(fd, PROMPTS[1],
                                                max_new_tokens=6)
        finally:
            eng.stop(drain=True, timeout=10)
            fd.close()
        ref = dense_reference(PROMPTS[1], 6)
        assert tokens == ref
        assert len(terminals) == 1            # exactly one typed terminal
        kind, doc = terminals[0]
        assert kind == "done" and doc["tokens"] == ref

    def test_bad_request_maps_400(self, metrics):
        eng = make_engine()
        fd = serving.FrontDoor(eng)
        try:
            status, _h, doc, _raw = post_generate(
                fd, PROMPTS[0], raw_body=b"{not json")
            assert status == 400
            status, _h, doc, _raw = post_generate(
                fd, PROMPTS[0], raw_body=b'{"nope": 1}')
            assert status == 400 and doc["error"] == "ValueError"
            status, _h, doc, _raw = post_generate(
                fd, PROMPTS[0], headers={"X-Deadline-S": "banana"})
            assert status == 400
            status, _h, doc, _raw = post_generate(
                fd, PROMPTS[0], headers={"X-Deadline-S": "-1"})
            assert status == 400
            # NaN passes a naive `<= 0` guard and would poison every
            # downstream timeout comparison; inf never expires
            for bad in ("nan", "inf"):
                status, _h, doc, _raw = post_generate(
                    fd, PROMPTS[0], headers={"X-Deadline-S": bad})
                assert status == 400, bad
        finally:
            fd.close()

    def test_queue_full_maps_429_with_retry_after(self, metrics):
        # a paused engine (no step loop) with a 1-deep queue: the second
        # request rejects with the structured QueueFull -> 429
        eng = make_engine(max_queue=1)
        fd = serving.FrontDoor(eng)
        try:
            first = threading.Thread(
                target=post_generate, args=(fd, PROMPTS[0]),
                kwargs={"timeout": 20.0}, daemon=True)
            first.start()
            deadline = time.monotonic() + 5.0
            while eng.queue_depth < 1 and time.monotonic() < deadline:
                time.sleep(0.002)
            status, hdrs, doc, _raw = post_generate(fd, PROMPTS[1])
            assert status == 429
            assert doc["error"] == "QueueFull"
            assert doc["retry_after_s"] > 0
            assert int(hdrs["Retry-After"]) >= 1
        finally:
            eng.run()          # drain the parked request, join the client
            first.join(timeout=10)
            eng.stop(drain=True, timeout=5)
            fd.close()

    def test_draining_maps_503(self, metrics):
        eng = make_engine()
        eng.stop(drain=True, timeout=1)
        fd = serving.FrontDoor(eng)
        try:
            status, _h, doc, _raw = post_generate(fd, PROMPTS[0])
            assert status == 503 and doc["error"] == "EngineStopped"
        finally:
            fd.close()

    def test_deadline_header_expiry_maps_504(self, metrics):
        # one busy slot; the probe request's X-Deadline-S expires in the
        # queue -> the admission-boundary sweep sheds it -> 504
        eng = make_engine(max_batch=1).warmup()
        fd = serving.FrontDoor(eng)
        eng.start()
        try:
            blocker = eng.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=30))
            status, _h, doc, _raw = post_generate(
                fd, PROMPTS[1], max_new_tokens=4,
                headers={"X-Deadline-S": "0.05"}, timeout=30.0)
            assert status == 504
            assert doc["error"] == "DeadlineExceeded"
            assert "retry_after_s" not in doc
            blocker.result(timeout=30)
        finally:
            eng.stop(drain=True, timeout=10)
            fd.close()

    def test_healthz_reports_per_replica_beacons(self, metrics):
        router, engines = make_router(k=2)
        fd = serving.FrontDoor(router)
        router.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                doc = trace.health()
                if "serving.engine.a" in doc["components"] and \
                        "serving.engine.b" in doc["components"]:
                    break
                time.sleep(0.005)
            conn = http.client.HTTPConnection(fd.host, fd.port, timeout=10)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                doc = json.loads(resp.read())
            finally:
                conn.close()
            assert resp.status == 200 and doc["status"] == "ok"
            for name in ("serving.engine.a", "serving.engine.b",
                         "serving.router"):
                comp = doc["components"][name]
                assert comp["ok"] and not comp["stale"]
                assert "age_s" in comp and "ttl_s" in comp
            assert doc["router"]["in_rotation"] == ["a", "b"]
        finally:
            router.stop(drain=True, timeout=10)
            fd.close()


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class TestRouter:
    def test_pick2_spreads_by_queue_depth(self, metrics):
        # paused engines: depth is the tie-breaker, so sequential submits
        # alternate replicas instead of piling onto one
        router, engines = make_router(k=2)
        futs = [router.submit(serving.GenerationRequest(
            PROMPTS[i % len(PROMPTS)], max_new_tokens=3))
            for i in range(4)]
        assert engines["a"].queue_depth == 2
        assert engines["b"].queue_depth == 2
        for eng in engines.values():
            eng.run()
        for i, f in enumerate(futs):
            assert f.result(timeout=10).tokens == \
                dense_reference(PROMPTS[i % len(PROMPTS)], 3)
        router.stop(drain=True, timeout=5)

    def test_hedging_defaults_off(self):
        assert serving.RouterConfig().hedge_s is None
        assert serving.RouterConfig(hedge_s=0).hedge_s is None

    def test_drain_replica_leaves_rotation_before_drain(self, metrics):
        router, engines = make_router(k=2)
        # park work on BOTH replicas (paused engines), then drain 'a':
        # its queued-never-admitted work must fail over to 'b'
        futs = [router.submit(serving.GenerationRequest(
            PROMPTS[i], max_new_tokens=3)) for i in range(4)]
        assert engines["a"].queue_depth == 2
        router.drain_replica("a", timeout=0.0, on_timeout="fail")
        assert router.in_rotation() == ["b"]
        # the out-latch precedes the drain in the decision log
        out_at = router.trace.index(("out", "a"))
        fails = [i for i, t in enumerate(router.trace)
                 if t[0] == "failover"]
        assert fails and all(i > out_at for i in fails)
        # every new submission lands on 'b' only
        futs.append(router.submit(serving.GenerationRequest(
            PROMPTS[4], max_new_tokens=3)))
        assert engines["a"].queue_depth == 0
        engines["b"].run()
        for i, f in enumerate(futs):
            assert f.result(timeout=10).tokens == \
                dense_reference(PROMPTS[i], 3)
        snap = obs.snapshot()
        assert snap.get("serving.router.failovers_total", 0) == 2
        for eng in engines.values():
            assert eng.kv.outstanding_pages == 0
        router.stop(drain=True, timeout=5)

    def test_no_failover_after_token_emitted(self, metrics):
        # at-most-once: an ADMITTED request (it streamed tokens) on a
        # killed replica resolves with the typed DrainTimeout — it is
        # never re-sent even though a healthy replica is free
        router, engines = make_router(k=2, max_batch=1)
        for eng in engines.values():
            eng.warmup()
        got = []
        first_token = threading.Event()

        def stream(rid, tok):
            got.append(tok)
            first_token.set()
            time.sleep(0.005)   # throttle decode: the kill must land
            # while the stream is provably mid-flight

        router.start()
        try:
            fut = router.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=40, stream=stream))
            assert first_token.wait(timeout=20)
            victim = router.trace[0][2]        # ("pick", rid, replica)
            router.drain_replica(victim, timeout=0.0, on_timeout="fail")
            with pytest.raises(serving.DrainTimeout):
                fut.result(timeout=10)
            assert not any(t[0] == "failover" for t in router.trace)
            assert obs.snapshot().get(
                "serving.router.failovers_total", 0) == 0
            # the client saw every token exactly once, then the typed end
            assert got == dense_reference(PROMPTS[0], 40)[:len(got)]
            assert engines[victim].kv.outstanding_pages == 0
        finally:
            router.stop(drain=True, timeout=10)

    def test_hedge_reroutes_queued_request(self, metrics):
        # replica 'a' is busy with a long request; the probe request sits
        # queued (never admitted) past hedge_s -> withdrawn and re-routed
        # to 'b' exactly once, no token ever duplicated
        router, engines = make_router(k=2, max_batch=1,
                                      hedge_s=0.05, poll_s=0.01)
        for eng in engines.values():
            eng.warmup()
        got = []
        router.start()
        try:
            long_fut = router.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=40,
                stream=lambda rid, tok: time.sleep(0.01)))  # hold 'a' busy
            # wait for the long request to hold 'a''s only slot, so the
            # probe ties onto 'a' (depth 0 both) and then sits QUEUED
            deadline = time.monotonic() + 10.0
            while engines["a"].active_requests < 1 and \
                    time.monotonic() < deadline:
                time.sleep(0.002)
            assert engines["a"].active_requests == 1
            probe = serving.GenerationRequest(
                PROMPTS[1], max_new_tokens=4,
                stream=lambda rid, tok: got.append(tok))
            fut = router.submit(probe)
            res = fut.result(timeout=20)
            assert res.tokens == dense_reference(PROMPTS[1], 4)
            assert got == res.tokens            # streamed exactly once
            long_fut.result(timeout=20)
        finally:
            router.stop(drain=True, timeout=10)
        snap = obs.snapshot()
        assert snap.get("serving.router.hedges_total", 0) == 1
        hedges = [t for t in router.trace if t[0] == "hedge"]
        assert hedges == [("hedge", probe.request_id, "a")]
        picks = [t for t in router.trace
                 if t[0] == "pick" and t[1] == probe.request_id]
        assert [p[2] for p in picks] == ["a", "b"]

    def test_breaker_opens_on_forward_faults(self, metrics):
        # an injected transport fault at router.forward opens replica
        # 'a''s breaker (threshold 1); the next request short-circuits
        # past 'a' (breaker_open in the trace, no engine touch) onto 'b'
        router, engines = make_router(
            k=2, router_kw={"breaker_threshold": 1,
                            "breaker_cooldown": 60.0})
        sched = faults.FaultSchedule()
        sched.error("router.forward", on=[1])
        with faults.installed(sched):
            f1 = router.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=3))
            f2 = router.submit(serving.GenerationRequest(
                PROMPTS[1], max_new_tokens=3))
        rep_a = next(r for r in router.replicas if r.name == "a")
        assert rep_a.breaker.state == "open"
        assert any(t[0] == "forward_fault" and t[2] == "a"
                   for t in router.trace)
        assert any(t[0] == "breaker_open" and t[2] == "a"
                   for t in router.trace)
        assert engines["a"].queue_depth == 0       # never touched again
        assert engines["b"].queue_depth == 2
        engines["b"].run()
        assert f1.result(timeout=10).tokens == \
            dense_reference(PROMPTS[0], 3)
        assert f2.result(timeout=10).tokens == \
            dense_reference(PROMPTS[1], 3)
        snap = obs.snapshot()
        assert snap.get("serving.router.retries_total", 0) >= 1
        router.stop(drain=True, timeout=5)

    def test_router_stopped_rejects_typed(self, metrics):
        router, _engines = make_router(k=2)
        router.stop(drain=True, timeout=1)
        with pytest.raises(serving.EngineStopped):
            router.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=2))

    def test_start_after_stop_restores_rotation(self, metrics):
        # stop() latches every replica out; start() is its inverse — a
        # restarted router must not answer 503 forever
        router, engines = make_router(k=2)
        router.stop(drain=True, timeout=1)
        assert router.in_rotation() == []
        router.start()
        try:
            assert router.in_rotation() == ["a", "b"]
            fut = router.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=3))
            assert fut.result(timeout=20).tokens == \
                dense_reference(PROMPTS[0], 3)
        finally:
            router.stop(drain=True, timeout=10)

    def test_expired_budget_is_504_not_failover(self, metrics):
        # a TTFT-only request whose budget died while queued on a killed
        # replica must resolve DeadlineExceeded WITHOUT backpressure
        # detail (504, no Retry-After) — never be re-routed to a healthy
        # replica or answered 503-retry-later
        from paddle_tpu.serving.http import retry_after_s, status_for
        router, engines = make_router(k=2)
        fut = router.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=3, ttft_budget_s=0.02))
        picked = router.trace[0][2]
        time.sleep(0.05)                     # the TTFT budget expires
        router.drain_replica(picked, timeout=0.0, on_timeout="fail")
        with pytest.raises(DeadlineExceeded) as ei:
            fut.result(timeout=10)
        assert status_for(ei.value) == 504
        assert retry_after_s(ei.value) is None
        assert not any(t[0] == "failover" for t in router.trace)
        other = next(n for n in ("a", "b") if n != picked)
        assert engines[other].queue_depth == 0
        router.stop(drain=True, timeout=5)

    def test_malformed_request_returns_half_open_probe(self, metrics):
        # ISSUE 18 (resource-discipline lint): a ValueError out of
        # Engine.submit means the replica ANSWERED — validated and
        # rejected. The breaker's half-open probe must come back on that
        # arm like QueueFull's, or one malformed client request against
        # a recovering replica wedges it half-open forever
        router, _engines = make_router(
            k=1, router_kw={"breaker_threshold": 1,
                            "breaker_cooldown": 0.0})
        br = router._replicas["a"].breaker
        br.before_call(); br.record_failure()
        assert br.state == "open"
        import test_serving as ts
        with pytest.raises(ValueError, match="max_len"):
            router.submit(serving.GenerationRequest(
                np.zeros(ts.M, np.int32), max_new_tokens=1))
        assert br.state == "closed"
        fut = router.submit(serving.GenerationRequest(
            PROMPTS[0], max_new_tokens=3))     # rotation is live again
        router.start()
        try:
            assert fut.result(timeout=20).tokens == \
                dense_reference(PROMPTS[0], 3)
        finally:
            router.stop(drain=True, timeout=10)

    def test_duplicate_beacons_rejected(self):
        # two UNNAMED engines share the process-global "serving.engine"
        # beacon — one wedging would be masked by the other's beats, so
        # construction refuses the ambiguity outright
        with pytest.raises(ValueError, match="beacon"):
            serving.Router([("a", make_engine()), ("b", make_engine())])


# ---------------------------------------------------------------------------
# shutdown under load (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

class TestShutdownUnderLoad:
    def _stream_worker(self, fd, prompt, n_new, out, first_token_evt):
        conn = http.client.HTTPConnection(fd.host, fd.port, timeout=60)
        try:
            conn.request("POST", "/v1/generate", body=json.dumps({
                "prompt": np.asarray(prompt).tolist(),
                "max_new_tokens": n_new, "stream": True}).encode())
            resp = conn.getresponse()
            first = resp.readline()       # first SSE line: stream is live
            first_token_evt.set()
            raw = first + resp.read()     # EOF == the server finished it
            out.append((resp.status, read_sse(raw)))
        finally:
            conn.close()

    @pytest.mark.parametrize("graceful", [True, False])
    def test_drain_ends_every_stream_typed(self, graceful, metrics):
        router, engines = make_router(k=2, max_batch=4)
        for eng in engines.values():
            eng.warmup()
        fd = serving.FrontDoor(router)
        router.start()
        outs = [[] for _ in range(4)]
        evts = [threading.Event() for _ in range(4)]
        threads = [threading.Thread(
            target=self._stream_worker,
            args=(fd, PROMPTS[i], 40, outs[i], evts[i]), daemon=True)
            for i in range(4)]
        # throttle decode (an injected per-slot delay, not an error) so
        # the stop() provably lands while every stream is mid-flight
        sched = faults.FaultSchedule()
        sched.delay("serving.step", seconds=0.005)
        try:
            with faults.installed(sched):
                for t in threads:
                    t.start()
                for e in evts:
                    assert e.wait(timeout=30)  # every stream mid-flight
                # graceful: generous budget, streams finish with `done`;
                # abrupt: zero budget, in-flight streams end with the
                # typed DrainTimeout error event — never a hung socket
                router.stop(drain=True,
                            timeout=(30.0 if graceful else 0.0),
                            on_timeout="fail")
                for t in threads:
                    t.join(timeout=30)
                    assert not t.is_alive(), "stream never terminated"
        finally:
            fd.close()
        statuses = []
        for i, out in enumerate(outs):
            assert out, "client thread died without a response"
            status, (tokens, terminals) = out[0]
            assert status == 200
            assert len(terminals) == 1, "stream must end exactly once"
            kind, doc = terminals[0]
            if kind == "done":
                assert tokens == doc["tokens"]
                assert doc["tokens"] == dense_reference(PROMPTS[i], 40)
                statuses.append(200)
            else:
                assert doc["status"] in (503, 504)
                assert doc["error"] in ("DrainTimeout", "EngineStopped")
                statuses.append(doc["status"])
        if graceful:
            assert statuses == [200, 200, 200, 200]
        else:
            assert 503 in statuses
        for eng in engines.values():
            assert eng.kv.outstanding_pages == 0
            assert eng.active_requests == 0 and eng.queue_depth == 0

    def test_wedged_admission_during_zero_budget_drain_resolves_typed(
            self, metrics):
        # the stranded-future window a loaded host exposed: the loop
        # thread is wedged MID-ADMISSION (popped from the queue, prefill
        # not yet landed — here a delay fault longer than the join grace)
        # when a zero-budget drain sweeps stragglers; the late admission
        # must resolve the Future typed instead of stranding it in a
        # stopped engine
        eng = make_engine(max_batch=1).warmup()
        sched = faults.FaultSchedule()
        sched.delay("serving.admit", on=[1], seconds=1.6)
        with faults.installed(sched):
            eng.start()
            fut = eng.submit(serving.GenerationRequest(
                PROMPTS[0], max_new_tokens=4))
            deadline = time.monotonic() + 5.0
            while eng.queue_depth > 0 and time.monotonic() < deadline:
                time.sleep(0.005)      # popped: the admission is in flight
            eng.stop(drain=True, timeout=0.0, on_timeout="fail")
        with pytest.raises(serving.DrainTimeout):
            fut.result(timeout=10)
        assert eng.kv.outstanding_pages == 0
        assert eng.active_requests == 0 and eng.queue_depth == 0
