"""paddle.fft parity tests vs numpy.fft (the reference's op-test pattern:
NumPy reference implementation + gradient check)."""

import numpy as np
import pytest

import paddle_tpu as paddle


RNG = np.random.default_rng(7)


def _real(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


def _cplx(*shape):
    return (RNG.standard_normal(shape) + 1j * RNG.standard_normal(shape)).astype(np.complex64)


@pytest.mark.parametrize("norm", ["backward", "forward", "ortho"])
def test_fft_ifft_roundtrip(norm):
    x = _cplx(3, 16)
    y = paddle.fft.fft(paddle.to_tensor(x), norm=norm)
    np.testing.assert_allclose(y.numpy(), np.fft.fft(x, norm=norm), rtol=1e-4, atol=1e-4)
    back = paddle.fft.ifft(y, norm=norm)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-4, atol=1e-4)


def test_fft_n_axis():
    x = _cplx(4, 10)
    y = paddle.fft.fft(paddle.to_tensor(x), n=8, axis=0)
    np.testing.assert_allclose(y.numpy(), np.fft.fft(x, n=8, axis=0), rtol=1e-4, atol=1e-4)


def test_rfft_irfft():
    x = _real(5, 12)
    y = paddle.fft.rfft(paddle.to_tensor(x))
    np.testing.assert_allclose(y.numpy(), np.fft.rfft(x), rtol=1e-4, atol=1e-4)
    z = paddle.fft.irfft(y, n=12)
    np.testing.assert_allclose(z.numpy(), x, rtol=1e-4, atol=1e-4)


def test_hfft_ihfft():
    x = _cplx(9)
    np.testing.assert_allclose(paddle.fft.hfft(paddle.to_tensor(x)).numpy(),
                               np.fft.hfft(x), rtol=1e-3, atol=1e-3)
    r = _real(16)
    np.testing.assert_allclose(paddle.fft.ihfft(paddle.to_tensor(r)).numpy(),
                               np.fft.ihfft(r), rtol=1e-4, atol=1e-4)


def test_fft2_and_fftn():
    x = _cplx(2, 8, 8)
    np.testing.assert_allclose(paddle.fft.fft2(paddle.to_tensor(x)).numpy(),
                               np.fft.fft2(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        paddle.fft.fftn(paddle.to_tensor(x), axes=(0, 2)).numpy(),
        np.fft.fftn(x, axes=(0, 2)), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        paddle.fft.ifftn(paddle.to_tensor(x)).numpy(),
        np.fft.ifftn(x), rtol=1e-4, atol=1e-4)


def test_rfft2_irfft2():
    x = _real(3, 8, 10)
    y = paddle.fft.rfft2(paddle.to_tensor(x))
    np.testing.assert_allclose(y.numpy(), np.fft.rfft2(x), rtol=1e-3, atol=1e-3)
    z = paddle.fft.irfft2(y, s=(8, 10))
    np.testing.assert_allclose(z.numpy(), x, rtol=1e-3, atol=1e-3)


def test_hfftn_ihfftn_roundtrip():
    r = _real(4, 16)
    spec = paddle.fft.ihfftn(paddle.to_tensor(r), axes=(-1,))
    back = paddle.fft.hfftn(spec, s=(16,), axes=(-1,))
    np.testing.assert_allclose(back.numpy(), r, rtol=1e-3, atol=1e-3)


def test_fftfreq_shift():
    np.testing.assert_allclose(paddle.fft.fftfreq(9, d=0.5).numpy(),
                               np.fft.fftfreq(9, d=0.5), rtol=1e-6)
    np.testing.assert_allclose(paddle.fft.rfftfreq(9, d=2.0).numpy(),
                               np.fft.rfftfreq(9, d=2.0), rtol=1e-6)
    x = _real(4, 5)
    np.testing.assert_allclose(
        paddle.fft.fftshift(paddle.to_tensor(x)).numpy(), np.fft.fftshift(x))
    np.testing.assert_allclose(
        paddle.fft.ifftshift(paddle.to_tensor(x), axes=1).numpy(),
        np.fft.ifftshift(x, axes=1))


def test_fft_grad():
    # d/dx of sum(|rfft(x)|^2) — check against numeric gradient
    x0 = _real(8)
    x = paddle.to_tensor(x0.copy(), stop_gradient=False)
    y = paddle.fft.rfft(x)
    loss = (y.real() ** 2 + y.imag() ** 2).sum()
    loss.backward()
    g = x.grad.numpy()

    def f(v):
        return float(np.sum(np.abs(np.fft.rfft(v)) ** 2))

    num = np.zeros_like(x0)
    eps = 1e-3
    for i in range(x0.size):
        e = np.zeros_like(x0)
        e[i] = eps
        num[i] = (f(x0 + e) - f(x0 - e)) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=2e-2, atol=2e-2)
