"""Program cost accounting (ISSUE 16): the process-global cost registry.

Covers the contract end to end: capture at all three hook sites
(dispatch-cache entry, captured/to_static program, serving bucket
warmup), per-signature records under one cache entry, retirement on
eviction / cache clear / retrace / program death, the HBM ledger
arithmetic against hand-computed param+pool bytes, the MFU/bandwidth
join on fake timings, no-cost-model degradation (counted, never
raised), the Prometheus series names, the ``/debug/cost`` route, the
flight-dump cost snapshot, and the 503-independent ``/healthz`` hbm
component.

The suite runs with ``PADDLE_TPU_COST=off`` globally (conftest) —
every test here opts in through the ``cost_on`` fixture.
"""

import gc
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import dispatch_cache as dcache
from paddle_tpu.observability import cost as cost_mod


@pytest.fixture()
def cost_on(metrics, monkeypatch):
    """Metrics enabled (via ``metrics``) + the cost hooks installed for
    one test; the suite-wide PADDLE_TPU_COST=off is overridden here."""
    monkeypatch.setenv("PADDLE_TPU_COST", "on")
    cost_mod.install()
    cost_mod.clear()
    cost_mod._HBM_WARN_ONCE[0] = False
    yield cost_mod
    cost_mod.uninstall()
    cost_mod.clear()
    cost_mod._HBM_WARN_ONCE[0] = False


def test_mode_resolution(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_COST", raising=False)
    assert cost_mod.mode() == "on"
    for off in ("off", "0", "false", "no"):
        monkeypatch.setenv("PADDLE_TPU_COST", off)
        assert cost_mod.mode() == "off"


def test_install_noop_when_off(metrics, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_COST", "off")
    cost_mod.uninstall()
    cost_mod.install()
    assert not cost_mod.installed()
    from paddle_tpu.jit import to_static as _dec  # the decorator
    import importlib
    ts_mod = importlib.import_module("paddle_tpu.jit.to_static")
    assert ts_mod is not _dec
    assert ts_mod._cost_hook is None
    assert dcache._cost_hook is None


# ---------------------------------------------------------------------------
# capture sites
# ---------------------------------------------------------------------------

def test_jit_site_capture_and_program_death(cost_on, metrics):
    @paddle.jit.to_static
    def f(x):
        return x * 2.0 + 1.0

    f(paddle.to_tensor(np.ones((4, 4), np.float32)))
    recs = cost_on.records(site="jit")
    assert len(recs) == 1
    r = recs[0]
    assert r["model_source"] == "xla"
    assert r["flops"] and r["flops"] > 0
    assert r["bytes_accessed"] and r["bytes_accessed"] > 0
    # peak = argument+output+temp+generated_code, all present on CPU XLA
    assert r["peak_bytes"] == (r["argument_bytes"] + r["output_bytes"]
                               + r["temp_bytes"]
                               + r["generated_code_bytes"])

    # ONE cache entry respecializes per aval: a second shape through the
    # same entry is a distinct program and lands its own record
    f(paddle.to_tensor(np.ones((8, 4), np.float32)))
    assert len(cost_on.records(site="jit")) == 2
    # same signature again: no re-capture
    f(paddle.to_tensor(np.ones((8, 4), np.float32)))
    assert len(cost_on.records(site="jit")) == 2

    captured = metrics.snapshot()["cost.programs_captured_total"]
    assert captured.get("site=jit,model_source=xla") == 2

    del f
    gc.collect()
    assert cost_on.records(site="jit") == []
    retired = metrics.snapshot()["cost.records_retired_total"]
    assert retired.get("site=jit") == 2


def test_dispatch_site_capture_evict_and_clear(cost_on, metrics):
    prev = (dcache._ENABLED, dcache._MAXSIZE, dcache._WARMUP)
    dcache.configure(enabled=True, maxsize=256, warmup=1)
    dcache.cache_clear()
    try:
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        for _ in range(3):           # past warmup: entry stores + serves
            y = x * 2.0
            z = x + y
        recs = cost_on.records(site="dispatch")
        assert len(recs) == 2
        assert all(r["model_source"] == "xla" and r["flops"] is not None
                   for r in recs)
        ops = {r["program"] for r in recs}
        assert any("mul" in o for o in ops) or any("scale" in o
                                                   for o in ops) or ops

        # shrinking maxsize evicts entries -> their records retire
        dcache.configure(maxsize=1)
        assert len(cost_on.records(site="dispatch")) == 1
        retired = metrics.snapshot()["cost.records_retired_total"]
        assert retired.get("site=dispatch") == 1

        # cache_clear drops every dispatch record
        dcache.cache_clear()
        assert cost_on.records(site="dispatch") == []
    finally:
        dcache.configure(enabled=prev[0], maxsize=prev[1], warmup=prev[2])
        dcache.cache_clear()


def test_serving_bucket_warmup_capture(cost_on):
    from test_serving import make_engine

    eng = make_engine(max_batch=4)
    eng.warmup(prompt_lens=[5])
    buckets = cost_on.decode_bucket_records()
    # /debug/cost lists one record per warmed bucket program
    assert set(buckets) == set(eng.config.buckets) == {1, 4}
    for b, rec in buckets.items():
        assert rec["site"] == "serving.decode" and rec["bucket"] == b
        assert rec["flops"] and rec["bytes_accessed"]
        assert f"[b={b}]" in rec["program"]
    prefill = cost_on.records(site="serving.prefill")
    assert len(prefill) == 1 and "[len=5]" in prefill[0]["program"]

    # engine death retires every bucket's record
    del eng
    gc.collect()
    assert cost_on.records(site="serving.decode") == []
    assert cost_on.records(site="serving.prefill") == []


def test_retire_event_drops_entry_records(cost_on):
    # the dead-state retrace path fires ("retire", sf, key=...) before
    # purging the entry: every per-signature record under it must go
    class SF:
        cost_site = cost_label = _fn = None

    sf = SF()
    key = ("treedef", "static")
    prefix = cost_on._sf_prefix(sf, key)
    for sig in ("aa", "bb"):
        cost_on._store(cost_mod.ProgramCostRecord(
            key=prefix + sig, site="jit", program="p",
            model_source="xla", flops=1.0))
    cost_on._store(cost_mod.ProgramCostRecord(
        key="sf:999:other", site="jit", program="q", model_source="xla"))
    cost_on._on_static_build("retire", sf, key=key)
    left = cost_on.records(site="jit")
    assert [r["key"] for r in left] == ["sf:999:other"]


# ---------------------------------------------------------------------------
# degradation
# ---------------------------------------------------------------------------

def test_lower_failure_degrades_counted(cost_on, metrics):
    def boom():
        raise RuntimeError("no lowering")

    rec = cost_on._capture("k1", "dispatch", "p", boom)
    assert rec.model_source == "none" and rec.flops is None
    rec2 = cost_on._capture("k2", "dispatch", "p2", boom,
                            analytic_flops=123.0)
    assert rec2.model_source == "analytic" and rec2.flops == 123.0
    fails = metrics.snapshot()["cost.analysis_failures_total"]
    assert fails.get("reason=lower_error") == 2
    # both records survive and are listed
    assert {r["key"] for r in cost_on.records()} == {"k1", "k2"}


def test_no_cost_model_degrades_counted(cost_on, metrics):
    class FakeCompiled:
        def cost_analysis(self):
            return None

        def memory_analysis(self):
            raise RuntimeError("backend has no memory stats")

        def as_text(self):
            return "HloModule m\n all-reduce(x)\n all-reduce-start(y)\n"

    class FakeLowered:
        def compile(self):
            return FakeCompiled()

    rec = cost_on._capture("k", "train.step", "step",
                           lambda: FakeLowered())
    assert rec.model_source == "none"
    assert rec.peak_bytes is None
    assert rec.collectives == {"all-reduce": 2}
    fails = metrics.snapshot()["cost.analysis_failures_total"]
    assert fails.get("reason=no_cost_model") == 1
    assert fails.get("reason=memory_analysis") == 1


def test_flops_counter_feeds_analytic_records(cost_on):
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU())
    total = paddle.flops(net, [2, 8])
    recs = cost_on.records(site="analytic")
    assert len(recs) == 1
    assert recs[0]["model_source"] == "analytic"
    assert recs[0]["flops"] == float(total) and total > 0


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------

def test_hbm_ledger_arithmetic(cost_on):
    gc.collect()
    led0 = cost_on.hbm_ledger()
    net = nn.Linear(8, 8)            # 8x8 weight + 8 bias, float32
    led1 = cost_on.hbm_ledger()
    assert led1["param_bytes"] - led0["param_bytes"] == (64 + 8) * 4

    class FakeArr:
        nbytes = 4096

    class FakeKV:
        pool = FakeArr()
        scales = None

    kv = FakeKV()
    cost_on.register_kv_cache(kv)
    led2 = cost_on.hbm_ledger()
    assert led2["kv_pool_bytes"] - led1["kv_pool_bytes"] == 4096

    # a live program's modeled temp rides into the peak
    cost_on._store(cost_mod.ProgramCostRecord(
        key="k", site="train.step", program="step", model_source="xla",
        temp_bytes=1 << 20))
    led3 = cost_on.hbm_ledger()
    assert led3["program_temp_peak_bytes"] == 1 << 20
    assert led3["peak_hbm_bytes"] == (led3["state_bytes_total"]
                                      + led3["kv_pool_bytes"]
                                      + (1 << 20))
    assert led3["headroom_bytes"] == led3["hbm_bytes"] - \
        led3["peak_hbm_bytes"]

    # dropping the cache drops its pool from the ledger (weakref)
    del kv
    gc.collect()
    assert cost_on.hbm_ledger()["kv_pool_bytes"] == \
        led1["kv_pool_bytes"]
    del net


def test_hbm_low_headroom_warns_once(cost_on, monkeypatch, caplog):
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1")
    nn.Linear(4, 4)                  # any resident state overflows 1 byte
    with caplog.at_level("WARNING", "paddle_tpu.observability.cost"):
        cost_on.hbm_ledger()
        assert any("HBM headroom" in r.message for r in caplog.records)
        caplog.clear()
        cost_on.hbm_ledger()         # latched: once per process
        assert not caplog.records


def test_device_model_env_overrides(cost_on, monkeypatch):
    dev = cost_on.device_model()
    assert dev["platform"] in ("cpu", "tpu") and dev["source"] == "default"
    monkeypatch.setenv("PADDLE_TPU_HBM_BYTES", "1000")
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "2e12")
    dev = cost_on.device_model()
    assert dev["hbm_bytes"] == 1000 and dev["peak_flops"] == 2e12
    assert dev["source"] == "env"


# ---------------------------------------------------------------------------
# utilization join
# ---------------------------------------------------------------------------

def test_utilization_join_math(cost_on, metrics, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("PADDLE_TPU_HBM_BW_BYTES", "1e9")
    cost_on._store(cost_mod.ProgramCostRecord(
        key="step", site="train.step", program="step", model_source="xla",
        flops=2e9, bytes_accessed=1e8))
    cost_on._store(cost_mod.ProgramCostRecord(
        key="dec", site="serving.decode", program="decode[b=4]",
        model_source="xla", flops=5e8, bucket=4))
    # fake measured timings: 10ms steps, 5ms TPOT
    metrics.observe("train.step_seconds", 0.01)
    metrics.observe("serving.tpot_seconds", 0.005)
    rows = {r["key"]: r for r in cost_on.utilization()}
    assert rows["step"]["mfu"] == pytest.approx(2e9 / (0.01 * 1e12))
    assert rows["step"]["bandwidth_util"] == pytest.approx(
        1e8 / (0.01 * 1e9))
    assert rows["dec"]["mfu"] == pytest.approx(5e8 / (0.005 * 1e12))
    assert rows["dec"]["bandwidth_util"] is None
    snap = metrics.snapshot()
    assert snap["cost.mfu"]["site=train.step,program=step"] == \
        pytest.approx(0.2)


def test_utilization_empty_without_timings(cost_on):
    cost_on._store(cost_mod.ProgramCostRecord(
        key="step", site="train.step", program="step", model_source="xla",
        flops=2e9))
    assert cost_on.utilization() == []


# ---------------------------------------------------------------------------
# operator surfaces
# ---------------------------------------------------------------------------

def test_prometheus_series_names(cost_on, metrics):
    @paddle.jit.to_static
    def f(x):
        return x + 1.0

    f(paddle.to_tensor(np.ones((2, 2), np.float32)))
    cost_on.hbm_ledger()
    text = metrics.prometheus_text()
    for fam in ("cost_programs", "cost_programs_captured_total",
                "cost_program_flops", "cost_program_bytes",
                "cost_program_peak_bytes", "cost_hbm_bytes"):
        assert fam in text, fam


def test_debug_cost_route(cost_on):
    @paddle.jit.to_static
    def f(x):
        return x + 1.0

    f(paddle.to_tensor(np.ones((2, 2), np.float32)))
    from paddle_tpu.observability.http import start_http_server
    srv = start_http_server(0)
    try:
        doc = json.load(urllib.request.urlopen(
            srv.url + "/debug/cost", timeout=10))
    finally:
        srv.close()
    assert doc["mode"] == "on" and doc["installed"] is True
    assert len(doc["records"]) == 1
    assert doc["records"][0]["site"] == "jit"
    assert doc["hbm"]["hbm_bytes"] > 0
    assert "utilization" in doc and "device" in doc


def test_flight_dump_carries_cost_snapshot(cost_on, tracing, tmp_path):
    cost_on._store(cost_mod.ProgramCostRecord(
        key="k", site="train.step", program="step", model_source="xla",
        flops=1.0))
    p = tracing.flight_recorder().dump("test_cost_abort")
    with open(p) as f:
        doc = json.load(f)
    assert doc["cost"]["records"][0]["key"] == "k"
    assert "hbm" in doc["cost"]


def test_healthz_hbm_component_is_503_independent(cost_on, tracing):
    # beacons are process-global and trace.clear() does not touch them:
    # retire ours or every later /healthz in the suite reads unhealthy
    try:
        tracing.heartbeat("test.engine", ttl_s=60.0)
        doc = tracing.health()
        assert doc["status"] == "ok"
        hbm = doc["components"]["hbm"]
        assert hbm["ok"] is True and hbm["stale"] is False
        assert hbm["headroom_bytes"] == hbm["hbm_bytes"] - \
            hbm["peak_hbm_bytes"]
        # a stale beacon flips the process status; the hbm component
        # never does (low headroom warns, it does not take us out of
        # rotation)
        tracing.heartbeat("stale.engine", ttl_s=0.0)
        doc = tracing.health()
        assert doc["status"] == "unhealthy"
        assert doc["components"]["hbm"]["ok"] is True
    finally:
        tracing.heartbeat_clear("test.engine")
        tracing.heartbeat_clear("stale.engine")
