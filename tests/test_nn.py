"""nn.Layer system + layers: shapes, state_dict, train/eval semantics."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_linear_shapes_and_numerics():
    l = nn.Linear(4, 3)
    x = paddle.randn([5, 4])
    y = l(x)
    assert y.shape == [5, 3]
    expected = x.numpy() @ l.weight.numpy() + l.bias.numpy()
    np.testing.assert_allclose(y.numpy(), expected, rtol=1e-5, atol=1e-5)


def test_conv2d_matches_reference():
    import jax
    conv = nn.Conv2D(3, 8, 3, stride=1, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    y = conv(x)
    assert y.shape == [2, 8, 16, 16]
    # against lax reference
    ref = jax.lax.conv_general_dilated(
        x._data, conv.weight._data, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ref = ref + conv.bias._data.reshape(1, -1, 1, 1)
    np.testing.assert_allclose(y.numpy(), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 5, 5]) * 3 + 1
    bn.train()
    y = bn(x)
    # normalized output ~ zero mean unit var per channel
    yn = y.numpy()
    assert abs(yn.mean()) < 1e-4
    assert abs(yn.std() - 1) < 1e-2
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), 0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [8, 4, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 3, 8])
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1, atol=1e-2)


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    d.train()
    y = d(x)
    zeros = float((y.numpy() == 0).mean())
    assert 0.3 < zeros < 0.7
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding_padding_idx():
    e = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([0, 3, 0, 5]))
    out = e(ids)
    np.testing.assert_allclose(out.numpy()[0], 0)
    np.testing.assert_allclose(out.numpy()[2], 0)
    assert not np.allclose(out.numpy()[1], 0)


def test_state_dict_roundtrip(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.BatchNorm1D(8), nn.Linear(8, 2))
    sd = m.state_dict()
    assert any("weight" in k for k in sd)
    path = str(tmp_path / "model.pdparams")
    paddle.save(sd, path)
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.BatchNorm1D(8), nn.Linear(8, 2))
    loaded = paddle.load(path)
    m2.set_state_dict(loaded)
    for (k1, v1), (k2, v2) in zip(m.state_dict().items(), m2.state_dict().items()):
        assert k1 == k2
        np.testing.assert_allclose(np.asarray(v1._data), np.asarray(v2._data))


def test_named_parameters_and_apply():
    m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
    names = [n for n, _ in m.named_parameters()]
    assert "0.weight" in names and "1.bias" in names
    seen = []
    m.apply(lambda l: seen.append(type(l).__name__))
    assert "Sequential" in seen and seen.count("Linear") == 2


def test_sublayer_replacement_and_hooks():
    m = nn.Sequential(nn.Linear(2, 2))
    calls = []
    h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    m(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    m(paddle.randn([1, 2]))
    assert calls == [1]


def test_mha_forward():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 5, 16])
    y = mha(x, x, x)
    assert y.shape == [2, 5, 16]


@pytest.mark.slow
def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    y = enc(x)
    assert y.shape == [2, 6, 16]
    # clone must be independent params
    w0 = enc.layers[0].linear1.weight.numpy()
    w1 = enc.layers[1].linear1.weight.numpy()
    assert not np.allclose(w0, w1)


def test_losses():
    logits = paddle.randn([8, 5])
    labels = paddle.randint(0, 5, [8])
    ce = nn.CrossEntropyLoss()(logits, labels)
    lp = np.log(np.exp(logits.numpy()) / np.exp(logits.numpy()).sum(-1, keepdims=True))
    expected = -lp[np.arange(8), labels.numpy()].mean()
    np.testing.assert_allclose(ce.numpy(), expected, rtol=1e-5)
    mse = nn.MSELoss()(paddle.ones([3]), paddle.zeros([3]))
    assert float(mse) == 1.0


def test_pooling():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
    np.testing.assert_allclose(
        nn.AdaptiveAvgPool2D(1)(x).numpy()[0, 0, 0, 0],
        x.numpy()[0, 0].mean(), rtol=1e-5)


@pytest.mark.parametrize("ceil", [False, True])
@pytest.mark.parametrize("excl", [True, False])
def test_pool_ceil_mode_and_divisors_match_torch(ceil, excl):
    """ceil_mode produces the reference output shapes AND divisors:
    partial last windows average over real elements (exclusive) or
    input+user-pad elements (include-pad, torch count_include_pad) — the
    ceil extension never counts. Round-3 fix: ceil_mode was silently a
    no-op for every pool."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    for L, k, s, p in [(9, 3, 2, 1), (10, 4, 3, 1), (13, 5, 4, 2)]:
        x = rng.normal(0, 1, (2, 3, L)).astype(np.float32)
        got = F.avg_pool1d(paddle.to_tensor(x), k, s, p, ceil_mode=ceil,
                           exclusive=excl).numpy()
        want = TF.avg_pool1d(torch.tensor(x), k, s, p, ceil_mode=ceil,
                             count_include_pad=not excl).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        gm = F.max_pool1d(paddle.to_tensor(x), k, s, p,
                          ceil_mode=ceil).numpy()
        wm = TF.max_pool1d(torch.tensor(x), k, s, p, ceil_mode=ceil).numpy()
        np.testing.assert_allclose(gm, wm, rtol=1e-5, atol=1e-6)
    for H, k, s, p in [(9, 3, 2, 1), (11, 4, 3, 1)]:
        x = rng.normal(0, 1, (2, 3, H, H)).astype(np.float32)
        got = F.avg_pool2d(paddle.to_tensor(x), k, s, p, ceil_mode=ceil,
                           exclusive=excl).numpy()
        want = TF.avg_pool2d(torch.tensor(x), k, s, p, ceil_mode=ceil,
                             count_include_pad=not excl).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
