"""Fused (multi-tensor) optimizer path + compiled-step state carriage.

Covers the perf-critical contracts found on real TPU hardware:
* fused AdamW numerics == unfused AdamW numerics;
* eager state materialization: the SECOND to_static call must hit the program
  cache (no silent whole-program recompile);
* LR schedulers drive compiled steps through carried state, not a baked float;
* externally loaded weights are folded into masters before the next trace.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _tiny_model():
    paddle.seed(7)
    return nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _run_steps(use_multi_tensor, n=4, grad_clip=None, wd=0.01,
               decay_fn=None):
    model = _tiny_model()
    opt = paddle.optimizer.AdamW(
        learning_rate=0.01, parameters=model.parameters(), weight_decay=wd,
        grad_clip=grad_clip, use_multi_tensor=use_multi_tensor,
        apply_decay_param_fun=decay_fn)
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(4, 8))
                         .astype(np.float32))
    for _ in range(n):
        loss = model(x).pow(2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return [np.asarray(p._data) for p in model.parameters()], float(loss)


def test_fused_adamw_matches_unfused():
    ref, _ = _run_steps(False)
    fused, _ = _run_steps(True)
    for a, b in zip(ref, fused):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_fused_adamw_global_norm_clip_matches():
    clip = paddle.nn.ClipGradByGlobalNorm(0.05)
    ref, _ = _run_steps(False, grad_clip=clip)
    fused, _ = _run_steps(True, grad_clip=clip)
    for a, b in zip(ref, fused):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_fused_adamw_decay_param_fun_matches():
    fn = lambda name: "weight" in (name or "")
    ref, _ = _run_steps(False, decay_fn=fn)
    fused, _ = _run_steps(True, decay_fn=fn)
    for a, b in zip(ref, fused):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_fused_state_dict_roundtrip():
    model = _tiny_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters(),
                                 use_multi_tensor=True)
    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    for _ in range(3):
        loss = model(x).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    state = opt.state_dict()
    assert any(k.endswith("_moment1") for k in state)

    # a fresh optimizer over the SAME model (param names key the state)
    opt2 = paddle.optimizer.AdamW(learning_rate=0.01,
                                  parameters=model.parameters(),
                                  use_multi_tensor=True)
    opt2.set_state_dict(state)
    np.testing.assert_allclose(np.asarray(opt2._fused["m"]._data),
                               np.asarray(opt._fused["m"]._data), rtol=1e-6)
    assert int(opt2._step_t._data) == 3


def test_to_static_second_call_hits_cache():
    """Eager accumulator materialization means one trace per signature."""
    model = _tiny_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())

    @paddle.jit.to_static
    def step(x):
        loss = model(x).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    step(x)
    n_entries = len(step.program_cache)
    step(x)
    assert len(step.program_cache) == n_entries == 1


def test_lr_scheduler_updates_compiled_step():
    """scheduler.step() between compiled calls must change the applied LR
    WITHOUT a retrace (LR rides as carried state)."""
    paddle.seed(0)
    model = nn.Linear(4, 4, bias_attr=False)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.5, step_size=1,
                                          gamma=0.1)
    opt = paddle.optimizer.SGD(learning_rate=sched,
                               parameters=model.parameters())

    @paddle.jit.to_static
    def step(x):
        loss = model(x).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = np.asarray(model.weight._data).copy()
    step(x)
    w1 = np.asarray(model.weight._data).copy()
    d1 = np.abs(w1 - w0).max()

    sched.step()  # lr: 0.5 -> 0.05
    n_entries = len(step.program_cache)
    step(x)
    assert len(step.program_cache) == n_entries, "LR change must not retrace"
    w2 = np.asarray(model.weight._data).copy()
    d2 = np.abs(w2 - w1).max()
    # grad of mean(x@W) wrt W is constant => update magnitude scales with lr
    np.testing.assert_allclose(d2 / d1, 0.1, rtol=1e-3)


def test_master_weights_refresh_after_external_load():
    """Loading a state_dict AFTER amp.decorate must not be clobbered by stale
    fp32 masters on the next compiled step."""
    paddle.seed(0)
    model = nn.Linear(4, 4, bias_attr=False)
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=model.parameters())
    model, opt = paddle.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    loaded = np.full((4, 4), 3.0, np.float32)
    model.set_state_dict({"weight": paddle.to_tensor(loaded)})

    @paddle.jit.to_static
    def step(x):
        loss = model(x).mean()
        loss.backward()
        opt.step()  # lr=0 => params must stay exactly as loaded
        opt.clear_grad()
        return loss

    step(paddle.to_tensor(np.ones((2, 4), np.float32)))
    np.testing.assert_allclose(
        np.asarray(model.weight._data.astype("float32")), loaded)


def test_fused_master_refresh_after_external_load():
    paddle.seed(0)
    model = nn.Linear(4, 4, bias_attr=False)
    opt = paddle.optimizer.AdamW(learning_rate=0.0,
                                 parameters=model.parameters(),
                                 use_multi_tensor=True, weight_decay=0.0)
    loaded = np.full((4, 4), 2.0, np.float32)
    model.set_state_dict({"weight": paddle.to_tensor(loaded)})

    @paddle.jit.to_static
    def step(x):
        loss = model(x).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step(paddle.to_tensor(np.ones((2, 4), np.float32)))
    np.testing.assert_allclose(np.asarray(model.weight._data), loaded)


def test_fused_step_with_missing_grad_matches_unfused():
    """A param with no grad one step (unused branch) must keep its m/v/master
    untouched — handled by the segment mask, never by a path fallback."""
    def run(fused):
        paddle.seed(11)
        a = nn.Linear(4, 4, bias_attr=False)
        b = nn.Linear(4, 4, bias_attr=False)
        opt = paddle.optimizer.AdamW(
            learning_rate=0.05, weight_decay=0.01,
            parameters=list(a.parameters()) + list(b.parameters()),
            use_multi_tensor=fused)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        for i in range(4):
            # layer b participates only on even steps
            y = a(x) + (b(x) if i % 2 == 0 else 0.0)
            y.mean().backward()
            opt.step()
            opt.clear_grad()
        return (np.asarray(a.weight._data), np.asarray(b.weight._data))

    ra, rb = run(False)
    fa, fb = run(True)
    np.testing.assert_allclose(ra, fa, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(rb, fb, rtol=2e-5, atol=2e-6)


def test_masters_survive_optimizer_state_restore():
    """opt.set_state_dict's loaded fp32 masters must NOT be overwritten by the
    pre-step refresh after a model weight load (version bookkeeping)."""
    paddle.seed(3)
    model = nn.Linear(4, 4, bias_attr=False)
    opt = paddle.optimizer.AdamW(learning_rate=0.0, weight_decay=0.0,
                                 parameters=model.parameters())
    model, opt = paddle.amp.decorate(model, opt, level="O2", dtype="bfloat16")
    # fabricate a checkpoint with masters holding fp32 detail a bf16 param
    # cannot represent
    fine = np.full((4, 4), 1.0 + 2**-12, np.float32)
    model.set_state_dict({"weight": paddle.to_tensor(
        fine.astype(np.float32))})  # param stores bf16(1.0)
    opt.set_state_dict({"step": 1,
                        "master_weights": {model.weight.name:
                                           paddle.to_tensor(fine)}})
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = model(x).mean()
    loss.backward()
    opt.step()  # lr=0: must be a no-op on the master
    opt.clear_grad()
    m = opt._master_weights[id(model.weight)]
    np.testing.assert_array_equal(np.asarray(m._data), fine)
