"""The driver's multichip dry-run must survive a TPU-latched environment.

Round-1 regression: ``dryrun_multichip`` relied on XLA_FLAGS alone, so when
the driver called it in a process whose default jax platform was the real
TPU plugin, model init allocated on the chip and died (libtpu mismatch —
MULTICHIP_r01.json). The fix pins the platform programmatically inside
``dryrun_multichip`` itself. These tests run the entry module in a fresh
subprocess WITHOUT scrubbing the TPU env, exactly like the driver does.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, env_overrides=None, timeout=600):
    env = dict(os.environ)
    # deliberately do NOT strip TPU-related vars; only drop the CPU pins the
    # test conftest added, restoring the hostile driver-like environment
    if env.get("JAX_PLATFORMS") == "cpu":
        del env["JAX_PLATFORMS"]
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split() if "host_platform_device_count" not in f)
    if env_overrides:
        env.update(env_overrides)
    return subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_dryrun_multichip_survives_unscrubbed_env():
    r = _run("import __graft_entry__ as g; g.dryrun_multichip(8)")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "dryrun_multichip OK" in r.stdout


@pytest.mark.slow
def test_dryrun_multichip_after_jax_import():
    # driver may import jax (and even list devices) before calling us
    r = _run(
        "import jax\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "dryrun_multichip OK" in r.stdout


@pytest.mark.slow
def test_dryrun_multichip_after_backend_init():
    # worst case: the default (possibly TPU) backend is already initialized
    # when dryrun_multichip is called — it must re-pin to an 8-device CPU mesh
    r = _run(
        "import jax\n"
        "jax.devices()\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "dryrun_multichip OK" in r.stdout
