"""Automatic tensor-parallel planner (VERDICT r2 missing #7 — upstream
auto_parallel planners; ours derives the megatron col/row plan from model
structure).

Guarantees: the derived plan matches the canonical assignment, the
parallelized model's outputs equal the serial model's, the sharded storage
is physically 1/N per device, and the compiled forward carries exactly ONE
all-reduce per block (the row-projection reduction — a wrong plan shows up
as extra collectives)."""

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_parallel.parallelize import (
    ColWiseParallel, RowWiseParallel, parallelize, plan_parallelize)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 4,
                                reason="needs the multi-device CPU mesh")

D = 32


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.q_proj = nn.Linear(D, D)
        self.k_proj = nn.Linear(D, D)
        self.v_proj = nn.Linear(D, D)
        self.o_proj = nn.Linear(D, D)
        self.fc1 = nn.Linear(D, 4 * D)
        self.fc2 = nn.Linear(4 * D, D)

    def forward(self, x):
        a = self.o_proj(paddle.tanh(self.q_proj(x)) *
                        paddle.tanh(self.k_proj(x)) + self.v_proj(x))
        return a + self.fc2(paddle.nn.functional.gelu(self.fc1(a)))


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.b0 = Block()
        self.b1 = Block()
        self.head = nn.Linear(D, 10)

    def forward(self, x):
        return self.head(self.b1(self.b0(x)))


def _mesh():
    return dist.ProcessMesh(np.arange(4), dim_names=["mp"])


def test_planner_assigns_megatron_pairs():
    paddle.seed(0)
    plan = plan_parallelize(Net(), _mesh())
    for b in ("b0", "b1"):
        for col in ("q_proj", "k_proj", "v_proj", "fc1"):
            assert isinstance(plan[f"{b}.{col}"], ColWiseParallel)
        for row in ("o_proj", "fc2"):
            assert isinstance(plan[f"{b}.{row}"], RowWiseParallel)
    # the lone head stays replicated (sharding it buys only comms)
    assert "head" not in plan


def test_planner_structural_fallback_without_name_hints():
    class Anon(nn.Layer):
        def __init__(self):
            super().__init__()
            self.first = nn.Linear(D, 2 * D)
            self.second = nn.Linear(2 * D, 2 * D)
            self.last = nn.Linear(2 * D, D)

        def forward(self, x):
            return self.last(paddle.tanh(self.second(paddle.tanh(
                self.first(x)))))

    # adjacent pairing: (first, second) form the megatron pair; the odd
    # leftover stays replicated — col-sharding two linears in a row would
    # force an extra mid-block collective
    plan = plan_parallelize(Anon(), _mesh())
    assert isinstance(plan["first"], ColWiseParallel)
    assert isinstance(plan["second"], RowWiseParallel)
    assert "last" not in plan


def test_planner_in_out_proj_naming():
    class MHAish(nn.Layer):
        def __init__(self):
            super().__init__()
            self.in_proj = nn.Linear(D, 3 * D)
            self.out_proj = nn.Linear(3 * D, D)

        def forward(self, x):
            return self.out_proj(paddle.tanh(self.in_proj(x)))

    plan = plan_parallelize(MHAish(), _mesh())
    assert isinstance(plan["in_proj"], ColWiseParallel)
    assert isinstance(plan["out_proj"], RowWiseParallel)


def test_planner_skips_indivisible_layers():
    class Odd(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(D, 30)  # 30 % 4 != 0
            self.b = nn.Linear(30, D)

        def forward(self, x):
            return self.b(paddle.tanh(self.a(x)))

    plan = plan_parallelize(Odd(), _mesh())
    assert plan == {}  # half a pair would add comms for nothing


def test_auto_parallelize_output_parity_and_layout():
    rng = np.random.default_rng(3)
    x_np = rng.normal(0, 1, (8, D)).astype(np.float32)

    paddle.seed(42)
    serial = Net()
    want = serial(paddle.to_tensor(x_np)).numpy()

    paddle.seed(42)
    mesh = _mesh()
    model = parallelize(Net(), mesh=mesh,
                        config={"mp_config": {"parallelize_plan": "auto"}})
    got = model(paddle.to_tensor(x_np)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    # physical layout: col weights hold 1/4 columns per device
    w = model.b0.q_proj.weight._data
    shapes = {s.data.shape for s in w.addressable_shards}
    assert shapes == {(D, D // 4)}
    wr = model.b0.o_proj.weight._data
    assert {s.data.shape for s in wr.addressable_shards} == {(D // 4, D)}

    # compiled propagation through the framework's own whole-step capture
    # (state rides as jit inputs WITH its shardings): one all-reduce per
    # row projection (2 blocks + maybe a head boundary) and no weight
    # all-gathers — a bad plan shows up as extra collectives
    paddle.set_flags({"FLAGS_to_static_capture_lowered": True})
    try:
        step = paddle.jit.to_static(lambda t: model(t))
        step(paddle.to_tensor(x_np))
        txt = step.compiled_text()
    finally:
        paddle.set_flags({"FLAGS_to_static_capture_lowered": False})
    import re
    n_ar = len(re.findall(r"= \S+ all-reduce\(", txt))
    assert n_ar == 4, f"expected one all-reduce per row projection, " \
                      f"got {n_ar}"  # o_proj + fc2, times 2 blocks
    assert "all-gather" not in txt, "plan must not force weight all-gathers"
