"""Multiprocess DataLoader workers (upstream: python/paddle/io/dataloader/
worker.py): spawned processes, order preservation, worker_init_fn,
persistent_workers, iterable sharding via get_worker_info."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset, IterableDataset, get_worker_info


class MapDS(Dataset):
    def __len__(self):
        return 23

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i % 2)


class ShardedIterDS(IterableDataset):
    def __iter__(self):
        wi = get_worker_info()
        lo = wi.id if wi else 0
        step = wi.num_workers if wi else 1
        for i in range(lo, 20, step):
            yield np.float32(i)


class FailingDS(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.float32(i)


def _init_fn(worker_id):
    import os
    os.environ["_PDTPU_TEST_WORKER"] = str(worker_id)


@pytest.mark.slow
def test_map_style_ordered_across_workers():
    dl = DataLoader(MapDS(), batch_size=4, num_workers=2,
                    worker_init_fn=_init_fn)
    batches = list(dl)
    assert len(batches) == 6
    xs = np.concatenate([np.asarray(b[0].numpy()) for b in batches])
    assert xs.shape == (23, 3)
    # order must match the sampler exactly, despite 2 async workers
    np.testing.assert_array_equal(xs[:, 0], np.arange(23, dtype=np.float32))
    assert str(batches[0][1].dtype) in ("int32", "int64")


@pytest.mark.slow
def test_persistent_workers_two_epochs():
    dl = DataLoader(MapDS(), batch_size=4, num_workers=2,
                    persistent_workers=True)
    e1 = [np.asarray(b[0].numpy()) for b in dl]
    e2 = [np.asarray(b[0].numpy()) for b in dl]
    assert dl._pool is not None  # pool survived between epochs
    np.testing.assert_array_equal(np.concatenate(e1), np.concatenate(e2))
    dl._pool.shutdown()


@pytest.mark.slow
def test_iterable_dataset_sharded_by_worker_info():
    dl = DataLoader(ShardedIterDS(), batch_size=2, num_workers=2)
    vals = sorted(float(v) for b in dl
                  for v in np.asarray(b.numpy()).ravel())
    assert vals == [float(i) for i in range(20)]


@pytest.mark.slow
def test_worker_exception_propagates():
    dl = DataLoader(FailingDS(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)


class FailingIterDS(IterableDataset):
    def __iter__(self):
        yield np.float32(1)
        raise ValueError("iter boom")


@pytest.mark.slow
def test_iterable_worker_exception_propagates():
    dl = DataLoader(FailingIterDS(), batch_size=1, num_workers=2)
    with pytest.raises(RuntimeError, match="iter boom"):
        list(dl)


@pytest.mark.slow
def test_persistent_pool_survives_early_break():
    """Breaking out mid-epoch must not leak stale batches into the next
    epoch (epoch-tagged result filtering)."""
    dl = DataLoader(MapDS(), batch_size=4, num_workers=2,
                    persistent_workers=True, prefetch_factor=4)
    it = iter(dl)
    next(it)  # take one batch, abandon the rest in flight
    it.close()
    xs = np.concatenate([np.asarray(b[0].numpy()) for b in dl])
    np.testing.assert_array_equal(xs[:, 0], np.arange(23, dtype=np.float32))
    dl._pool.shutdown()
