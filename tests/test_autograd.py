"""Autograd tape semantics (parity with eager engine behaviors in
paddle/fluid/eager/: accumulation, hooks, no_grad, retain_graph, paddle.grad)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_backward_accumulates():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    g1 = x.grad.numpy().copy()
    y2 = (x * 3).sum()
    y2.backward()
    np.testing.assert_allclose(x.grad.numpy(), g1 + 3.0)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0])  # stop_gradient default True
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    d = (x * 2).detach()
    assert d.stop_gradient
    z = (x + d).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient
    y2 = x * 5
    assert not y2.stop_gradient


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])
    with pytest.raises(RuntimeError):
        y.backward()  # graph released now


def test_non_scalar_backward_needs_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y2 = x * 2
    y2.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0])


def test_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = paddle.to_tensor([4.0], stop_gradient=False)
    z = x * x * y
    gx, gy = paddle.grad(z, [x, y])
    np.testing.assert_allclose(gx.numpy(), [24.0])
    np.testing.assert_allclose(gy.numpy(), [9.0])
    assert x.grad is None and y.grad is None  # .grad slots untouched


def test_tensor_hook():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    h = x.register_hook(lambda g: g * 10)
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])
    h.remove()
    x.clear_grad()
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    loss = (a * 1 + b * 2 + c * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 2, 3], [1, 2, 3]])


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_pylayer_saved_tensor_is_a_method():
    # upstream spells it ctx.saved_tensor() — a CALL (py_layer.py); it was
    # briefly a property here, which broke reference PyLayer code
    seen = {}

    class Square(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor()
            seen["consistent"] = ctx.saved_tensor() == ctx.saved_tensors()
            return g * 2 * x

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Square.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])
    assert seen["consistent"]
    assert callable(paddle.autograd.PyLayerContext.saved_tensor)


def test_setitem_grad_flow():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2
    y[1] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 0.0, 2.0])


def test_getitem_grad_flow():
    x = paddle.to_tensor(np.eye(3, dtype=np.float32), stop_gradient=False)
    y = x[0:2, 1]
    y.sum().backward()
    expected = np.zeros((3, 3), np.float32)
    expected[0, 1] = expected[1, 1] = 1
    np.testing.assert_allclose(x.grad.numpy(), expected)


class TestHigherOrderGrad:
    """create_graph=True: backward recorded on the tape (upstream double-grad
    nodes in paddle/fluid/eager/)."""

    def test_double_grad_cubic(self):
        x = paddle.to_tensor(np.array([2.0], "float32"), stop_gradient=False)
        (g,) = paddle.grad((x * x * x).sum(), x, create_graph=True)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-6)
        (g2,) = paddle.grad(g.sum(), x)
        np.testing.assert_allclose(g2.numpy(), [12.0], rtol=1e-6)

    def test_triple_grad_tanh(self):
        x = paddle.to_tensor(np.array([0.5], "float32"), stop_gradient=False)
        (g,) = paddle.grad(paddle.tanh(x).sum(), x, create_graph=True)
        (g2,) = paddle.grad(g.sum(), x, create_graph=True)
        (g3,) = paddle.grad(g2.sum(), x)
        t = np.tanh(0.5)
        np.testing.assert_allclose(g.numpy(), [1 - t * t], rtol=1e-5)
        np.testing.assert_allclose(g2.numpy(), [-2 * t * (1 - t * t)],
                                   rtol=1e-5)
        np.testing.assert_allclose(g3.numpy(), [(6 * t * t - 2) * (1 - t * t)],
                                   rtol=1e-4)

    def test_double_grad_matmul_chain(self):
        a = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3),
                             stop_gradient=False)
        b = paddle.to_tensor(np.ones((3, 2), "float32"), stop_gradient=False)
        y = paddle.matmul(a, b)
        loss = (y * y).sum()
        (ga,) = paddle.grad(loss, a, create_graph=True)
        # ga = 2 (a b) b^T with b = ones(3,2):
        # ga.sum() = 12 * sum(a)  =>  d(ga.sum())/da = 12 everywhere
        (gga,) = paddle.grad(ga.sum(), a)
        np.testing.assert_allclose(gga.numpy(), np.full((2, 3), 12.0),
                                   rtol=1e-5)

    def test_wgan_gp_penalty_backward(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.random((4, 3), dtype=np.float32),
                             stop_gradient=False)
        w = paddle.to_tensor(rng.random((3, 3), dtype=np.float32),
                             stop_gradient=False)
        y = paddle.matmul(x, w).sum()
        (gx,) = paddle.grad(y, x, create_graph=True)
        penalty = ((gx * gx).sum() - 1.0) ** 2
        penalty.backward()
        assert w.grad is not None
        # d(penalty)/dw: gx = 1 @ w^T rows -> analytic via numpy
        gx_np = np.tile(w.numpy().sum(axis=1), (4, 1))
        coef = 2.0 * ((gx_np ** 2).sum() - 1.0)
        grad_w = np.zeros((3, 3), np.float32)
        for i in range(3):  # d gx[:,i] / d w[i,j] = 1 for all j
            grad_w[i, :] = coef * 2.0 * gx_np[:, i].sum() / 4 * 1.0
        # direction check only (scale folded): compare against autodiff of
        # numpy-equivalent computation via finite differences
        eps = 1e-3
        w_np = w.numpy().copy()
        def pen(wv):
            gxv = np.tile(wv.sum(axis=1), (4, 1))
            return ((gxv * gxv).sum() - 1.0) ** 2
        fd = np.zeros_like(w_np)
        for i in range(3):
            for j in range(3):
                wp = w_np.copy(); wp[i, j] += eps
                wm = w_np.copy(); wm[i, j] -= eps
                fd[i, j] = (pen(wp) - pen(wm)) / (2 * eps)
        np.testing.assert_allclose(w.grad.numpy(), fd, rtol=2e-2, atol=1e-2)

    def test_grad_reentrant_from_hook(self):
        """paddle.grad called from inside a backward hook must not corrupt
        the outer leaf filtering (round-1: module-global _leaf_filter)."""
        x = paddle.to_tensor(np.array([3.0], "float32"), stop_gradient=False)
        side = {}

        def hook(g):
            a = paddle.to_tensor(np.array([2.0], "float32"),
                                 stop_gradient=False)
            (ga,) = paddle.grad((a * a).sum(), a)
            side["inner"] = ga.numpy()
            return g

        y = x * x
        y.register_hook(hook)
        loss = y.sum()
        loss.backward()
        np.testing.assert_allclose(side["inner"], [4.0], rtol=1e-6)
        assert x.grad is not None  # outer accumulation unaffected
        np.testing.assert_allclose(x.grad.numpy(), [6.0], rtol=1e-6)

    def test_create_graph_leaf_grad_is_connected(self):
        x = paddle.to_tensor(np.array([1.5], "float32"), stop_gradient=False)
        (g,) = paddle.grad((x ** 4).sum(), x, create_graph=True)
        assert not g.stop_gradient
        assert g._grad_node is not None
