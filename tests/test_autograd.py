"""Autograd tape semantics (parity with eager engine behaviors in
paddle/fluid/eager/: accumulation, hooks, no_grad, retain_graph, paddle.grad)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_backward_accumulates():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    g1 = x.grad.numpy().copy()
    y2 = (x * 3).sum()
    y2.backward()
    np.testing.assert_allclose(x.grad.numpy(), g1 + 3.0)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0])  # stop_gradient default True
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    d = (x * 2).detach()
    assert d.stop_gradient
    z = (x + d).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient
    y2 = x * 5
    assert not y2.stop_gradient


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])
    with pytest.raises(RuntimeError):
        y.backward()  # graph released now


def test_non_scalar_backward_needs_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y2 = x * 2
    y2.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0])


def test_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = paddle.to_tensor([4.0], stop_gradient=False)
    z = x * x * y
    gx, gy = paddle.grad(z, [x, y])
    np.testing.assert_allclose(gx.numpy(), [24.0])
    np.testing.assert_allclose(gy.numpy(), [9.0])
    assert x.grad is None and y.grad is None  # .grad slots untouched


def test_tensor_hook():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    h = x.register_hook(lambda g: g * 10)
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])
    h.remove()
    x.clear_grad()
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    loss = (a * 1 + b * 2 + c * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 2, 3], [1, 2, 3]])


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_setitem_grad_flow():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2
    y[1] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 0.0, 2.0])


def test_getitem_grad_flow():
    x = paddle.to_tensor(np.eye(3, dtype=np.float32), stop_gradient=False)
    y = x[0:2, 1]
    y.sum().backward()
    expected = np.zeros((3, 3), np.float32)
    expected[0, 1] = expected[1, 1] = 1
    np.testing.assert_allclose(x.grad.numpy(), expected)
