"""Tests for SSD/YOLO/RPN detection ops (prior_box, yolo_box, yolo_loss,
matrix_nms, generate_proposals, distribute_fpn_proposals) and the new
ResNeXt/Inception model variants."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.vision as vision
from paddle_tpu.vision import ops as vops


class TestPriorBox:
    def test_shapes_and_ranges(self):
        feat = paddle.zeros([1, 256, 4, 4])
        img = paddle.zeros([1, 3, 32, 32])
        boxes, vars_ = vops.prior_box(feat, img, min_sizes=[8.0],
                                      max_sizes=[16.0], aspect_ratios=[2.0],
                                      flip=True, clip=True)
        # priors: ar 1 + ar 2 + ar 1/2 + sqrt(min*max) = 4
        assert boxes.shape == [4, 4, 4, 4]
        assert vars_.shape == boxes.shape
        arr = np.asarray(boxes.numpy())
        assert arr.min() >= 0.0 and arr.max() <= 1.0
        np.testing.assert_allclose(np.asarray(vars_.numpy())[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2])

    def test_center_alignment(self):
        feat = paddle.zeros([1, 1, 2, 2])
        img = paddle.zeros([1, 3, 16, 16])
        boxes, _ = vops.prior_box(feat, img, min_sizes=[4.0])
        arr = np.asarray(boxes.numpy())
        # first cell center should be at offset 0.5 * step = 4 px -> 0.25
        cx = (arr[0, 0, 0, 0] + arr[0, 0, 0, 2]) / 2
        assert abs(cx - 0.25) < 1e-6


class TestYoloBox:
    def test_decode_shapes_and_threshold(self):
        cn, na = 3, 2
        x = paddle.to_tensor(
            np.random.randn(2, na * (5 + cn), 4, 4).astype("float32"))
        imgsz = paddle.to_tensor(np.array([[32, 32], [32, 32]], "int32"))
        b, s = vops.yolo_box(x, imgsz, anchors=[10, 14, 23, 27],
                             class_num=cn, conf_thresh=0.5,
                             downsample_ratio=8)
        assert b.shape == [2, na * 16, 4]
        assert s.shape == [2, na * 16, cn]
        arr = np.asarray(s.numpy())
        assert ((arr == 0) | (arr > 0.5 * 0.0)).all()  # zeros below thresh
        barr = np.asarray(b.numpy())
        assert barr.min() >= 0 and barr.max() <= 31  # clipped to image

    def test_known_center_box(self):
        # zero logits: sigmoid=0.5 -> center at cell centers, w=h=anchor
        cn, na = 1, 1
        x = paddle.zeros([1, na * (5 + cn), 2, 2])
        imgsz = paddle.to_tensor(np.array([[16, 16]], "int32"))
        b, s = vops.yolo_box(x, imgsz, anchors=[8, 8], class_num=cn,
                             conf_thresh=0.0, downsample_ratio=8,
                             clip_bbox=False)
        arr = np.asarray(b.numpy())[0, 0]
        # cell (0,0): center (0.5/2, 0.5/2)*16 = 4, anchor 8/16*16 = 8 wide
        np.testing.assert_allclose(arr, [0.0, 0.0, 8.0, 8.0], atol=1e-4)


class TestYoloLoss:
    def test_finite_and_differentiable(self):
        cn, na = 3, 2
        gtb = paddle.to_tensor(
            np.array([[[0.5, 0.5, 0.3, 0.4], [0, 0, 0, 0]]] * 2, "float32"))
        gtl = paddle.to_tensor(np.zeros((2, 2), "int32"))
        x = paddle.to_tensor(
            np.random.randn(2, na * (5 + cn), 4, 4).astype("float32"),
            stop_gradient=False)
        loss = vops.yolo_loss(x, gtb, gtl, anchors=[10, 14, 23, 27],
                              anchor_mask=[0, 1], class_num=cn,
                              ignore_thresh=0.7, downsample_ratio=8)
        assert loss.shape == [2]
        loss.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()

    def test_loss_decreases_with_training(self):
        cn, na = 2, 1
        gtb = paddle.to_tensor(np.array([[[0.5, 0.5, 0.4, 0.4]]], "float32"))
        gtl = paddle.to_tensor(np.zeros((1, 1), "int32"))
        from paddle_tpu.core.tensor import Parameter
        x = Parameter(np.random.randn(1, na * (5 + cn), 4, 4)
                      .astype("float32") * 0.1, name="yolo_feat")
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=[x])
        first = last = None
        for i in range(30):
            loss = vops.yolo_loss(x, gtb, gtl, anchors=[13, 13],
                                  anchor_mask=[0], class_num=cn,
                                  ignore_thresh=0.7,
                                  downsample_ratio=8).sum()
            if first is None:
                first = float(loss)
            last = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert last < first * 0.5, (first, last)


class TestMatrixNMS:
    def test_decay_values(self):
        bx = paddle.to_tensor(
            np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]]],
                     "float32"))
        sc = paddle.to_tensor(np.array([[[0.9, 0.85, 0.7]]], "float32"))
        out, idx, nums = vops.matrix_nms(bx, sc, score_threshold=0.1,
                                         post_threshold=0.1, nms_top_k=3,
                                         keep_top_k=3, return_index=True,
                                         background_label=-1)
        arr = np.asarray(out.numpy())[0]
        assert int(nums.numpy()[0]) == 3
        np.testing.assert_allclose(arr[0, 1], 0.9, atol=1e-6)
        # the overlapping box (iou ~0.68) decays by (1 - iou)
        assert 0.2 < arr[2, 1] < 0.4
        # the far box keeps its score
        np.testing.assert_allclose(arr[1, 1], 0.7, atol=1e-6)

    def test_background_label_default_zeroes_class0(self):
        bx = paddle.to_tensor(np.random.rand(1, 3, 4).astype("float32"))
        sc = paddle.to_tensor(np.random.rand(1, 1, 3).astype("float32"))
        out, nums = vops.matrix_nms(bx, sc, score_threshold=0.01,
                                    nms_top_k=3, keep_top_k=3)
        assert int(nums.numpy()[0]) == 0

    def test_gaussian_mode(self):
        bx = paddle.to_tensor(
            np.array([[[0, 0, 10, 10], [1, 1, 11, 11]]], "float32"))
        sc = paddle.to_tensor(np.array([[[0.9, 0.8]]], "float32"))
        out, nums = vops.matrix_nms(bx, sc, score_threshold=0.1,
                                    post_threshold=0.0, nms_top_k=2,
                                    keep_top_k=2, use_gaussian=True,
                                    background_label=-1)
        arr = np.asarray(out.numpy())[0]
        assert arr[1, 1] < 0.8  # decayed


class TestGenerateProposals:
    def test_static_output_and_counts(self):
        h = w = 4
        a = 3
        np.random.seed(0)
        scores = paddle.to_tensor(np.random.rand(1, a, h, w)
                                  .astype("float32"))
        deltas = paddle.to_tensor(
            (np.random.randn(1, 4 * a, h, w) * 0.1).astype("float32"))
        anchors_np = np.random.rand(h, w, a, 4).astype("float32") * 16
        anchors_np[..., 2:] += anchors_np[..., :2] + 4
        rois, probs, n = vops.generate_proposals(
            scores, deltas,
            paddle.to_tensor(np.array([[32.0, 32.0]], "float32")),
            paddle.to_tensor(anchors_np),
            paddle.to_tensor(np.ones((h, w, a, 4), "float32")),
            pre_nms_top_n=20, post_nms_top_n=10, nms_thresh=0.5,
            min_size=1.0)
        assert rois.shape == [1, 10, 4]
        assert probs.shape == [1, 10, 1]
        cnt = int(n.numpy()[0])
        assert 1 <= cnt <= 10
        arr = np.asarray(rois.numpy())[0]
        assert arr.min() >= 0 and arr.max() <= 32

    def test_min_size_filters(self):
        h = w = 2
        a = 1
        scores = paddle.to_tensor(np.ones((1, a, h, w), "float32"))
        deltas = paddle.to_tensor(np.zeros((1, 4, h, w), "float32"))
        anchors_np = np.zeros((h, w, a, 4), "float32")
        anchors_np[..., 2:] = 0.5  # all anchors tiny
        rois, probs, n = vops.generate_proposals(
            scores, deltas,
            paddle.to_tensor(np.array([[32.0, 32.0]], "float32")),
            paddle.to_tensor(anchors_np),
            paddle.to_tensor(np.ones((h, w, a, 4), "float32")),
            post_nms_top_n=4, min_size=5.0)
        assert int(n.numpy()[0]) == 0


class TestDistributeFPN:
    def test_routing_and_restore(self):
        rois_in = paddle.to_tensor(
            np.array([[0, 0, 10, 10], [0, 0, 100, 100], [0, 0, 300, 300]],
                     "float32"))
        multi, restore = vops.distribute_fpn_proposals(rois_in, 2, 5, 4, 224)
        sizes = [m.shape[0] for m in multi]
        assert sum(sizes) == 3 and len(multi) == 4
        # floor(log2(scale/224)) + 4, clamped: 10px -> lvl 2, 100px -> lvl 2,
        # 300px -> lvl 4
        assert sizes == [2, 0, 1, 0]
        # restore index is a permutation
        r = np.asarray(restore.numpy()).reshape(-1)
        assert sorted(r.tolist()) == [0, 1, 2]

    def test_rois_num_output(self):
        rois_in = paddle.to_tensor(np.array([[0, 0, 50, 50]], "float32"))
        multi, restore, nums = vops.distribute_fpn_proposals(
            rois_in, 2, 5, 4, 224, rois_num=paddle.to_tensor(
                np.array([1], "int32")))
        assert len(nums) == 4


class TestNewModels:
    def test_resnext_forward(self):
        m = vision.models.resnext50_32x4d(num_classes=10)
        out = m(paddle.to_tensor(np.random.randn(1, 3, 64, 64)
                                 .astype("float32")))
        assert out.shape == [1, 10]

    def test_wide_resnet101(self):
        m = vision.models.wide_resnet101_2(num_classes=4)
        out = m(paddle.to_tensor(np.random.randn(1, 3, 64, 64)
                                 .astype("float32")))
        assert out.shape == [1, 4]

    def test_inception_v3(self):
        m = vision.models.inception_v3(num_classes=7)
        m.eval()
        out = m(paddle.to_tensor(np.random.randn(1, 3, 128, 128)
                                 .astype("float32")))
        assert out.shape == [1, 7]
        assert np.isfinite(out.numpy()).all()


class TestReviewFixes4:
    def test_model_average_is_a_mean(self):
        from paddle_tpu.core.tensor import Parameter
        from paddle_tpu.incubate.optimizer import ModelAverage
        p = Parameter(np.array([4.0], "float32"), name="ma_mean")
        ma = ModelAverage(0.5, parameters=[p])
        ma.step()                      # sum = 4
        p._set_data(p._data * 0 + 8.0)
        ma.step()                      # sum = 12, cnt = 2
        with ma.apply():
            np.testing.assert_allclose(np.asarray(p.numpy()), [6.0])
        np.testing.assert_allclose(np.asarray(p.numpy()), [8.0])

    def test_yolo_box_iou_aware_layout(self):
        cn, na = 2, 2
        h = w = 2
        # zero yolo block, large IoU logits: scores must react to the IoU
        # block placed AS A LEADING BLOCK of na channels
        feat = np.zeros((1, na + na * (5 + cn), h, w), "float32")
        feat[:, :na] = 5.0  # iou logits
        b, s = vops.yolo_box(paddle.to_tensor(feat),
                             paddle.to_tensor(np.array([[16, 16]], "int32")),
                             anchors=[8, 8, 12, 12], class_num=cn,
                             conf_thresh=0.0, downsample_ratio=8,
                             clip_bbox=False, iou_aware=True,
                             iou_aware_factor=0.5)
        # with zero yolo logits, obj=0.5, cls=0.5, iou=sigmoid(5)≈0.993
        # score = (0.5^0.5 * 0.993^0.5) * 0.5 ≈ 0.352
        np.testing.assert_allclose(np.asarray(s.numpy()), 0.3523, atol=1e-3)
        # boxes still decode from zero logits: w = anchor/input * img
        arr = np.asarray(b.numpy())[0]
        np.testing.assert_allclose(arr[0, 2] - arr[0, 0], 8.0, atol=1e-4)

    def test_prior_box_min_max_order(self):
        feat = paddle.zeros([1, 1, 1, 1])
        img = paddle.zeros([1, 3, 16, 16])
        boxes, _ = vops.prior_box(feat, img, min_sizes=[4.0], max_sizes=[8.0],
                                  aspect_ratios=[2.0], flip=True,
                                  min_max_aspect_ratios_order=True)
        arr = np.asarray(boxes.numpy())[0, 0]  # (P, 4), P = 4
        widths = (arr[:, 2] - arr[:, 0]) * 16
        # order: min (4), sqrt(4*8)≈5.657, ar2 (4*sqrt2), ar0.5 (4/sqrt2)
        np.testing.assert_allclose(
            widths, [4.0, 32 ** 0.5, 4 * 2 ** 0.5, 4 / 2 ** 0.5], atol=1e-4)

    def test_asp_m8_and_odd_shapes(self):
        import paddle_tpu.incubate as incubate
        import paddle_tpu.nn as nn
        model = nn.Linear(8, 2)  # weight (8, 2): last dim 2 not divisible by 8
        masks = incubate.asp.prune_model(model, n=2, m=8)
        assert masks == {}  # skipped, not crashed/mis-masked
        model2 = nn.Linear(2, 8)
        incubate.asp.prune_model(model2, n=2, m=8)
        assert abs(incubate.asp.calculate_density(model2.weight) - 0.25) < 0.01

    def test_rope_decode_step_with_position_ids(self):
        import paddle_tpu.incubate as incubate
        q = paddle.to_tensor(np.random.randn(2, 1, 4, 16).astype("float32"))
        cos = paddle.to_tensor(np.random.rand(1, 8, 1, 16).astype("float32"))
        sin = paddle.to_tensor(np.random.rand(1, 8, 1, 16).astype("float32"))
        pid = paddle.to_tensor(np.array([[5], [2]], "int32"))
        qq, _, _ = incubate.nn.functional.fused_rotary_position_embedding(
            q, sin=sin, cos=cos, position_ids=pid)
        assert qq.shape == [2, 1, 4, 16]

    def test_fused_norm_begin_norm_axis(self):
        import paddle_tpu.incubate as incubate
        x = paddle.to_tensor(np.random.randn(2, 3, 4).astype("float32"))
        out = incubate.nn.functional.fused_layer_norm(x, begin_norm_axis=1)
        arr = np.asarray(out.numpy())
        # normalized jointly over axes 1..2 -> per-sample mean 0, var 1
        np.testing.assert_allclose(arr.reshape(2, -1).mean(1), 0.0, atol=1e-5)
        np.testing.assert_allclose(arr.reshape(2, -1).var(1), 1.0, atol=1e-3)
