"""paddle_tpu.resilience: retry policies, circuit breakers, deterministic
fault injection, and crash-safe verified checkpointing.

The acceptance surface of PR 5: (a) a seeded/scripted ``FaultSchedule``
yields the SAME retry/failover trace on identical runs; (b) PS push dedup
holds under injected lost REPLIES; (c) the store client reconnects once on
a mid-request connection reset; (d) breaker state walks
closed→open→half-open→closed; (e) a kill injected during a checkpoint
save leaves the last-good checkpoint loadable with checksums verified;
(f) all of it is visible through the observability Prometheus exporter.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401  (backend init)
from paddle_tpu import observability as obs
from paddle_tpu import resilience as resil
from paddle_tpu.resilience.breaker import CircuitBreaker


@pytest.fixture(autouse=True)
def _clean_resilience(monkeypatch):
    """Fresh policies/breakers (re-reading env), fast backoffs, metrics
    on, no leftover schedule."""
    for name in ("PS_RPC", "STORE_CONNECT", "RPC_DIAL"):
        monkeypatch.setenv(f"PADDLE_TPU_RETRY_{name}_BASE_DELAY", "0.001")
        monkeypatch.setenv(f"PADDLE_TPU_RETRY_{name}_MAX_DELAY", "0.002")
    resil.reset_policies()
    resil.reset_breakers()
    resil.uninstall()
    obs.enable()
    obs.reset()
    yield
    resil.uninstall()
    resil.reset_policies()
    resil.reset_breakers()
    obs.disable()
    obs.reset()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        pol = resil.RetryPolicy("t.ok", base_delay=0.001, max_delay=0.002)
        calls = [0]
        for attempt in pol.start():
            calls[0] += 1
            try:
                if calls[0] < 3:
                    raise ConnectionError("transient")
                break
            except ConnectionError as e:
                attempt.fail(e)
        assert calls[0] == 3
        assert obs.snapshot()["resilience.retries_total"] == {
            "policy=t.ok": 2.0}

    def test_attempt_cap_reraises_original_and_counts_giveup(self):
        pol = resil.RetryPolicy("t.cap", base_delay=0.001, max_attempts=3)
        calls = [0]
        with pytest.raises(ConnectionError, match="always"):
            for attempt in pol.start():
                calls[0] += 1
                try:
                    raise ConnectionError("always")
                except ConnectionError as e:
                    attempt.fail(e)
        assert calls[0] == 3
        assert obs.snapshot()["resilience.giveups_total"] == {
            "policy=t.cap": 1.0}

    def test_deadline_bounds_attempts(self):
        pol = resil.RetryPolicy("t.dl", base_delay=0.005, jitter=0.0)
        calls = [0]
        with pytest.raises(TimeoutError):
            for attempt in pol.start(deadline=0.02):
                calls[0] += 1
                try:
                    raise TimeoutError("slow")
                except TimeoutError as e:
                    attempt.fail(e)
        assert 2 <= calls[0] <= 10  # bounded by the 20ms budget, not ∞

    def test_deadline_scope_propagates_and_clamps(self):
        import time
        # ambient 10ms scope clamps a policy whose own deadline is 10s
        pol = resil.RetryPolicy("t.scope", base_delay=0.001, deadline=10.0)
        with resil.deadline_scope(0.01):
            att = pol.start()
            assert att.remaining() <= 0.01 + 1e-3
            # a nested LOOSER scope cannot extend the outer budget
            with resil.deadline_scope(5.0):
                assert resil.current_deadline() <= time.monotonic() + 0.011
        assert resil.current_deadline() is None

    def test_backoff_growth_and_jitter_bounds(self):
        slept = []
        pol = resil.RetryPolicy("t.growth", base_delay=0.1, multiplier=2.0,
                                max_delay=0.4, jitter=0.25,
                                sleep=slept.append)
        with pytest.raises(OSError):
            for attempt in pol.start():
                try:
                    raise OSError("x")
                except OSError as e:
                    if len(slept) >= 5:
                        raise
                    attempt.fail(e)
        # nominal schedule 0.1, 0.2, 0.4, 0.4, 0.4 — each within ±25%
        for nominal, got in zip([0.1, 0.2, 0.4, 0.4, 0.4], slept):
            assert nominal * 0.75 - 1e-9 <= got <= nominal * 1.25 + 1e-9

    def test_env_overrides_apply_at_creation(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_RETRY_T_ENV_MAX_ATTEMPTS", "7")
        monkeypatch.setenv("PADDLE_TPU_RETRY_T_ENV_BASE_DELAY", "0.5")
        resil.reset_policies()
        pol = resil.get_policy("t.env", base_delay=0.1)
        assert pol.max_attempts == 7 and pol.base_delay == 0.5
        # cached: later defaults do not reconfigure
        assert resil.get_policy("t.env", base_delay=9.9).base_delay == 0.5

    def test_jitter_sleep_bounds(self):
        import random
        slept = []
        d = resil.jitter_sleep(1.0, frac=0.25, rng=random.Random(3),
                               sleep=slept.append)
        assert slept == [d] and 0.75 <= d <= 1.25
        assert resil.jitter_sleep(0.0, sleep=slept.append) == 0.0


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_full_state_walk(self):
        t = [0.0]
        br = CircuitBreaker("ep", failure_threshold=2, cooldown=5.0,
                            clock=lambda: t[0])
        br.before_call(); br.record_failure()
        br.before_call(); br.record_failure()
        assert br.state == "open"
        with pytest.raises(resil.BreakerOpen):
            br.before_call()  # cooling: fast local failure
        t[0] = 6.0
        br.before_call()      # cooldown elapsed: half-open probe admitted
        assert br.state == "half_open"
        with pytest.raises(resil.BreakerOpen):
            br.before_call()  # single probe slot taken
        br.record_success()
        assert br.state == "closed"
        snap = obs.snapshot()
        assert snap["resilience.breaker_state"] == {"endpoint=ep": 0.0}
        trans = snap["resilience.breaker_transitions_total"]
        assert trans["endpoint=ep,to=open"] == 1.0
        assert trans["endpoint=ep,to=half_open"] == 1.0
        assert trans["endpoint=ep,to=closed"] == 1.0
        assert snap["resilience.breaker_short_circuits_total"] == {
            "endpoint=ep": 2.0}

    def test_failed_probe_reopens(self):
        t = [0.0]
        br = CircuitBreaker("ep2", failure_threshold=1, cooldown=1.0,
                            clock=lambda: t[0])
        br.before_call(); br.record_failure()
        t[0] = 2.0
        br.before_call()
        br.record_failure()   # probe failed
        assert br.state == "open"
        with pytest.raises(resil.BreakerOpen):
            br.before_call()  # new cooldown window
        t[0] = 4.0
        br.before_call(); br.record_success()
        assert br.state == "closed"

    def test_reset_closes_and_registry_caches(self):
        br = resil.breaker_for("ps/srv0", failure_threshold=1)
        assert resil.breaker_for("ps/srv0") is br
        br.before_call(); br.record_failure()
        assert br.state == "open"
        br.reset()
        assert br.state == "closed"
        br.before_call()  # admitted again


# ---------------------------------------------------------------------------
# FaultSchedule
# ---------------------------------------------------------------------------

class TestFaultSchedule:
    def test_zero_overhead_when_uninstalled(self):
        resil.fault_point("nowhere")  # no schedule: pure no-op

    def test_scripted_indices_and_kinds(self):
        s = resil.FaultSchedule()
        s.error("a.site", on=(2,), error=ConnectionResetError)
        s.delay("a.site", on=(3,), seconds=0.0)
        with resil.installed(s):
            resil.fault_point("a.site")
            with pytest.raises(ConnectionResetError):
                resil.fault_point("a.site")
            resil.fault_point("a.site")  # delay(0): returns
        assert s.trace == [("a.site", 2, "error"), ("a.site", 3, "delay")]
        assert s.calls("a.site") == 3

    def test_kill_is_not_an_ordinary_exception(self):
        s = resil.FaultSchedule().kill("k.site", on=(1,))
        with resil.installed(s):
            with pytest.raises(resil.KillPoint):
                try:
                    resil.fault_point("k.site")
                except Exception:  # noqa: BLE001 — the point of the test
                    pytest.fail("KillPoint must evade `except Exception`")

    def test_seeded_schedule_is_deterministic(self):
        def run(seed):
            s = resil.FaultSchedule(seed=seed)
            s.error("p.site", prob=0.5, error=ConnectionError)
            with resil.installed(s):
                for _ in range(32):
                    try:
                        resil.fault_point("p.site")
                    except ConnectionError:
                        pass  # injected: the workload keeps going
            return list(s.trace)

        t1, t2 = run(1234), run(1234)
        assert t1 == t2 and t1  # same seed → same trace, and faults fired
        assert run(99) != t1    # different seed → different plan

    def test_times_cap(self):
        s = resil.FaultSchedule().error("c.site", prob=1.0, times=2)
        with resil.installed(s):
            for _ in range(2):
                with pytest.raises(resil.FaultInjected):
                    resil.fault_point("c.site")
            resil.fault_point("c.site")  # budget spent: clean
        assert len(s.trace) == 2
        assert obs.snapshot()["resilience.injected_faults_total"] == {
            "kind=error,site=c.site": 2.0}


# ---------------------------------------------------------------------------
# PS client under injected faults (in-process: deterministic, no sockets)
# ---------------------------------------------------------------------------

def _fake_rpc_client(monkeypatch):
    """PsClient whose rpc plane executes handlers in-process: the real
    ``_call`` retry/breaker path runs unchanged, transport faults come
    from the installed FaultSchedule."""
    from types import SimpleNamespace
    from paddle_tpu.distributed import ps_service as ps
    from paddle_tpu.distributed.rpc import RpcTransportError, WorkerInfo

    info = WorkerInfo("srv", 0, "127.0.0.1", 1)
    fake = SimpleNamespace(
        rpc_sync=lambda server, fn, args=None: fn(*(args or ())),
        RpcTransportError=RpcTransportError,
        get_worker_info=lambda name: info,
        refresh_worker_info=lambda name: info)
    monkeypatch.setattr(ps.PsClient, "_rpc", lambda self: fake)
    ps.reset_server_state()
    return ps


class TestPsFaultInjection:
    def test_push_dedup_under_injected_reply_drops(self, monkeypatch):
        ps = _fake_rpc_client(monkeypatch)
        from paddle_tpu.distributed.rpc import RpcTransportError

        def scenario():
            ps.reset_server_state()
            resil.reset_breakers()
            client = ps.PsClient("srv", lr=1.0, retry_timeout=5.0)
            client.create_table("t", np.zeros((4, 2), np.float32))
            sched = resil.FaultSchedule()
            # drop the REPLY of the first push rpc: the server APPLIED the
            # gradient, the client must retry, the seq watermark must
            # discard the duplicate
            sched.drop("ps.reply", on=(2,), error=RpcTransportError)
            with resil.installed(sched):
                client.push("t", [1], np.ones((1, 2), np.float32))
                client.push("t", [1], np.ones((1, 2), np.float32))
            snap = client.table_snapshot("t")
            return list(sched.trace), snap.copy(), dict(ps.serve_stats())

        trace1, table1, stats1 = scenario()
        # exactly-once despite the retried wire push
        np.testing.assert_allclose(table1[1], [-2.0, -2.0])
        assert stats1["dup_pushes"] == 1
        assert obs.snapshot()["ps.rpc_retries_total"] >= 1.0

        # acceptance: the same schedule yields the same retry/failover
        # trace twice
        trace2, table2, stats2 = scenario()
        assert trace1 == trace2 == [("ps.reply", 2, "error")]
        np.testing.assert_array_equal(table1, table2)
        assert stats1["dup_pushes"] == stats2["dup_pushes"]

    def test_exhausted_budget_raises_transport_error_not_breaker(
            self, monkeypatch):
        ps = _fake_rpc_client(monkeypatch)
        from paddle_tpu.distributed.rpc import RpcTransportError

        client = ps.PsClient("srv", retry_timeout=0.05)
        sched = resil.FaultSchedule()
        sched.drop("ps.call", prob=1.0, error=RpcTransportError)
        with resil.installed(sched):
            with pytest.raises(RpcTransportError):
                client.create_table("t", np.zeros((2, 2), np.float32))
        snap = obs.snapshot()
        assert snap["ps.rpc_failures_total"] == 1.0
        # the per-server breaker opened along the way (threshold 5 < the
        # ~50 attempts a 50ms budget of 1ms backoffs admits)
        assert snap["resilience.breaker_state"]["endpoint=ps/srv"] == 2.0
        assert snap["resilience.breaker_short_circuits_total"][
            "endpoint=ps/srv"] >= 1.0

    def test_server_side_error_is_not_retried(self, monkeypatch):
        ps = _fake_rpc_client(monkeypatch)

        client = ps.PsClient("srv", retry_timeout=5.0)
        sched = resil.FaultSchedule()
        # a HANDLER error ships back with its original type: the call
        # executed, the client must not retry it
        sched.error("ps.handler", on=(1,), error=RuntimeError)
        with resil.installed(sched):
            with pytest.raises(RuntimeError):
                client.push("t-absent", [0], np.ones((1, 1), np.float32))
        assert obs.snapshot().get("ps.rpc_retries_total") is None

    def test_server_side_error_during_probe_frees_breaker(self, monkeypatch):
        ps = _fake_rpc_client(monkeypatch)

        # force the per-server breaker open, with a zero cooldown so the
        # very next call runs as the half-open PROBE
        br = resil.breaker_for("ps/srv", failure_threshold=1, cooldown=0.0)
        br.before_call(); br.record_failure()
        assert br.state == "open"
        client = ps.PsClient("srv", retry_timeout=5.0)
        sched = resil.FaultSchedule().error("ps.handler", on=(1,),
                                            error=RuntimeError)
        with resil.installed(sched):
            with pytest.raises(RuntimeError):
                client.push("t-absent", [0], np.ones((1, 1), np.float32))
        # the probe hit an APPLICATION error: endpoint executed the call,
        # so the breaker closes and the probe slot is freed (a wedged
        # half_open here would fail every future call to this server)
        assert br.state == "closed"
        client.create_table("t", np.zeros((2, 2), np.float32))  # admitted

    def test_injected_fault_during_probe_frees_breaker(self, monkeypatch):
        ps = _fake_rpc_client(monkeypatch)

        # ISSUE 18: the ps.call fault seam sits INSIDE the breaker's
        # record try now — a non-transport injected fault used to escape
        # between before_call() and the rpc with the half-open probe
        # still out, wedging the breaker half-open forever (found by the
        # resource-discipline lint)
        br = resil.breaker_for("ps/srv", failure_threshold=1, cooldown=0.0)
        br.before_call(); br.record_failure()
        assert br.state == "open"
        client = ps.PsClient("srv", retry_timeout=5.0)
        sched = resil.FaultSchedule().error("ps.call", on=(1,),
                                            error=RuntimeError)
        with resil.installed(sched):
            with pytest.raises(RuntimeError):
                client.create_table("t", np.zeros((2, 2), np.float32))
        assert br.state == "closed"
        client.create_table("t", np.zeros((2, 2), np.float32))  # admitted

    def test_breaker_only_exhaustion_raises_transport_error(
            self, monkeypatch):
        ps = _fake_rpc_client(monkeypatch)
        from paddle_tpu.distributed.rpc import RpcTransportError

        # breaker opened by a PREVIOUS call's failures, long cooldown: a
        # new call whose budget is shorter than the cooldown only ever
        # sees BreakerOpen — it must still surface the documented
        # transport type
        br = resil.breaker_for("ps/srv", failure_threshold=1, cooldown=60.0)
        br.before_call(); br.record_failure()
        client = ps.PsClient("srv", retry_timeout=0.02)
        with pytest.raises(RpcTransportError, match="breaker"):
            client.create_table("t", np.zeros((2, 2), np.float32))


# ---------------------------------------------------------------------------
# TCPStore reconnect (pure-python client; native skipped by use_native)
# ---------------------------------------------------------------------------

class TestStoreReconnect:
    def test_reconnect_once_on_injected_reset(self):
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True, world_size=1, use_native=False,
                         timeout=5.0)
        try:
            store.set("k", b"v1")
            sched = resil.FaultSchedule()
            sched.error("store.request", on=(1,),
                        error=ConnectionResetError)
            with resil.installed(sched):
                assert store.get("k") == b"v1"  # reconnected + resent
            assert obs.snapshot()["store.reconnects_total"] == 1.0
            assert store.get("k") == b"v1"      # healthy afterwards
        finally:
            store.close()

    def test_second_consecutive_failure_surfaces(self):
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True, world_size=1, use_native=False,
                         timeout=5.0)
        try:
            sched = resil.FaultSchedule()
            sched.error("store.request", on=(1, 2),
                        error=ConnectionResetError)
            with resil.installed(sched):
                with pytest.raises(ConnectionError):
                    store.set("k", b"v")
        finally:
            store.close()


class TestRpcDial:
    def test_total_timeout_not_multiplied_by_attempts(self, monkeypatch):
        import time
        from paddle_tpu.distributed import rpc

        seen = []

        def refuse(addr, timeout=None):
            seen.append(timeout)
            raise ConnectionRefusedError("refused")

        monkeypatch.setattr(rpc.socket, "create_connection", refuse)
        info = rpc.WorkerInfo("w", 0, "127.0.0.1", 1)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            rpc._dial(info, 0.05)
        # the caller's timeout is a TOTAL budget: per-attempt connect
        # timeouts are clamped to what remains, never 3 × 0.05
        assert time.monotonic() - t0 < 1.0
        assert 1 <= len(seen) <= 3
        assert all(t is not None and t <= 0.06 for t in seen)


# ---------------------------------------------------------------------------
# crash-safe checkpointing
# ---------------------------------------------------------------------------

def _state(values):
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor
    return {"model": {"w": Tensor(jnp.asarray(values, jnp.float32))}}


class TestCrashSafeCheckpoint:
    def test_manifest_commit_and_pointers(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([1.0, 2.0]), str(tmp_path / "step1"))
        m = ckpt.verify_checkpoint(str(tmp_path / "step1"))
        assert m["version"] == 1 and "model.w" in m["arrays"]
        assert m["arrays"]["model.w"]["crc32"] is not None
        assert (tmp_path / "latest").read_text().strip() == "step1"
        ckpt.save_state_dict(_state([3.0, 4.0]), str(tmp_path / "step2"))
        assert (tmp_path / "latest").read_text().strip() == "step2"
        assert (tmp_path / "latest.prev").read_text().strip() == "step1"

    @pytest.mark.parametrize("site", ["checkpoint.write",
                                      "checkpoint.commit"])
    def test_kill_during_save_leaves_last_good_loadable(self, tmp_path,
                                                        site):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([1.0, 2.0]), str(tmp_path / "step1"))
        sched = resil.FaultSchedule().kill(site, on=(1,))
        with resil.installed(sched):
            with pytest.raises(resil.KillPoint):
                ckpt.save_state_dict(_state([9.0, 9.0]),
                                     str(tmp_path / "step2"))
        # the interrupted save never committed, never moved the pointer
        assert (tmp_path / "latest").read_text().strip() == "step1"
        target = _state([0.0, 0.0])
        ckpt.load_state_dict(target, str(tmp_path / "step2"))
        np.testing.assert_array_equal(
            np.asarray(target["model"]["w"]._data), [1.0, 2.0])
        assert obs.snapshot()["checkpoint.fallbacks_total"] == 1.0

    def test_crc_mismatch_falls_back(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([1.0, 2.0]), str(tmp_path / "s1"))
        ckpt.save_state_dict(_state([3.0, 4.0]), str(tmp_path / "s2"))
        # corrupt s2's recorded checksum: verification must reject it
        mpath = tmp_path / "s2" / "manifest.json"
        m = json.loads(mpath.read_text())
        m["arrays"]["model.w"]["crc32"] ^= 0xDEAD
        mpath.write_text(json.dumps(m))
        target = _state([0.0, 0.0])
        ckpt.load_state_dict(target, str(tmp_path / "s2"))
        np.testing.assert_array_equal(
            np.asarray(target["model"]["w"]._data), [1.0, 2.0])
        snap = obs.snapshot()
        assert snap["checkpoint.fallbacks_total"] == 1.0
        assert snap["checkpoint.crc_mismatches_total"] == 1.0

    def test_no_fallback_available_raises(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([1.0, 2.0]), str(tmp_path / "only"))
        os.remove(tmp_path / "only" / "manifest.json")
        with pytest.raises(ckpt.CheckpointCorruptError, match="verify"):
            ckpt.load_state_dict(_state([0.0, 0.0]),
                                 str(tmp_path / "only"))
        snap = obs.snapshot()
        # a verification failure with nowhere to fall back is NOT a
        # fallback — alerting keys on fallbacks_total
        assert snap.get("checkpoint.fallbacks_total") is None
        assert snap["checkpoint.verification_failures_total"] == 1.0

    def test_stale_async_commit_cannot_roll_latest_back(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([1.0]), str(tmp_path / "old"))
        ckpt.save_state_dict(_state([2.0]), str(tmp_path / "new"))
        assert (tmp_path / "latest").read_text().strip() == "new"
        # a slow async save of "old" finishing NOW (stale seq) must not
        # rotate the pointer backwards
        ckpt._update_latest(str(tmp_path / "old"), seq=0)
        assert (tmp_path / "latest").read_text().strip() == "new"

    def test_verify_false_keeps_original_error_surface(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        with pytest.raises(FileNotFoundError):
            ckpt.load_state_dict(_state([0.0]), str(tmp_path / "absent"),
                                 verify=False)

    def test_verify_false_loads_legacy_directory(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([5.0, 6.0]), str(tmp_path / "legacy"))
        os.remove(tmp_path / "legacy" / "manifest.json")
        target = _state([0.0, 0.0])
        ckpt.load_state_dict(target, str(tmp_path / "legacy"),
                             verify=False)
        np.testing.assert_array_equal(
            np.asarray(target["model"]["w"]._data), [5.0, 6.0])

    def test_async_save_commits_manifest(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.async_save_state_dict(_state([7.0, 8.0]),
                                   str(tmp_path / "as1"))
        ckpt.wait_async_saves()
        ckpt.verify_checkpoint(str(tmp_path / "as1"))
        target = _state([0.0, 0.0])
        ckpt.load_state_dict(target, str(tmp_path / "as1"))
        np.testing.assert_array_equal(
            np.asarray(target["model"]["w"]._data), [7.0, 8.0])

    def test_user_errors_never_trigger_fallback(self, tmp_path):
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.distributed import checkpoint as ckpt

        ckpt.save_state_dict(_state([1.0, 2.0]), str(tmp_path / "u1"))
        with pytest.raises(KeyError):
            ckpt.load_state_dict(
                {"nope": Tensor(jnp.zeros(2))}, str(tmp_path / "u1"))
        with pytest.raises(ValueError, match="shape mismatch"):
            ckpt.load_state_dict(
                {"model": {"w": Tensor(jnp.zeros((3, 3)))}},
                str(tmp_path / "u1"))
        assert obs.snapshot().get("checkpoint.fallbacks_total") is None


# ---------------------------------------------------------------------------
# exporter visibility (acceptance: metrics scrape-able)
# ---------------------------------------------------------------------------

def test_resilience_metrics_visible_in_prometheus_text(tmp_path):
    from paddle_tpu.distributed import checkpoint as ckpt

    pol = resil.RetryPolicy("t.prom", base_delay=0.001)
    for attempt in pol.start():
        try:
            if attempt.attempt < 2:
                raise OSError("x")
            break
        except OSError as e:
            attempt.fail(e)
    br = resil.breaker_for("prom/ep", failure_threshold=1)
    br.before_call(); br.record_failure()
    sched = resil.FaultSchedule().kill("checkpoint.commit", on=(1,))
    ckpt.save_state_dict(_state([1.0]), str(tmp_path / "a"))
    with resil.installed(sched):
        with pytest.raises(resil.KillPoint):
            ckpt.save_state_dict(_state([2.0]), str(tmp_path / "b"))
    ckpt.load_state_dict(_state([0.0]), str(tmp_path / "b"))

    text = obs.prometheus_text()
    for sample in ("resilience_retries_total", "resilience_breaker_state",
                   "resilience_breaker_transitions_total",
                   "resilience_injected_faults_total",
                   "checkpoint_fallbacks_total", "checkpoint_saves_total"):
        assert sample in text, sample
    parsed = obs.parse_prometheus_text(text)
    assert parsed["checkpoint_fallbacks_total"][""] == 1.0
    assert parsed["resilience_breaker_state"]['{endpoint="prom/ep"}'] == 2.0
