"""Tests for the extended API-parity batch: math_ext ops, linalg additions,
static utility surface, dlpack, namespace fills.

Pattern per SURVEY.md §4: every op vs a NumPy reference.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


class TestMathExt:
    def test_cdist(self):
        x = np.random.randn(4, 5).astype("float32")
        y = np.random.randn(3, 5).astype("float32")
        d = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y))
        ref = np.linalg.norm(x[:, None, :] - y[None, :, :], axis=-1)
        np.testing.assert_allclose(d.numpy(), ref, atol=1e-4)

    def test_cdist_p1(self):
        x = np.random.randn(4, 5).astype("float32")
        y = np.random.randn(3, 5).astype("float32")
        d = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y), p=1.0)
        ref = np.abs(x[:, None, :] - y[None, :, :]).sum(-1)
        np.testing.assert_allclose(d.numpy(), ref, atol=1e-4)

    def test_cdist_mm_path(self):
        x = np.random.randn(80, 8).astype("float32")
        y = np.random.randn(70, 8).astype("float32")
        d = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y))
        ref = np.linalg.norm(x[:, None, :] - y[None, :, :], axis=-1)
        np.testing.assert_allclose(d.numpy(), ref, atol=1e-2)

    def test_ldexp_signbit_inf_checks(self):
        x = paddle.to_tensor([1.0, -2.0])
        np.testing.assert_allclose(paddle.ldexp(x, paddle.to_tensor([2, 3])).numpy(),
                                   [4.0, -16.0])
        assert paddle.signbit(x).numpy().tolist() == [False, True]
        inf = paddle.to_tensor([np.inf, -np.inf, 1.0])
        assert paddle.isposinf(inf).numpy().tolist() == [True, False, False]
        assert paddle.isneginf(inf).numpy().tolist() == [False, True, False]
        assert paddle.isreal(x).numpy().all()

    def test_isin(self):
        out = paddle.isin(paddle.to_tensor([1, 2, 3, 4]),
                          paddle.to_tensor([2, 4]))
        assert out.numpy().tolist() == [False, True, False, True]

    def test_renorm(self):
        x = np.random.randn(3, 4).astype("float32") * 10
        out = paddle.renorm(paddle.to_tensor(x), p=2.0, axis=0, max_norm=1.0)
        norms = np.linalg.norm(out.numpy(), axis=1)
        assert (norms <= 1.0 + 1e-4).all()

    def test_combinations(self):
        c = paddle.combinations(paddle.to_tensor([1, 2, 3, 4]), 3)
        assert c.shape == [4, 3]
        cr = paddle.combinations(paddle.to_tensor([1, 2]), 2,
                                 with_replacement=True)
        assert cr.numpy().tolist() == [[1, 1], [1, 2], [2, 2]]

    def test_fill_diagonal_(self):
        t = paddle.zeros([3, 4])
        t.fill_diagonal_(7.0)
        ref = np.zeros((3, 4), "float32")
        np.fill_diagonal(ref, 7.0)
        np.testing.assert_allclose(t.numpy(), ref)

    def test_diagonal_scatter(self):
        x = np.zeros((3, 3), "float32")
        y = np.array([1.0, 2.0], "float32")
        out = paddle.diagonal_scatter(paddle.to_tensor(x),
                                      paddle.to_tensor(y), offset=1)
        ref = x.copy()
        ref[0, 1], ref[1, 2] = 1.0, 2.0
        np.testing.assert_allclose(out.numpy(), ref)

    def test_unfold_as_strided_view_as(self):
        x = paddle.to_tensor(np.arange(10).astype("float32"))
        u = x.unfold(0, 4, 3)
        assert u.numpy().tolist() == [[0, 1, 2, 3], [3, 4, 5, 6], [6, 7, 8, 9]]
        s = paddle.as_strided(x, [3, 2], [2, 1])
        assert s.numpy().tolist() == [[0, 1], [2, 3], [4, 5]]
        v = x.view_as(paddle.zeros([2, 5]))
        assert v.shape == [2, 5]
        assert x.contiguous() is x and x.is_contiguous()

    def test_strides_matches_numpy(self):
        # element strides, not bytes: numpy strides / itemsize
        for shape in [(2, 3, 4), (5,), (1, 1), (3, 1, 2)]:
            a = np.zeros(shape, np.float32)
            t = paddle.to_tensor(a)
            want = [s // a.itemsize for s in a.strides]
            assert t.strides == want            # attribute, like upstream
            assert paddle.strides(t) == want    # functional spelling
        s = paddle.to_tensor(np.float32(3.0))
        assert s.strides == [] and paddle.strides(s) == []

    def test_is_contiguous_dense_buffers(self):
        t = paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4))
        assert t.is_contiguous() is True
        assert paddle.is_contiguous(t) is True
        # derived views gather into fresh dense buffers: still contiguous,
        # with the canonical strides of the NEW shape
        s = paddle.as_strided(t, [2, 2], [4, 1])
        assert s.is_contiguous() and s.strides == [2, 1]

    def test_standard_gamma(self):
        alpha = paddle.full([1000], 5.0)
        g = paddle.standard_gamma(alpha)
        assert abs(float(g.numpy().mean()) - 5.0) < 0.5

    def test_top_p_sampling(self):
        logits = np.full((2, 8), -10.0, "float32")
        logits[:, 0] = 10.0  # all mass on token 0
        vals, ids = paddle.top_p_sampling(paddle.to_tensor(logits),
                                          paddle.to_tensor([0.9, 0.9]))
        assert ids.numpy().reshape(-1).tolist() == [0, 0]

    def test_gradients_flow(self):
        x = paddle.to_tensor(np.random.randn(4, 5).astype("float32"),
                             stop_gradient=False)
        y = paddle.to_tensor(np.random.randn(3, 5).astype("float32"))
        paddle.cdist(x, y).sum().backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


class TestLinalgExt:
    def test_lu_roundtrip(self):
        a = np.random.randn(5, 5).astype("float32")
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(a))
        p, l, u = paddle.linalg.lu_unpack(lu_t, piv)
        np.testing.assert_allclose(p.numpy() @ l.numpy() @ u.numpy(), a,
                                   atol=1e-4)

    def test_lu_get_infos(self):
        a = np.random.randn(4, 4).astype("float32")
        lu_t, piv, info = paddle.linalg.lu(paddle.to_tensor(a), get_infos=True)
        assert int(info.numpy()) == 0

    def test_matrix_exp(self):
        a = np.diag([1.0, 2.0]).astype("float32")
        out = paddle.linalg.matrix_exp(paddle.to_tensor(a))
        np.testing.assert_allclose(out.numpy(), np.diag(np.exp([1.0, 2.0])),
                                   rtol=1e-5)

    def test_ormqr(self):
        a = np.random.randn(4, 3).astype("float32")
        h, tau = np.linalg.qr(a, mode="raw")  # h is packed transposed (n, m)
        packed = np.asarray(h.T, "float32")
        tau = np.asarray(tau, "float32")
        c = np.random.randn(4, 2).astype("float32")
        out = paddle.linalg.ormqr(paddle.to_tensor(packed),
                                  paddle.to_tensor(tau),
                                  paddle.to_tensor(c))
        q = np.linalg.qr(a, mode="complete")[0].astype("float32")
        np.testing.assert_allclose(np.abs(out.numpy()), np.abs(q @ c),
                                   atol=1e-3)

    def test_vector_matrix_norm(self):
        x = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(
            paddle.linalg.vector_norm(paddle.to_tensor(x), 2.0, axis=1).numpy(),
            np.linalg.norm(x, axis=1), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.matrix_norm(paddle.to_tensor(x)).numpy(),
            np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.vecdot(paddle.to_tensor(x), paddle.to_tensor(x)).numpy(),
            (x * x).sum(-1), rtol=1e-5)


class TestStaticExt:
    def test_fc_program(self):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                inp = static.data("x", [None, 4], "float32")
                h = static.nn.fc(inp, 8, activation="relu")
                out = static.nn.fc(h, 2)
            res = static.Executor().run(
                prog, feed={"x": np.random.randn(3, 4).astype("float32")},
                fetch_list=[out])
            assert res[0].shape == (3, 2)
        finally:
            paddle.disable_static()

    def test_conv_bn_embedding_program(self):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                img = static.data("img", [None, 3, 8, 8], "float32")
                c = static.nn.conv2d(img, 4, 3, padding=1, act="relu")
                bn = static.nn.batch_norm(c)
                ids = static.data("ids", [None, 5], "int64")
                emb = static.nn.embedding(ids, [10, 6])
            res = static.Executor().run(
                prog,
                feed={"img": np.random.randn(2, 3, 8, 8).astype("float32"),
                      "ids": np.random.randint(0, 10, (2, 5))},
                fetch_list=[bn, emb])
            assert res[0].shape == (2, 4, 8, 8)
            assert res[1].shape == (2, 5, 6)
        finally:
            paddle.disable_static()

    def test_gradients(self):
        x = paddle.to_tensor(np.array([3.0], "float32"), stop_gradient=False)
        y = (x * x).sum()
        g = static.gradients([y], [x])
        np.testing.assert_allclose(g[0].numpy(), [6.0])

    def test_py_func(self):
        out = paddle.zeros([3])
        static.py_func(lambda a: a + 1,
                       paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32")),
                       out)
        assert out.numpy().tolist() == [2.0, 3.0, 4.0]

    def test_accuracy_auc(self):
        pred = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], "float32"))
        label = paddle.to_tensor(np.array([[1], [0]]))
        assert abs(float(static.accuracy(pred, label)) - 1.0) < 1e-6
        scores = paddle.to_tensor(
            np.array([[0.3, 0.7], [0.6, 0.4], [0.2, 0.8], [0.9, 0.1]],
                     "float32"))
        labels = paddle.to_tensor(np.array([1, 0, 1, 0]))
        assert float(static.auc(scores, labels)) > 0.9

    def test_create_parameter_guards(self):
        p = static.create_parameter([4, 4], "float32")
        assert p.shape == [4, 4] and not p.stop_gradient
        with static.name_scope("blk"):
            pass
        with static.device_guard("cpu"):
            pass
        v = static.create_global_var([2], 1.5, "float32")
        np.testing.assert_allclose(v.numpy(), [1.5, 1.5])


class TestNamespaceFills:
    def test_flags_and_modes(self):
        assert paddle.in_dynamic_mode()
        assert not paddle.in_static_mode()
        assert isinstance(paddle.is_grad_enabled(), bool)
        assert paddle.amp.is_bfloat16_supported()
        assert paddle.amp.is_float16_supported()
        assert not paddle.is_compiled_with_xpu()
        assert not paddle.is_compiled_with_rocm()
        assert paddle.is_compiled_with_cinn()

    def test_places(self):
        for fn in (paddle.XPUPlace, paddle.MLUPlace, paddle.IPUPlace):
            assert fn(0).device_type in ("cpu", "tpu")
        assert paddle.CUDAPinnedPlace().device_type == "cpu"

    def test_tensor_module(self):
        assert paddle.tensor.abs is paddle.abs
        assert paddle.tensor.matmul is paddle.matmul
        with pytest.raises(AttributeError):
            paddle.tensor.not_a_real_op_name

    def test_rng_state_roundtrip(self):
        st = paddle.get_cuda_rng_state()
        a = paddle.rand([4]).numpy()
        paddle.set_cuda_rng_state(st)
        b = paddle.rand([4]).numpy()
        np.testing.assert_allclose(a, b)

    def test_sysconfig(self):
        assert paddle.sysconfig.get_include().endswith("csrc")
        assert paddle.sysconfig.get_lib()

    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils import dlpack
        x = paddle.to_tensor(np.random.randn(3, 3).astype("float32"))
        y = dlpack.from_dlpack(dlpack.to_dlpack(x))
        np.testing.assert_allclose(y.numpy(), x.numpy())

    def test_cpp_extension_load(self, tmp_path):
        src = tmp_path / "ext.cc"
        src.write_text('extern "C" int add3(int x) { return x + 3; }\n')
        from paddle_tpu.utils import cpp_extension
        lib = cpp_extension.load("t_ext", [str(src)],
                                 build_directory=str(tmp_path))
        assert lib.add3(4) == 7

    def test_download_local_passthrough(self, tmp_path):
        f = tmp_path / "weights.bin"
        f.write_bytes(b"x")
        from paddle_tpu.utils import download
        assert download.get_path_from_url(str(f)) == str(f)
        with pytest.raises(RuntimeError):
            download.get_weights_path_from_url("http://example.com/nope.bin")


class TestReviewFixes:
    def test_lu_unpack_batched(self):
        a = np.random.randn(2, 4, 4).astype("float32")
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(a))
        p, l, u = paddle.linalg.lu_unpack(lu_t, piv)
        rec = np.einsum("bij,bjk,bkl->bil", p.numpy(), l.numpy(), u.numpy())
        np.testing.assert_allclose(rec, a, atol=1e-4)

    def test_ldexp_no_overflow(self):
        out = paddle.ldexp(paddle.to_tensor([1e-30], "float32"),
                           paddle.to_tensor([200]))
        np.testing.assert_allclose(out.numpy(),
                                   np.ldexp(np.float32(1e-30), 200),
                                   rtol=1e-6)

    def test_ormqr_batched(self):
        packed = np.zeros((2, 4, 3), "float32")
        tau = np.zeros((2, 3), "float32")  # zero reflectors -> Q = I
        c = np.random.randn(2, 4, 2).astype("float32")
        out = paddle.linalg.ormqr(paddle.to_tensor(packed),
                                  paddle.to_tensor(tau), paddle.to_tensor(c))
        np.testing.assert_allclose(out.numpy(), c, atol=1e-6)

    def test_static_conv2d_bias_attr(self):
        from paddle_tpu.nn import initializer as I

        class Attr:
            initializer = I.Constant(0.5)

        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                img = static.data("img", [1, 1, 4, 4], "float32")
                out = static.nn.conv2d(img, 2, 3, padding=1, bias_attr=Attr())
            res = static.Executor().run(
                prog, feed={"img": np.zeros((1, 1, 4, 4), "float32")},
                fetch_list=[out])
            np.testing.assert_allclose(res[0], 0.5, atol=1e-6)
        finally:
            paddle.disable_static()
