"""Benchmark: Llama decoder train-step throughput on the available device.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Metric of record (BASELINE.json): tokens/sec/chip on a Llama-2-style decoder.
A single TPU v5 lite chip cannot hold 7B for training, so the bench runs
1.59B params at seq 4096 — the benchmark-of-record config since round 3
(kept for cross-round continuity; the measured single-chip ceiling is
2.067B, RESULTS.md "single-chip wall") — using the reduced-footprint
optimizer (int8 block-
quantized moments via the fused Pallas update, master-weight-free bf16
params with stochastic rounding; ~4 bytes/param of state), scan-over-layers
and activation recompute. ``vs_baseline`` is
achieved-MFU / 0.45 (the A100-class MFU target recorded in BASELINE.md —
the reference published no numbers).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def smoke() -> None:
    """On-chip regression surface beyond the headline number: run every
    example entry point (the five BASELINE configs) on the real device and
    report one JSON line. ``python bench.py --smoke``."""
    import subprocess

    root = os.path.dirname(os.path.abspath(__file__))
    cases = [
        ("train_resnet.py", ["--steps", "2", "--batch", "8",
                             "--image-size", "32", "--arch", "resnet18"]),
        ("finetune_bert.py", ["--steps", "2"]),
        ("train_ppyoloe.py", ["--steps", "1", "--image-size", "64"]),
        ("train_llama_hybrid.py", ["--dp", "1", "--mp", "1", "--steps", "2"]),
        ("train_deepfm.py", ["--steps", "2", "--batch", "32"]),
    ]
    env = dict(os.environ)
    env.pop("PADDLE_PLATFORM", None)  # run on whatever the real device is
    results = {}
    ok = True
    for script, args in cases:
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, os.path.join(root, "examples", script),
                 *args],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=root)
            passed = out.returncode == 0 and "loss" in out.stdout
        except subprocess.TimeoutExpired:
            out = None
            passed = False
        ok = ok and passed
        results[script] = {"ok": passed,
                           "secs": round(time.perf_counter() - t0, 1)}
        if not passed:
            results[script]["tail"] = "timeout" if out is None else \
                (out.stdout + out.stderr)[-400:]
    print(json.dumps({"metric": "examples_on_chip_smoke",
                      "value": sum(r["ok"] for r in results.values()),
                      "unit": "examples_passing", "vs_baseline": 1.0 if ok
                      else 0.0, "detail": results}))
    sys.exit(0 if ok else 1)


def _read_lkg(metric: str) -> dict | None:
    """Read the last-known-good record for ``metric`` from RESULTS.md.

    RESULTS.md carries machine-readable LKG lines of the form
    ``<!-- LKG {"metric": ..., "value": ..., ...} -->`` so the bench can
    defend its own capture: a driver run that lands far below the recorded
    LKG on the same device class is flagged, not silently recorded.
    """
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "RESULTS.md")
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    import re
    best = None
    for m in re.finditer(r"<!--\s*LKG\s+(\{.*?\})\s*-->", text, re.DOTALL):
        try:
            rec = json.loads(m.group(1))
        except json.JSONDecodeError:
            print(f"bench: unreadable LKG record skipped: {m.group(1)[:80]}",
                  file=sys.stderr)
            continue
        if (rec.get("metric") == metric
                and isinstance(rec.get("value"), (int, float))):
            best = rec  # last one in the file wins
    return best


def _anomaly_reasons(tok_per_sec, call_ms, lkg) -> list[str]:
    """Why this run should not stand as a number of record ([] = healthy).

    Two independent signals: landing far below the same-device last-known-
    good (the round-4 capture artifact: recorded MFU 0.163 vs actual 0.615),
    and heavy step-time skew within the run (a relay stall mid-capture)."""
    reasons = []
    if lkg and tok_per_sec < 0.5 * lkg["value"]:
        reasons.append(f"throughput {tok_per_sec:.0f} < 50% of "
                       f"last-known-good {lkg['value']:.0f}")
    p50 = float(np.percentile(call_ms, 50))
    p90 = float(np.percentile(call_ms, 90))
    if p90 > 2.0 * p50:
        reasons.append(f"step-time p90 {p90:.0f}ms > 2x p50 {p50:.0f}ms")
    return reasons


TELEMETRY_FIELDS = ("dispatch.ops_total", "jit.traces_total",
                    "jit.compiles_total", "jit.cache_hits_total",
                    "jit.graph_breaks_total")

# training-under-fire counters (ISSUE 10): the claim of record is that a
# healthy bench run needed NONE of the recovery machinery — every field
# zero. A diff showing nonzero here means the measured run itself
# retried, skipped, rolled back, or tripped the watchdog.
TRAIN_RESILIENCE_FIELDS = ("retries", "restarts", "skipped_batches",
                           "watchdog_trips")

# whole-step capture counters (ISSUE 11): the row of record pins that the
# measured steps actually ran as ONE compiled donated-buffer program —
# hits > 0 with zero bypasses on a healthy run. A run whose every step
# bypassed capture measured the eager debug tier and must read as suspect.
STEP_CAPTURE_FIELDS = ("mode", "hits", "retraces", "bypasses",
                       "donated_bytes")

# tracing overhead (ISSUE 12): the flight recorder is ALWAYS on, so its
# cost on the captured hot path is part of every number of record — the
# row pins the captured-step p50 with tracing off vs flight-recorder-only
# vs fully on, and >2% flight-vs-off delta disqualifies the run.
TRACE_OVERHEAD_FIELDS = ("step_ms_p50_off", "step_ms_p50_flight",
                         "step_ms_p50_on", "flight_overhead_pct",
                         "on_overhead_pct")
_TRACE_OVERHEAD_MAX_PCT = 2.0


def _counter_total(snap: dict, name: str) -> int:
    """Sum a counter family out of a snapshot: unlabeled families are a
    plain number, labeled ones a {'k=v': value} dict."""
    v = snap.get(name, 0)
    if isinstance(v, dict):
        return int(sum(v.values()))
    return int(v)


def _train_resilience_detail(snap: dict) -> dict:
    """Select the train.* recovery counters; schema pinned by
    TRAIN_RESILIENCE_FIELDS (all fields always present, zeros included)."""
    return {
        "retries": _counter_total(snap, "train.retries_total"),
        "restarts": _counter_total(snap, "train.restarts_total"),
        "skipped_batches": _counter_total(snap,
                                          "train.skipped_batches_total"),
        "watchdog_trips": _counter_total(snap,
                                         "train.watchdog_trips_total"),
    }


def _step_capture_detail(snap: dict, mode: str) -> dict:
    """Select the train.capture_* counters; schema pinned by
    STEP_CAPTURE_FIELDS (all fields always present, zeros included)."""
    return {
        "mode": mode,
        "hits": _counter_total(snap, "train.capture_hits_total"),
        "retraces": _counter_total(snap, "train.capture_retraces_total"),
        "bypasses": _counter_total(snap, "train.capture_bypasses_total"),
        "donated_bytes": int(snap.get("train.capture_donated_bytes", 0)),
    }


def _capture_suspect_reasons(cap: dict) -> list[str]:
    """Why the capture block disqualifies this run ([] = healthy): a run
    whose steps ran the per-op eager tier — capture off (e.g. the test
    suite's PADDLE_TPU_STEP_CAPTURE=off inherited into the bench env), or
    every step bypassed — measured a structurally different (and ~8x
    slower) program than the number of record claims."""
    if cap["mode"] == "off":
        return ["step capture disabled (PADDLE_TPU_STEP_CAPTURE=off): the "
                "run measured the eager debug tier, not the compiled step"]
    if cap["hits"] == 0 and cap["bypasses"] > 0:
        return [f"step capture enabled but all {cap['bypasses']} steps "
                "bypassed to the eager tier (train.capture_bypasses_total "
                "has the reasons)"]
    return []


def _trace_overhead_detail(off_p50: float, flight_p50: float,
                           on_p50: float) -> dict:
    """Build the pinned trace_overhead block (schema:
    TRACE_OVERHEAD_FIELDS) from the three measured per-step p50s (ms)."""
    def pct(x: float) -> float:
        return round(100.0 * (x - off_p50) / off_p50, 2) if off_p50 else 0.0

    return {
        "step_ms_p50_off": round(off_p50, 3),
        "step_ms_p50_flight": round(flight_p50, 3),
        "step_ms_p50_on": round(on_p50, 3),
        "flight_overhead_pct": pct(flight_p50),
        "on_overhead_pct": pct(on_p50),
    }


def _trace_suspect_reasons(block: dict) -> list[str]:
    """Why the trace_overhead block disqualifies this run ([] = healthy):
    the always-on flight recorder must be near-free on the captured hot
    path — a >2% p50 delta vs tracing-off means every number of record is
    quietly paying for observability. (Full 'on' mode is an opt-in debug
    tier; its cost is reported but not gated.)"""
    if block["flight_overhead_pct"] > _TRACE_OVERHEAD_MAX_PCT:
        return [f"flight-recorder-only tracing cost "
                f"{block['flight_overhead_pct']}% of the off-mode step "
                f"p50 (> {_TRACE_OVERHEAD_MAX_PCT}% budget)"]
    return []


def _telemetry_detail(snap: dict) -> dict:
    """Select the bench-relevant counters out of an observability snapshot.

    Every field in ``TELEMETRY_FIELDS`` is always present (0 when never
    bumped) so BENCH JSON rows stay schema-stable across rounds."""
    return {k: int(snap.get(k, 0)) for k in TELEMETRY_FIELDS}


# program cost accounting (ISSUE 16): the row of record carries XLA's own
# cost/memory analysis of the measured step program — flops and bytes as
# the compiler modeled them, the modeled MFU recomputed from the measured
# per-call step time, and the HBM ledger's peak/headroom. model_source
# records whether XLA's cost model or the analytic flops counter produced
# the figure; an all-null cost block means the registry never saw the
# measured program and the MFU claim has no model behind it.
COST_FIELDS = ("model_source", "step_flops", "step_bytes", "mfu_modeled",
               "peak_hbm_bytes", "hbm_headroom_bytes")


def _cost_detail(doc: dict, analytic_step_flops: float,
                 step_seconds: float, peak_flops: float) -> dict:
    """Build the pinned cost block (schema: COST_FIELDS) from one
    ``cost.debug_doc()`` snapshot plus the measured per-CALL seconds of
    the captured step program (the same program the train.step record
    describes — both cover ``scan_k`` scanned steps).

    Prefers the XLA-measured train.step record; falls back to the analytic
    estimate (model_source="analytic") when the compiler returned no cost
    model, and to all-null (model_source="none") when the registry never
    saw the step program at all."""
    rec = None
    for r in doc.get("records", []):
        if r.get("site") == "train.step":
            rec = r
            break
    flops = rec.get("flops") if rec else None
    nbytes = rec.get("bytes_accessed") if rec else None
    source = rec.get("model_source") if rec else None
    if flops is None and analytic_step_flops:
        flops, source = float(analytic_step_flops), "analytic"
    mfu_modeled = None
    if flops and step_seconds and peak_flops:
        mfu_modeled = round(flops / (step_seconds * peak_flops), 4)
    hbm = doc.get("hbm") or {}
    out = {
        "model_source": source or "none",
        "step_flops": flops,
        "step_bytes": nbytes,
        "mfu_modeled": mfu_modeled,
        "peak_hbm_bytes": hbm.get("peak_hbm_bytes"),
        "hbm_headroom_bytes": hbm.get("headroom_bytes"),
    }
    assert set(out) == set(COST_FIELDS)
    return out


def _cost_suspect_reasons(block: dict) -> list[str]:
    """Why the cost block disqualifies this run ([] = healthy): an
    entirely empty cost accounting means the registry never captured the
    measured program AND the analytic fallback was unavailable — the MFU
    of record has no cost model behind it."""
    if (block["step_flops"] is None and block["step_bytes"] is None
            and block["peak_hbm_bytes"] is None):
        return ["cost accounting empty: no program record and no analytic "
                "fallback (PADDLE_TPU_COST=off inherited into the bench "
                "env?)"]
    return []


def _dispatch_probe(jax) -> float:
    """Median round-trip latency (ms) of a trivial compiled dispatch.

    Fingerprints the attachment mode: a directly-attached chip measures
    ~0.1-1 ms, the relay this environment tunnels through ~20 ms, and a
    contended/degraded relay far more. Recorded in the JSON so an anomalous
    capture carries its own explanation."""
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    f(x).block_until_ready()  # compile
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(ts))


def main() -> None:
    # persistent XLA compilation cache (ROADMAP 3b): default a stable local
    # dir so the row of record carries cold vs warm compile seconds — set
    # BEFORE the paddle import, which wires jax's cache dir at init
    import tempfile
    os.environ.setdefault(
        "PADDLE_TPU_COMPILE_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "paddle_tpu_xla_cache"))

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    # dispatch/compile telemetry rides along in the JSON: per-op dispatch
    # cost inside the timed loop is one counter bump + histogram insert,
    # noise next to the ~seconds-scale compiled steps being measured
    obs.enable()

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        # 1.59B params at batch 6 on one 16GB v5e — enabled by int8 m/v
        # (fused Pallas update) + master-free bf16 AdamW (~4 B/param state)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2560,
                          intermediate_size=6912, num_hidden_layers=18,
                          num_attention_heads=20, num_key_value_heads=20,
                          max_position_embeddings=4096,
                          scan_layers=True, recompute=True)
        # int8 moments (round 5: fused Pallas update) free ~3GB vs bf16
        # state, so batch 6 now fits — the measured sweet spot (b3 0.6123,
        # b5 0.6202, b6 0.6306, b8 OOM); 24 steps = 6 timed calls, enough
        # samples for honest p50/p90
        batch, seq, steps, scan_k = 6, 4096, 24, 4
        peak_flops = 197e12  # v5e bf16 peak per chip
    else:  # CPU smoke config so the bench always runs
        cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=4,
                               kv_heads=4, inter=256, max_pos=256)
        batch, seq, steps, scan_k = 4, 128, 4, 2
        peak_flops = 1e12

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    # big scan-stacked params: on TPU the int8-state update runs as ONE
    # fused Pallas kernel per param (ops/q8_adam_pallas.py); the
    # master-free bf16 write-back uses stochastic rounding
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 use_multi_tensor=not on_tpu,
                                 moment_dtype="int8" if on_tpu else "float32",
                                 use_master_weights=False if on_tpu else None)
    if on_tpu:
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16", master_weight=False)

    # whole-step static capture (ISSUE 11): the train step — fwd, bwd,
    # optimizer update — is ONE donated-buffer compiled program, scanned
    # over scan_k steps per call (the standard TPU trainer pattern —
    # amortizes per-dispatch overhead); the body fn stays a plain per-step
    # train step, and train.capture_* counters ride into the row of record
    cap_mode = paddle.core.step_capture.mode()

    def train_step_body(ids):
        with paddle.amp.auto_cast(enable=on_tpu, level="O2", dtype="bfloat16"):
            loss, _ = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step = paddle.jit.capture_step(train_step_body,
                                         iters_per_call=scan_k)

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size,
                                        (scan_k, batch, seq), dtype=np.int32))

    # warmup / compile (twice: a second call would catch any lazy-state
    # retrace, so the timed loop never eats a recompile). The first call's
    # wall time is the compile+first-run split the JSON reports.
    t0 = time.perf_counter()
    loss = train_step(ids)
    _ = np.asarray(loss._data)
    compile_s = time.perf_counter() - t0
    loss = train_step(ids)
    _ = np.asarray(loss._data)
    steps_run = (steps // scan_k) * scan_k  # what the timed loop executes

    def timed_loop():
        """One timed pass; returns (tok/s, per-call ms list, final loss)."""
        call_ms = []
        nonlocal_loss = None
        t_all = time.perf_counter()
        for _ in range(steps_run // scan_k):
            t0 = time.perf_counter()
            nonlocal_loss = train_step(ids)
            _ = np.asarray(nonlocal_loss._data)  # per-call sync: honest
            call_ms.append((time.perf_counter() - t0) * 1e3)
        dt = time.perf_counter() - t_all
        return (batch * seq * steps_run) / dt, call_ms, nonlocal_loss

    metric = "llama_train_tokens_per_sec_per_chip"
    lkg = _read_lkg(metric) if on_tpu else None
    probe_ms = _dispatch_probe(jax)

    # the throughput guard only makes sense against the same device class;
    # device_kind is the stable name ("TPU v5 lite"), str(dev) varies by
    # platform/runtime
    dev_names = f"{dev} {getattr(dev, 'device_kind', '')}"
    if lkg and lkg.get("device") and lkg["device"] not in dev_names:
        lkg = None

    def anomalous(tok_per_sec, call_ms):
        return _anomaly_reasons(tok_per_sec, call_ms, lkg)

    tok_per_sec, call_ms, loss = timed_loop()
    # CPU runs are CI smoke on shared cores — variance there is expected
    # and not a capture-integrity signal
    suspect_reasons = anomalous(tok_per_sec, call_ms) if on_tpu else []
    retried = False
    if suspect_reasons:
        # Self-heal once: relay attachment hiccups are transient; a second
        # pass over the SAME compiled executable either recovers or confirms.
        retried = True
        tok2, call2, loss2 = timed_loop()
        if tok2 > tok_per_sec:
            tok_per_sec, call_ms, loss = tok2, call2, loss2
        suspect_reasons = anomalous(tok_per_sec, call_ms)

    loss = loss[-1]  # last step's loss for reporting
    flops_per_token = model.flops_per_token(seq)
    mfu = tok_per_sec * flops_per_token / peak_flops

    # warm-start compile: drop the in-memory executable cache and rebuild
    # the SAME program — the re-lower now deserializes from the persistent
    # compilation cache instead of re-running XLA, which is what a fleet
    # rollout / crash-restart (PR 8/10 recovery) pays. compile_s stays the
    # cold number of record; the cold-vs-warm delta is the pinned win.
    compile_warm_s = None
    if os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR"):
        jax.clear_caches()
        t0 = time.perf_counter()
        _w = train_step(ids)
        _ = np.asarray(_w._data)
        compile_warm_s = round(time.perf_counter() - t0, 1)

    # tracing overhead (ISSUE 12): re-run the SAME compiled executable a
    # few calls per trace mode — spans/ring writes are host-side only, so
    # no retrace — and pin the per-step p50 deltas. Restore the ambient
    # mode afterwards so the block never perturbs later measurement.
    from paddle_tpu.observability import trace as _trace_mod

    def _p50_under_mode(m: str) -> float:
        _trace_mod.set_mode(m)
        ms = []
        for _ in range(max(3, steps_run // scan_k)):
            t0 = time.perf_counter()
            l_ = train_step(ids)
            _ = np.asarray(l_._data)
            ms.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(ms, 50)) / scan_k

    ambient_trace_mode = _trace_mod.mode()
    try:
        trace_block = _trace_overhead_detail(
            _p50_under_mode("off"), _p50_under_mode("flight"),
            _p50_under_mode("on"))
    finally:
        _trace_mod.set_mode(ambient_trace_mode)
    # CPU runs are shared-core CI smoke: sub-ms jitter there routinely
    # exceeds 2% and is not a capture-integrity signal
    if on_tpu:
        suspect_reasons = suspect_reasons + _trace_suspect_reasons(
            trace_block)

    out = {
        "metric": metric,
        "value": round(tok_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "detail": {
            "device": str(dev), "params": model.num_params(),
            "hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
            "batch": batch, "seq": seq, "steps": steps_run,
            "mfu": round(mfu, 4), "final_loss": round(float(loss), 4),
            "step_ms_p50": round(float(np.percentile(call_ms, 50)) / scan_k, 1),
            "step_ms_p90": round(float(np.percentile(call_ms, 90)) / scan_k, 1),
            "compile_s": round(compile_s, 1),
            "compile_warm_s": compile_warm_s,
            "dispatch_probe_ms": round(probe_ms, 2),
            "retried": retried,
        },
    }
    # one snapshot feeds every counter block: the row of record must not
    # mix two points in time (schemas pinned by TRAIN_RESILIENCE_FIELDS /
    # STEP_CAPTURE_FIELDS in test_bench_selfdefense)
    snap = obs.snapshot()
    out["detail"]["telemetry"] = _telemetry_detail(snap)
    out["detail"]["train_resilience"] = _train_resilience_detail(snap)
    cap_detail = _step_capture_detail(snap, cap_mode)
    out["detail"]["step_capture"] = cap_detail
    out["detail"]["trace_overhead"] = trace_block
    # cost accounting (ISSUE 16): one debug_doc() snapshot, same point in
    # time as `snap`; the step program's record joins the measured per-call
    # p50 into the modeled MFU (both cover one scan_k-step call)
    from paddle_tpu.observability import cost as _cost_mod
    cost_detail = _cost_detail(
        _cost_mod.debug_doc(),
        flops_per_token * batch * seq * scan_k,
        float(np.percentile(call_ms, 50)) / 1e3,
        peak_flops)
    out["detail"]["cost"] = cost_detail
    suspect_reasons = suspect_reasons + _capture_suspect_reasons(cap_detail)
    suspect_reasons = suspect_reasons + _cost_suspect_reasons(cost_detail)
    if suspect_reasons:
        out["suspect"] = True
        out["detail"]["suspect_reasons"] = suspect_reasons
        if lkg:
            out["detail"]["last_known_good"] = lkg
    print(json.dumps(out))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        main()
