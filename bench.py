"""Benchmark: Llama decoder train-step throughput on the available device.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Metric of record (BASELINE.json): tokens/sec/chip on a Llama-2-style decoder.
A single TPU v5 lite chip cannot hold 7B for training, so the bench runs the
LARGEST Llama that fits — 1.59B params at seq 4096 (the north-star regime's
per-chip story) — using the reduced-footprint optimizer (bf16 moments,
master-weight-free bf16 params with stochastic rounding; 6 bytes/param of
state), scan-over-layers and activation recompute. ``vs_baseline`` is
achieved-MFU / 0.45 (the A100-class MFU target recorded in BASELINE.md —
the reference published no numbers).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def smoke() -> None:
    """On-chip regression surface beyond the headline number: run every
    example entry point (the five BASELINE configs) on the real device and
    report one JSON line. ``python bench.py --smoke``."""
    import subprocess

    root = os.path.dirname(os.path.abspath(__file__))
    cases = [
        ("train_resnet.py", ["--steps", "2", "--batch", "8",
                             "--image-size", "32", "--arch", "resnet18"]),
        ("finetune_bert.py", ["--steps", "2"]),
        ("train_ppyoloe.py", ["--steps", "1", "--image-size", "64"]),
        ("train_llama_hybrid.py", ["--dp", "1", "--mp", "1", "--steps", "2"]),
        ("train_deepfm.py", ["--steps", "2", "--batch", "32"]),
    ]
    env = dict(os.environ)
    env.pop("PADDLE_PLATFORM", None)  # run on whatever the real device is
    results = {}
    ok = True
    for script, args in cases:
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, os.path.join(root, "examples", script),
                 *args],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=root)
            passed = out.returncode == 0 and "loss" in out.stdout
        except subprocess.TimeoutExpired:
            out = None
            passed = False
        ok = ok and passed
        results[script] = {"ok": passed,
                           "secs": round(time.perf_counter() - t0, 1)}
        if not passed:
            results[script]["tail"] = "timeout" if out is None else \
                (out.stdout + out.stderr)[-400:]
    print(json.dumps({"metric": "examples_on_chip_smoke",
                      "value": sum(r["ok"] for r in results.values()),
                      "unit": "examples_passing", "vs_baseline": 1.0 if ok
                      else 0.0, "detail": results}))
    sys.exit(0 if ok else 1)


def main() -> None:
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        # 1.59B params: the largest config that trains on one 16GB v5e —
        # enabled by bf16 m/v + master-free bf16 AdamW (6 B/param state)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2560,
                          intermediate_size=6912, num_hidden_layers=18,
                          num_attention_heads=20, num_key_value_heads=20,
                          max_position_embeddings=4096,
                          scan_layers=True, recompute=True)
        # seq 4096 / bs 3 is the measured MFU sweet spot for this model
        # (RESULTS.md north-star table: 0.614 vs 0.595 at seq 2048/bs 6)
        batch, seq, steps, scan_k = 3, 4096, 16, 4
        peak_flops = 197e12  # v5e bf16 peak per chip
    else:  # CPU smoke config so the bench always runs
        cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=4,
                               kv_heads=4, inter=256, max_pos=256)
        batch, seq, steps, scan_k = 4, 128, 4, 2
        peak_flops = 1e12

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    # big scan-stacked params: the per-param update path is the fused one
    # under whole-step jit (XLA folds it in); bf16 state halves optimizer
    # HBM traffic and the master-free write-back uses stochastic rounding
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 use_multi_tensor=not on_tpu,
                                 moment_dtype="bfloat16" if on_tpu else "float32",
                                 use_master_weights=False if on_tpu else None)
    if on_tpu:
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16", master_weight=False)

    # scan-over-steps: ONE compiled call runs scan_k optimizer steps (the
    # standard TPU trainer pattern — amortizes per-dispatch overhead); the
    # body fn stays a plain per-step train step
    @paddle.jit.to_static(iters_per_call=scan_k)
    def train_step(ids):
        with paddle.amp.auto_cast(enable=on_tpu, level="O2", dtype="bfloat16"):
            loss, _ = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size,
                                        (scan_k, batch, seq), dtype=np.int32))

    # warmup / compile (twice: a second call would catch any lazy-state
    # retrace, so the timed loop never eats a recompile)
    loss = train_step(ids)
    _ = np.asarray(loss._data)
    loss = train_step(ids)
    _ = np.asarray(loss._data)
    steps_run = (steps // scan_k) * scan_k  # what the timed loop executes
    t0 = time.perf_counter()
    for _ in range(steps_run // scan_k):
        loss = train_step(ids)
    _ = np.asarray(loss._data)  # sync
    dt = time.perf_counter() - t0
    loss = loss[-1]  # last step's loss for reporting

    tokens = batch * seq * steps_run
    tok_per_sec = tokens / dt
    flops_per_token = model.flops_per_token(seq)
    mfu = tok_per_sec * flops_per_token / peak_flops

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "detail": {
            "device": str(dev), "params": model.num_params(),
            "hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
            "batch": batch, "seq": seq, "steps": steps_run,
            "mfu": round(mfu, 4), "final_loss": round(float(loss), 4),
        },
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        main()
