"""BASELINE config #2: ERNIE-3.0 fine-tune throughput on the real chip.

The reference published no number (BASELINE.md); this records ours:
sequence-classification fine-tune steps/sec and examples/sec for the
ernie3_medium trunk (6 layers, h=768) in bf16 AMP O2 under whole-step
to_static.

Run: python benchmarks/bench_ernie.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.ernie import ErnieConfig, ErnieForSequenceClassification

B, L, STEPS = 32, 128, 30


def main():
    import jax

    on_tpu = jax.devices()[0].platform != "cpu"
    paddle.seed(0)
    cfg = ErnieConfig.ernie3_medium() if on_tpu else ErnieConfig.tiny()
    model = ErnieForSequenceClassification(cfg, num_classes=2)
    opt = paddle.optimizer.AdamW(learning_rate=5e-5, weight_decay=0.01,
                                 parameters=model.parameters(),
                                 use_multi_tensor=True)
    if on_tpu:
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")

    @paddle.jit.to_static
    def step(ids, label):
        with paddle.amp.auto_cast(enable=on_tpu, level="O2",
                                  dtype="bfloat16"):
            loss, _ = model(ids, labels=label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (B, L), dtype=np.int32))
    label = paddle.to_tensor(rng.integers(0, 2, (B,)).astype(np.int64))

    for _ in range(3):  # compile + cache warm
        loss = step(ids, label)
    _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss = step(ids, label)
    final = float(loss)
    dt = (time.perf_counter() - t0) / STEPS
    print(f"device: {jax.devices()[0]}")
    print(f"ernie3_medium fine-tune: {1.0 / dt:.1f} steps/s, "
          f"{B / dt:,.0f} examples/s, {B * L / dt:,.0f} tokens/s "
          f"(batch {B}, seq {L}, final loss {final:.4f})")


if __name__ == "__main__":
    main()
