"""Eager-dispatch overhead microbenchmark (SURVEY.md §7 hard-part #2).

Measures fwd+bwd through the eager tape (apply() -> vjp record, one device
dispatch per op) three ways over the SAME repeated-signature chain:

* ``cold_ms``   — compiled-op cache disabled (the seed dispatch path:
  un-jitted fn + a fresh ``jax.vjp`` trace per op per call);
* ``cached_ms`` — signature-keyed compiled-op cache enabled and warm
  (``core/dispatch_cache.py``): each op dispatches to a cached jitted
  executable, the tape reuses the cached vjp;
* ``compiled_fwd_bwd_ms`` — the whole chain under ``to_static`` (the
  upper bound whole-program compilation buys).

``speedup_x = cold_ms / cached_ms`` is the acceptance metric (ISSUE 2
target: >= 3x); ``hit_rate`` comes from the cache's own counters and
pins that the measurement actually exercised the hot path.

``--captured-step`` (ISSUE 11) adds the whole-step capture leg: the same
fwd+bwd chain through ``paddle.jit.capture_step`` — ``captured_step_ms``
per step and ``captured_dispatches_per_step`` (the single compiled
program call, plus any eager op dispatch that leaked around it during a
warm step; the expectation pinned in tests is exactly 1).

Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_ITERS = 200          # loop iterations; each runs 2 elementwise ops
OPS = 2 * N_ITERS      # elementwise ops per forward chain (+ final sum)

# schema of the JSON row, pinned by tests/test_bench_selfdefense.py
# (captured_* fields are null unless --captured-step ran the leg)
RESULT_FIELDS = (
    "benchmark", "chain_elementwise_ops",
    "cold_ms", "cached_ms", "speedup_x", "hit_rate",
    "cold_us_per_op", "cached_us_per_op",
    "compiled_fwd_bwd_ms", "device",
    "captured_step_ms", "captured_dispatches_per_step",
    "captured_speedup_x",
)


def _captured_leg(paddle, jax, x, chain, reps: int):
    """Time the chain as ONE captured (donated) program and count what a
    warm step dispatches: 1 program call + however many eager op
    dispatches leaked around it (expected: none)."""
    import time

    from paddle_tpu import observability as obs

    def step(v):
        loss = chain(v)
        loss.backward()
        return loss

    cap = paddle.jit.capture_step(step)
    cap(x)                       # trace + compile
    x.clear_grad()
    if cap.stats["retraces"] == 0:
        # capture bypassed (PADDLE_TPU_STEP_CAPTURE=off inherited from the
        # environment, or a live seam): there is no captured leg to
        # measure — report nulls rather than losing the whole row
        print(f"bench_eager_dispatch: captured-step leg skipped "
              f"(bypasses: {cap.stats['bypasses']})", file=sys.stderr)
        return None, None
    obs.enable()
    before = obs.snapshot().get("dispatch.ops_total", 0)
    cap(x)                       # one warm step under the op-dispatch hook
    jax.block_until_ready(x.grad._data)
    eager_ops = int(obs.snapshot().get("dispatch.ops_total", 0) - before)
    obs.disable()
    x.clear_grad()
    t0 = time.perf_counter()
    for _ in range(reps * 10):
        cap(x)
    jax.block_until_ready(x.grad._data)
    dt = (time.perf_counter() - t0) / (reps * 10)
    x.clear_grad()
    if cap.stats["hits"] < reps * 10:
        # the timed loop didn't actually run warm captured steps
        # (mid-run bypass): the measurement is not the captured tier
        print(f"bench_eager_dispatch: captured-step leg invalid "
              f"({cap.stats}); reporting nulls", file=sys.stderr)
        return None, None
    return dt, 1 + eager_ops


def main() -> None:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.core import dispatch_cache as dcache

    x = paddle.to_tensor(np.random.randn(64, 64).astype("float32"),
                         stop_gradient=False)

    def chain(v):
        for _ in range(N_ITERS):
            v = v * 1.0001 + 0.001
        return v.sum()

    def eager_step():
        loss = chain(x)
        loss.backward()
        jax.block_until_ready(x.grad._data)  # wait on the actual output
        x.clear_grad()

    def time_steps(reps: int) -> float:
        eager_step()  # warm-up covers backward-path setup too
        t0 = time.perf_counter()
        for _ in range(reps):
            eager_step()
        return (time.perf_counter() - t0) / reps

    reps = 5
    dcache.configure(enabled=False)
    cold_dt = time_steps(reps)

    dcache.configure(enabled=True, warmup=2)
    dcache.cache_clear()
    eager_step()  # sighting 1: cold misses
    eager_step()  # sighting 2: per-signature compiles
    dcache.stats_clear()  # count hit_rate over the timed (warm) reps only
    cached_dt = time_steps(reps)
    info = dcache.cache_info()

    # compiled fwd+bwd (symmetric with the eager measurement)
    @paddle.jit.to_static
    def static_step(v):
        loss = chain(v)
        loss.backward()
        return loss

    static_step(x)  # compile
    x.clear_grad()
    t0 = time.perf_counter()
    for _ in range(reps * 10):
        static_step(x)
    jax.block_until_ready(x.grad._data)
    static_dt = (time.perf_counter() - t0) / (reps * 10)
    x.clear_grad()

    captured_dt = captured_dispatches = None
    if "--captured-step" in sys.argv:
        captured_dt, captured_dispatches = _captured_leg(paddle, jax, x,
                                                         chain, reps)

    row = {
        "benchmark": "eager_dispatch",
        "chain_elementwise_ops": OPS,
        "cold_ms": round(cold_dt * 1e3, 2),
        "cached_ms": round(cached_dt * 1e3, 2),
        "speedup_x": round(cold_dt / cached_dt, 2),
        "hit_rate": round(info["hit_rate"], 4),
        "cold_us_per_op": round(1e6 * cold_dt / OPS, 1),
        "cached_us_per_op": round(1e6 * cached_dt / OPS, 1),
        "compiled_fwd_bwd_ms": round(static_dt * 1e3, 3),
        "device": str(jax.devices()[0]),
        "captured_step_ms": None if captured_dt is None
        else round(captured_dt * 1e3, 3),
        "captured_dispatches_per_step": captured_dispatches,
        "captured_speedup_x": None if captured_dt is None
        else round(cold_dt / captured_dt, 2),
    }
    assert set(row) == set(RESULT_FIELDS)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
