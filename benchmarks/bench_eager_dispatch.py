"""Eager-dispatch overhead microbenchmark (SURVEY.md §7 hard-part #2).

Measures fwd+bwd through the eager tape (apply() -> vjp record, one device
dispatch per op) vs the SAME fwd+bwd chain compiled under ``to_static`` —
quantifying the Python dispatch cost the reference buries in codegen'd C++
ad_funcs, and the factor whole-step compilation buys back. Both paths run
forward AND backward; timing blocks on the produced gradient.

Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_ITERS = 200          # loop iterations; each runs 2 elementwise ops
OPS = 2 * N_ITERS      # elementwise ops per forward chain (+ final sum)


def main() -> None:
    import jax

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.random.randn(64, 64).astype("float32"),
                         stop_gradient=False)

    def chain(v):
        for _ in range(N_ITERS):
            v = v * 1.0001 + 0.001
        return v.sum()

    def eager_step():
        loss = chain(x)
        loss.backward()
        jax.block_until_ready(x.grad._data)  # wait on the actual output
        x.clear_grad()

    eager_step()  # warm-up covers backward-path setup too
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        eager_step()
    eager_dt = (time.perf_counter() - t0) / reps

    # compiled fwd+bwd (symmetric with the eager measurement)
    @paddle.jit.to_static
    def static_step(v):
        loss = chain(v)
        loss.backward()
        return loss

    static_step(x)  # compile
    x.clear_grad()
    t0 = time.perf_counter()
    for _ in range(reps * 10):
        static_step(x)
    jax.block_until_ready(x.grad._data)
    static_dt = (time.perf_counter() - t0) / (reps * 10)
    x.clear_grad()

    print(json.dumps({
        "benchmark": "eager_dispatch",
        "chain_elementwise_ops": OPS,
        "eager_fwd_bwd_ms": round(eager_dt * 1e3, 2),
        "eager_us_per_op": round(1e6 * eager_dt / OPS, 1),
        "compiled_fwd_bwd_ms": round(static_dt * 1e3, 3),
        "eager_vs_compiled_x": round(eager_dt / static_dt, 1),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
