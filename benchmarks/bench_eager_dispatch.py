"""Eager-dispatch overhead microbenchmark (SURVEY.md §7 hard-part #2).

Measures fwd+bwd through the eager tape (apply() -> vjp record, one device
dispatch per op) three ways over the SAME repeated-signature chain:

* ``cold_ms``   — compiled-op cache disabled (the seed dispatch path:
  un-jitted fn + a fresh ``jax.vjp`` trace per op per call);
* ``cached_ms`` — signature-keyed compiled-op cache enabled and warm
  (``core/dispatch_cache.py``): each op dispatches to a cached jitted
  executable, the tape reuses the cached vjp;
* ``compiled_fwd_bwd_ms`` — the whole chain under ``to_static`` (the
  upper bound whole-program compilation buys).

``speedup_x = cold_ms / cached_ms`` is the acceptance metric (ISSUE 2
target: >= 3x); ``hit_rate`` comes from the cache's own counters and
pins that the measurement actually exercised the hot path.

Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_ITERS = 200          # loop iterations; each runs 2 elementwise ops
OPS = 2 * N_ITERS      # elementwise ops per forward chain (+ final sum)

# schema of the JSON row, pinned by tests/test_bench_selfdefense.py
RESULT_FIELDS = (
    "benchmark", "chain_elementwise_ops",
    "cold_ms", "cached_ms", "speedup_x", "hit_rate",
    "cold_us_per_op", "cached_us_per_op",
    "compiled_fwd_bwd_ms", "device",
)


def main() -> None:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.core import dispatch_cache as dcache

    x = paddle.to_tensor(np.random.randn(64, 64).astype("float32"),
                         stop_gradient=False)

    def chain(v):
        for _ in range(N_ITERS):
            v = v * 1.0001 + 0.001
        return v.sum()

    def eager_step():
        loss = chain(x)
        loss.backward()
        jax.block_until_ready(x.grad._data)  # wait on the actual output
        x.clear_grad()

    def time_steps(reps: int) -> float:
        eager_step()  # warm-up covers backward-path setup too
        t0 = time.perf_counter()
        for _ in range(reps):
            eager_step()
        return (time.perf_counter() - t0) / reps

    reps = 5
    dcache.configure(enabled=False)
    cold_dt = time_steps(reps)

    dcache.configure(enabled=True, warmup=2)
    dcache.cache_clear()
    eager_step()  # sighting 1: cold misses
    eager_step()  # sighting 2: per-signature compiles
    dcache.stats_clear()  # count hit_rate over the timed (warm) reps only
    cached_dt = time_steps(reps)
    info = dcache.cache_info()

    # compiled fwd+bwd (symmetric with the eager measurement)
    @paddle.jit.to_static
    def static_step(v):
        loss = chain(v)
        loss.backward()
        return loss

    static_step(x)  # compile
    x.clear_grad()
    t0 = time.perf_counter()
    for _ in range(reps * 10):
        static_step(x)
    jax.block_until_ready(x.grad._data)
    static_dt = (time.perf_counter() - t0) / (reps * 10)
    x.clear_grad()

    row = {
        "benchmark": "eager_dispatch",
        "chain_elementwise_ops": OPS,
        "cold_ms": round(cold_dt * 1e3, 2),
        "cached_ms": round(cached_dt * 1e3, 2),
        "speedup_x": round(cold_dt / cached_dt, 2),
        "hit_rate": round(info["hit_rate"], 4),
        "cold_us_per_op": round(1e6 * cold_dt / OPS, 1),
        "cached_us_per_op": round(1e6 * cached_dt / OPS, 1),
        "compiled_fwd_bwd_ms": round(static_dt * 1e3, 3),
        "device": str(jax.devices()[0]),
    }
    assert set(row) == set(RESULT_FIELDS)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
