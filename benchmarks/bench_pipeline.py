"""Pipeline-parallel schedule: activation-memory profile + step time.

Evidence for the GPipe-with-remat schedule choice (SURVEY §7 hard-part 1):
1F1B's advantage over plain GPipe is bounding live activations at O(S)
microbatches instead of O(M). Under XLA, `jax.checkpoint` on the stage body
achieves the same bound inside the scan — only the per-tick boundary
activation rides the carry; block internals are recomputed in backward.
This script measures the compiled backward's temp-buffer footprint with and
without remat (XLA memory_analysis), and the cached step time.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
     python benchmarks/bench_pipeline.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import paddle_tpu as paddle

if len(jax.devices()) < 4:
    # fewer than 4 real chips: 4-device virtual CPU mesh (programmatic pin —
    # env vars are latched by TPU-plugin sitecustomize hooks)
    paddle.device.force_platform("cpu", 4)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet.tpu_pipeline import (pipelined_forward,
                                                       stack_stage_params)

S, M, B, L, D = 4, 8, 4, 128, 256


def main():
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
    rng = np.random.default_rng(0)
    per_stage = [
        {"w1": jnp.asarray(rng.normal(0, 0.02, (D, 4 * D)).astype(np.float32)),
         "w2": jnp.asarray(rng.normal(0, 0.02, (4 * D, D)).astype(np.float32))}
        for _ in range(S)]
    micro = jnp.asarray(rng.normal(0, 1, (M, B, L, D)).astype(np.float32))
    stacked = stack_stage_params(per_stage, mesh, "pp")

    def stage(p, x):
        return jnp.tanh(jnp.tanh(x @ p["w1"]) @ p["w2"]) + x

    rows = {}
    for remat in (True, False):
        def loss(params, mi, _remat=remat):
            out = pipelined_forward(stage, params, mi, mesh, "pp",
                                    remat=_remat)
            return jnp.sum(out ** 2)

        g = jax.jit(jax.grad(loss))
        compiled = g.lower(stacked, micro).compile()
        ma = compiled.memory_analysis()
        g(stacked, micro)  # warm
        t0 = time.perf_counter()
        for _ in range(10):
            jax.block_until_ready(g(stacked, micro))
        dt = (time.perf_counter() - t0) / 10
        rows[remat] = (ma.temp_size_in_bytes / 1e6, dt * 1e3)
        print(f"remat={remat}: temp={rows[remat][0]:.1f}MB "
              f"step={rows[remat][1]:.1f}ms")
    ratio = rows[False][0] / rows[True][0]
    print(f"activation-memory reduction from remat: {ratio:.2f}x "
          f"(S={S}, M={M}: GPipe+remat holds the O(S) boundary activations "
          f"1F1B targets)")


if __name__ == "__main__":
    main()
