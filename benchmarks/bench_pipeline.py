"""Pipeline-parallel schedule: activation-memory profile + step time.

Evidence for the GPipe-with-remat schedule choice (SURVEY §7 hard-part 1):
1F1B's advantage over plain GPipe is bounding live activations at O(S)
microbatches instead of O(M). Under XLA, `jax.checkpoint` on the stage body
achieves the same bound inside the scan — only the per-tick boundary
activation rides the carry; block internals are recomputed in backward.
This script measures the compiled backward's temp-buffer footprint with and
without remat (XLA memory_analysis), and the cached step time.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
     python benchmarks/bench_pipeline.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import paddle_tpu as paddle

if len(jax.devices()) < 4:
    # fewer than 4 real chips: 4-device virtual CPU mesh (programmatic pin —
    # env vars are latched by TPU-plugin sitecustomize hooks)
    paddle.device.force_platform("cpu", 4)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet.tpu_pipeline import (pipelined_forward,
                                                       stack_stage_params)

S, M, B, L, D = 4, 8, 4, 128, 256


def main():
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
    rng = np.random.default_rng(0)
    per_stage = [
        {"w1": jnp.asarray(rng.normal(0, 0.02, (D, 4 * D)).astype(np.float32)),
         "w2": jnp.asarray(rng.normal(0, 0.02, (4 * D, D)).astype(np.float32))}
        for _ in range(S)]
    micro = jnp.asarray(rng.normal(0, 1, (M, B, L, D)).astype(np.float32))
    stacked = stack_stage_params(per_stage, mesh, "pp")

    def stage(p, x):
        return jnp.tanh(jnp.tanh(x @ p["w1"]) @ p["w2"]) + x

    rows = {}
    for remat in (True, False):
        def loss(params, mi, _remat=remat):
            out = pipelined_forward(stage, params, mi, mesh, "pp",
                                    remat=_remat)
            return jnp.sum(out ** 2)

        g = jax.jit(jax.grad(loss))
        compiled = g.lower(stacked, micro).compile()
        ma = compiled.memory_analysis()
        g(stacked, micro)  # warm
        t0 = time.perf_counter()
        for _ in range(10):
            jax.block_until_ready(g(stacked, micro))
        dt = (time.perf_counter() - t0) / 10
        rows[remat] = (ma.temp_size_in_bytes / 1e6, dt * 1e3)
        print(f"remat={remat}: temp={rows[remat][0]:.1f}MB "
              f"step={rows[remat][1]:.1f}ms")
    ratio = rows[False][0] / rows[True][0]
    print(f"activation-memory reduction from remat: {ratio:.2f}x "
          f"(S={S}, M={M}: GPipe+remat holds the O(S) boundary activations "
          f"1F1B targets)")

    bubble_and_overlap(mesh, per_stage, stacked, stage)
    vpp_comparison(mesh, per_stage, stage)


# ---------------------------------------------------------------------------
# Bubble measurement + ppermute-overlap evidence + VPP refutation
# ---------------------------------------------------------------------------

def bubble_and_overlap(mesh, per_stage, stacked, stage):
    """Measure the fill/drain cost directly.

    In the compiled SPMD scan every stage computes every tick, so the
    pipeline 'bubble' is not idle time — it is WASTED COMPUTE on the
    (S - 1) fill/drain ticks: utilization = M / (M + S - 1), the same
    fraction 1F1B loses to its bubble. Two consequences this measures:

    * per-microbatch time should scale as (M + S - 1) / M — doubling M
      must NOT double step time;
    * vs the grad-accumulation fallback (serial M x full-model fwd+bwd on
      every device, no stage placement) the pipelined step trades the
      (M + S - 1)/M waste for 1/S of the per-device parameter memory and
      compute-per-device.
    """
    import jax

    print("\n-- bubble: per-microbatch tick scaling (model: (M+S-1)/M) --")
    times = {}
    for m in (4, 8, 16):
        micro = jnp.asarray(np.random.default_rng(0).normal(
            0, 1, (m, B, L, D)).astype(np.float32))

        def loss(params, mi):
            return jnp.sum(pipelined_forward(stage, params, mi, mesh,
                                             "pp") ** 2)

        g = jax.jit(jax.grad(loss))
        jax.block_until_ready(g(stacked, micro))
        t0 = time.perf_counter()
        for _ in range(8):
            jax.block_until_ready(g(stacked, micro))
        dt = (time.perf_counter() - t0) / 8
        times[m] = dt
        model = (m + S - 1) / m
        print(f"M={m:2d}: step={dt * 1e3:7.1f}ms  per-mb={dt / m * 1e3:6.1f}ms"
              f"  waste-model={model:.3f}  bubble={(S - 1) / (m + S - 1):.1%}")
    # measured per-microbatch ratio M=4 vs M=16 should approach the model
    meas = (times[4] / 4) / (times[16] / 16)
    model = ((4 + S - 1) / 4) / ((16 + S - 1) / 16)
    print(f"per-mb time ratio M=4/M=16: measured {meas:.2f} "
          f"vs fill/drain model {model:.2f}")

    # serial grad-accumulation fallback: every device runs the full model
    micro = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, (M, B, L, D)).astype(np.float32))

    def serial_loss(params_list, mi):
        total = 0.0
        for k in range(M):
            y = mi[k]
            for p in params_list:
                y = stage(p, y)
            total = total + jnp.sum(y ** 2)
        return total

    gs = jax.jit(jax.grad(serial_loss))
    jax.block_until_ready(gs(per_stage, micro))
    t0 = time.perf_counter()
    for _ in range(8):
        jax.block_until_ready(gs(per_stage, micro))
    dts = (time.perf_counter() - t0) / 8
    print(f"grad-accum fallback (full model on every device): "
          f"{dts * 1e3:.1f}ms vs pipelined {times[M] * 1e3:.1f}ms "
          f"(pipelined also holds only 1/{S} of the params per device)")

    # ppermute/compute overlap evidence: the compiled HLO issues the
    # collective-permute asynchronously (start/done pair with compute
    # scheduled between) — the XLA analogue of NCCL-stream overlap
    def loss8(params, mi):
        return jnp.sum(pipelined_forward(stage, params, mi, mesh, "pp") ** 2)

    txt = jax.jit(jax.grad(loss8)).lower(stacked, micro).compile().as_text()
    starts = txt.count("collective-permute-start")
    dones = txt.count("collective-permute-done")
    async_pairs = starts > 0 and dones > 0
    print(f"CPU HLO: {starts} collective-permute-start / {dones} -done pairs "
          f"({'ASYNC' if async_pairs else 'sync (CPU backend lowers ppermute synchronously)'})")

    # the claim that matters is about the TPU backend: AOT-compile the same
    # scan+ppermute structure against a virtual v5e 2x2 topology (no chips
    # needed) and count the async start/done pairs the TPU scheduler emits
    try:
        from jax.experimental import topologies
        from jax.sharding import Mesh as _Mesh, PartitionSpec as _P
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x2")
        tmesh = _Mesh(np.array(topo.devices).reshape(4), ("pp",))

        def tbody(x):
            w = jnp.zeros((D, D), jnp.bfloat16)

            def tick(c, _):
                y = jnp.tanh(c @ w)
                return jax.lax.ppermute(
                    y, "pp", [(i, (i + 1) % 4) for i in range(4)]), None

            out, _ = jax.lax.scan(tick, x, None, length=8)
            return out

        tf = jax.shard_map(tbody, mesh=tmesh, in_specs=_P("pp"),
                           out_specs=_P("pp"))
        ttxt = jax.jit(tf).lower(jax.ShapeDtypeStruct((4 * B * L, D),
                                                      jnp.bfloat16)) \
            .compile().as_text()
        ts, td = (ttxt.count("collective-permute-start"),
                  ttxt.count("collective-permute-done"))
        print(f"TPU (v5e:2x2 AOT) HLO: {ts} collective-permute-start / "
              f"{td} -done pairs — the TPU scheduler issues the hop "
              f"asynchronously and overlaps it with the next tick's compute")
    except Exception as e:  # AOT topology unavailable in some environments
        print(f"TPU AOT overlap check unavailable: {type(e).__name__}")


def vpp_comparison(mesh, per_stage, stage):
    """Interleaved/VPP schedule, measured in the same SPMD-scan form.

    VPP splits each stage into V chunks to shrink the 1F1B bubble from
    (S-1)/(M+S-1) toward (S-1)/(V*M+S-1) — but that win exists only when
    the bubble is IDLE time a runtime can fill. In the compiled SPMD scan
    there is no idle: every device computes every tick, and splitting
    stages into V chunks deepens the pipeline to S*V positions, growing
    the wasted fill/drain ticks to (S*V - 1) chunk-ticks. Predicted cost
    ratio vs GPipe-scan: (M + S*V - 1) / (V * (M + S - 1) / V) ... i.e.
    (M/V + S - 1/V) / (M + S - 1) per unit work — WORSE for V > 1 at the
    same M. This measures that prediction.
    """
    import jax

    V = 2
    # uniform comparison model: S*V square matmul chunks; GPipe groups V
    # consecutive chunks per stage body, VPP pipelines them individually
    rng = np.random.default_rng(1)
    chunks = [{"w": jnp.asarray(rng.normal(0, 0.05, (D, D)).astype(np.float32))}
              for _ in range(S * V)]

    def chunk_body(p, x):
        return jnp.tanh(x @ p["w"])

    # GPipe view of the same model: stage s = chunks [s*V, (s+1)*V)
    per_stage = [{f"w{v}": chunks[s * V + v]["w"] for v in range(V)}
                 for s in range(S)]

    def stage(p, x):
        for v in range(V):
            x = jnp.tanh(x @ p[f"w{v}"])
        return x

    SV = S * V
    stacked_chunks = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, 0), *chunks)
    from jax.sharding import NamedSharding, PartitionSpec as P
    stacked_chunks = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P("pp", *([None] * (a.ndim - 1))))),
        stacked_chunks)

    micro = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, (M, B, L, D)).astype(np.float32))

    def local_fn(chunks_local, mi):
        # chunks_local leaves: (V, ...) — this device's V chunk slices
        dev = jax.lax.axis_index("pp")
        T = M + SV - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def vary(x):
            try:
                return jax.lax.pcast(x, "pp", to="varying")
            except ValueError:
                return x

        # act[v]: activation entering this device's v-th chunk
        acts = [vary(jnp.zeros_like(mi[0])) for _ in range(V)]
        out0 = vary(jnp.zeros((M,) + mi.shape[1:], mi.dtype))

        def tick(carry, t):
            acts, out_buf = carry
            new_acts = []
            for v in range(V):
                x_in = acts[v]
                if v == 0:
                    mb = jnp.clip(t, 0, M - 1)
                    x_in = jnp.where(dev == 0, mi[mb], x_in)
                y = chunk_body(
                    jax.tree_util.tree_map(lambda a: a[v], chunks_local),
                    x_in)
                new_acts.append(y)
            # last chunk of last device records output
            rec = t - (SV - 1)
            valid = jnp.logical_and(dev == S - 1,
                                    jnp.logical_and(rec >= 0, rec < M))
            out_buf = jax.lax.cond(
                valid,
                lambda ob: jax.lax.dynamic_update_index_in_dim(
                    ob, new_acts[-1], jnp.clip(rec, 0, M - 1), 0),
                lambda ob: ob, out_buf)
            # route: chunk v feeds chunk v+1 locally; last chunk hops devices
            hopped = jax.lax.ppermute(new_acts[-1], "pp", perm)
            carried = [hopped] + new_acts[:-1]
            return (carried, out_buf), None

        (acts, out_buf), _ = jax.lax.scan(tick, (acts, out0),
                                          jnp.arange(M + SV - 1))
        out_buf = jnp.where(dev == S - 1, out_buf, jnp.zeros_like(out_buf))
        return jax.lax.psum(out_buf, "pp")

    n_dims = jax.tree_util.tree_map(
        lambda a: P("pp", *([None] * (a.ndim - 1))), stacked_chunks)
    mapped = jax.shard_map(local_fn, mesh=mesh,
                           in_specs=(n_dims, P()), out_specs=P())

    def vpp_loss(params, mi):
        return jnp.sum(mapped(params, mi) ** 2)

    g = jax.jit(jax.grad(vpp_loss))
    jax.block_until_ready(g(stacked_chunks, micro))
    t0 = time.perf_counter()
    for _ in range(8):
        jax.block_until_ready(g(stacked_chunks, micro))
    dt_vpp = (time.perf_counter() - t0) / 8

    def gpipe_loss(params, mi):
        return jnp.sum(pipelined_forward(stage, params, mi, mesh, "pp") ** 2)

    stacked = stack_stage_params(per_stage, mesh, "pp")
    g2 = jax.jit(jax.grad(gpipe_loss))
    jax.block_until_ready(g2(stacked, micro))
    t0 = time.perf_counter()
    for _ in range(8):
        jax.block_until_ready(g2(stacked, micro))
    dt_gp = (time.perf_counter() - t0) / 8

    # each tick costs one stage-equivalent in both schedules (V chunks of
    # 1/V work vs one full stage body); only the tick counts differ
    pred = (M + SV - 1) / (M + S - 1)
    print(f"\n-- VPP (V={V}) in the SPMD scan: measured {dt_vpp * 1e3:.1f}ms "
          f"vs GPipe-scan {dt_gp * 1e3:.1f}ms "
          f"(ratio {dt_vpp / dt_gp:.2f}, fill/drain model {pred:.2f}) --")
    print("VPP deepens the compiled pipeline without any idle time to "
          "recover; GPipe-scan's waste already equals 1F1B's bubble "
          "fraction (S-1)/(M+S-1) — raise accumulate_steps to shrink it.")


if __name__ == "__main__":
    main()
