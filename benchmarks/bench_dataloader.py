"""Input-pipeline throughput: thread prefetch vs multiprocess workers.

The ResNet config feeds ~2,000 img/s on one chip; a GIL-bound transform
pipeline would starve it. This measures images/sec through DataLoader with
a deliberately CPU-heavy per-sample transform (resize + normalize + HWC->CHW
in numpy) for num_workers = 0 (thread double-buffering) and 4 (spawned
worker processes).

Run: python benchmarks/bench_dataloader.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset

N, H, W = 1024, 96, 96
OUT = 48


class SyntheticImages(Dataset):
    """Raw uint8 images; the transform is the CPU cost being measured."""

    def __init__(self):
        rng = np.random.default_rng(0)
        self._data = rng.integers(0, 255, (N, H, W, 3), np.uint8)

    def __len__(self):
        return N

    def __getitem__(self, i):
        img = self._data[i].astype(np.float32) / 255.0
        # cheap bilinear-ish resize via strided mean pooling + normalize
        k = H // OUT
        img = img.reshape(OUT, k, OUT, k, 3).mean(axis=(1, 3))
        img = (img - 0.45) / 0.22
        for _ in range(3):  # extra arithmetic to emulate augmentation cost
            img = np.tanh(img) * 1.01
        return np.transpose(img, (2, 0, 1)), np.int64(i % 10)


def run(num_workers: int) -> float:
    dl = DataLoader(SyntheticImages(), batch_size=64,
                    num_workers=num_workers, persistent_workers=True)
    # warm epoch (spawn cost excluded from steady-state number)
    for _ in dl:
        pass
    t0 = time.perf_counter()
    seen = 0
    for xb, yb in dl:
        seen += xb.shape[0]
    dt = time.perf_counter() - t0
    if dl._pool is not None:
        dl._pool.shutdown()
        dl._pool = None
    return seen / dt


def main():
    ncpu = os.cpu_count() or 1
    r0 = run(0)
    r4 = run(4)
    print(f"host cores: {ncpu}")
    print(f"num_workers=0 (thread prefetch): {r0:,.0f} img/s")
    print(f"num_workers=4 (processes):       {r4:,.0f} img/s "
          f"({r4 / r0:.2f}x)")
    if ncpu <= 1:
        print("NOTE: single-core host — worker scaling is core-bound; "
              "the number demonstrates overhead parity, not speedup")


if __name__ == "__main__":
    main()
