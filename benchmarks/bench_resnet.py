"""ResNet-50 training/inference throughput (BASELINE config #1).

Usage: python benchmarks/bench_resnet.py [--batch 64] [--steps 10]
Prints one JSON line with images/sec (the PaddleClas-style metric).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--eval", action="store_true", help="inference only")
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    on_tpu = jax.devices()[0].platform != "cpu"
    paddle.seed(0)
    model = paddle.vision.models.resnet50(num_classes=1000)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    if on_tpu:
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.normal(size=(
        args.batch, 3, args.image_size, args.image_size)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 1000, args.batch).astype(np.int64))

    if args.eval:
        model.eval()

        @paddle.jit.to_static
        def step(x):
            with paddle.no_grad(), paddle.amp.auto_cast(enable=on_tpu,
                                                        level="O2"):
                return model(x)
    else:
        @paddle.jit.to_static
        def step(x, y=None):
            with paddle.amp.auto_cast(enable=on_tpu, level="O2"):
                loss = nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

    fargs = (x,) if args.eval else (x, y)
    for _ in range(2):  # compile + post-materialization warmup
        out = step(*fargs)
    np.asarray(out._data if hasattr(out, "_data") else out)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = step(*fargs)
    _ = np.asarray((out._data if hasattr(out, "_data") else out))
    dt = time.perf_counter() - t0

    print(json.dumps({
        "benchmark": "resnet50_" + ("infer" if args.eval else "train"),
        "images_per_sec": round(args.batch * args.steps / dt, 1),
        "batch": args.batch, "image_size": args.image_size,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
