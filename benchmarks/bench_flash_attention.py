"""Flash-attention kernel benchmark: Pallas vs XLA softmax attention.

Usage: python benchmarks/bench_flash_attention.py [--seqs 1024 2048 4096]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, nargs="+",
                    default=[1024, 2048, 4096, 8192])
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    results = []
    for seq in args.seqs:
        q = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(args.batch, seq, args.heads, args.head_dim))
            .astype(np.float32) * 0.1)
        q._set_data(q._data.astype(jnp.bfloat16))
        entry = {"seq": seq}
        for name, flag in (("pallas", "pallas"), ("xla", "xla")):
            paddle.set_flags({"FLAGS_flash_impl": flag})

            @paddle.jit.to_static
            def fwd(q):
                return F.flash_attention(q, q, q, causal=True)

            try:
                out = fwd(q)
                np.asarray(out._data[0, 0, 0, 0])
                t0 = time.perf_counter()
                for _ in range(10):
                    out = fwd(q)
                np.asarray(out._data[0, 0, 0, 0])
                dt = (time.perf_counter() - t0) / 10
                flops = 4 * args.batch * args.heads * seq * seq * \
                    args.head_dim / 2  # causal
                entry[name + "_ms"] = round(dt * 1e3, 2)
                entry[name + "_tflops"] = round(flops / dt / 1e12, 1)
            except Exception as e:  # XLA OOM at long seq is expected
                entry[name + "_ms"] = f"OOM/{type(e).__name__}"

        # masked (padding via segment ids): stays on the flash kernel —
        # round-4 item; previously masked attention fell back to XLA and
        # OOMed at seq 8192
        paddle.set_flags({"FLAGS_flash_impl": "pallas"})
        segs = np.ones((args.batch, seq), np.int32)
        segs[:, -seq // 8:] = 0  # 1/8 padding tail
        qseg = paddle.to_tensor(np.ones((args.batch, seq), np.int32))
        kseg = paddle.to_tensor(segs)

        @paddle.jit.to_static
        def fwd_masked(q, qs, ks):
            return F.flash_attention(q, q, q, causal=True,
                                     q_segment_ids=qs, kv_segment_ids=ks)

        try:
            out = fwd_masked(q, qseg, kseg)
            np.asarray(out._data[0, 0, 0, 0])
            t0 = time.perf_counter()
            for _ in range(10):
                out = fwd_masked(q, qseg, kseg)
            np.asarray(out._data[0, 0, 0, 0])
            dt = (time.perf_counter() - t0) / 10
            flops = 4 * args.batch * args.heads * seq * seq * \
                args.head_dim / 2
            entry["masked_pallas_ms"] = round(dt * 1e3, 2)
            entry["masked_pallas_tflops"] = round(flops / dt / 1e12, 1)
        except Exception as e:
            entry["masked_pallas_ms"] = f"OOM/{type(e).__name__}"
        results.append(entry)
        print(json.dumps(entry))


if __name__ == "__main__":
    main()
