"""Llama decoder train throughput across sizes/sequence lengths.

Usage: python benchmarks/bench_llama.py [--hidden 1024] [--layers 8]
       [--batch 16] [--seq 1024] [--scan-k 4] [--steps 20]
Same metric as the repo-root bench.py (the benchmark of record), but
parameterized for sweeps.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--inter", type=int, default=2816)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--scan-k", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="GQA kv heads (0 = same as --heads)")
    ap.add_argument("--state", choices=["fp32", "bf16", "int8"],
                    default="fp32",
                    help="optimizer state: fp32 masters+moments (reference "
                         "behavior), bf16 moments + master-weight-free "
                         "bf16 params with stochastic rounding, or int8 "
                         "block-quantized moments (2 B/param of m+v)")
    ap.add_argument("--q8-chunk", type=int, default=0,
                    help="int8-state chunk size in elements (0 = default); "
                         "bigger = fewer serial optimizer chunks, more "
                         "transient HBM")
    ap.add_argument("--q8-unroll", type=int, default=0,
                    help="chunks per int8-update loop iteration "
                         "(0 = default)")
    ap.add_argument("--q8-window", type=int, default=0,
                    help="params in flight in the int8 update "
                         "(0 = default)")
    ap.add_argument("--scan-layers", action="store_true",
                    help="stack identical decoder layers under lax.scan")
    ap.add_argument("--recompute", action="store_true",
                    help="activation checkpointing on the layer body")
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.devices()[0].platform != "cpu"
    peak = 197e12 if on_tpu else 1e12
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=args.hidden,
                      intermediate_size=args.inter,
                      num_hidden_layers=args.layers,
                      num_attention_heads=args.heads,
                      num_key_value_heads=args.kv_heads or args.heads,
                      max_position_embeddings=max(2048, args.seq),
                      scan_layers=args.scan_layers,
                      recompute=args.recompute)
    model = LlamaForCausalLM(cfg)
    bf16_state = args.state in ("bf16", "int8")
    # narrow state: bf16 (6 B/param) or int8 block-quantized (4 B/param)
    # moments + no fp32 masters (params update in bf16 with stochastic
    # rounding) vs the reference's 16 B/param. The big scan-stacked params
    # make the per-param (unfused) path the fast one here.
    moment = {"fp32": "float32", "bf16": "bfloat16",
              "int8": "int8"}[args.state]
    if args.q8_chunk:
        paddle.optimizer.Adam._Q8_CHUNK_ELEMS = args.q8_chunk
    if args.q8_unroll:
        paddle.optimizer.Adam._Q8_UNROLL = args.q8_unroll
    if args.q8_window:
        paddle.optimizer.Adam._Q8_PARAM_WINDOW = args.q8_window
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        use_multi_tensor=not args.scan_layers and args.state != "int8",
        moment_dtype=moment,
        use_master_weights=False if bf16_state else None)
    if on_tpu:
        model, opt = paddle.amp.decorate(
            model, opt, level="O2", dtype="bfloat16",
            master_weight=False if bf16_state else None)

    @paddle.jit.to_static(iters_per_call=args.scan_k)
    def train_step(ids):
        with paddle.amp.auto_cast(enable=on_tpu, level="O2",
                                  dtype="bfloat16"):
            loss, _ = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    ids = paddle.to_tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (args.scan_k, args.batch, args.seq),
        dtype=np.int32))
    for _ in range(2):
        loss = train_step(ids)
    np.asarray(loss._data)
    steps_run = (args.steps // args.scan_k) * args.scan_k
    t0 = time.perf_counter()
    for _ in range(steps_run // args.scan_k):
        loss = train_step(ids)
    np.asarray(loss._data)
    dt = time.perf_counter() - t0
    tok = args.batch * args.seq * steps_run / dt
    mfu = tok * model.flops_per_token(args.seq) / peak
    print(json.dumps({
        "benchmark": "llama_train", "tokens_per_sec": round(tok, 1),
        "mfu": round(mfu, 4), "params": model.num_params(),
        "hidden": args.hidden, "layers": args.layers, "batch": args.batch,
        "seq": args.seq, "scan_k": args.scan_k, "state": args.state,
        "scan_layers": args.scan_layers, "recompute": args.recompute,
        "final_loss": round(float(np.asarray(loss._data).reshape(-1)[-1]), 4),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
