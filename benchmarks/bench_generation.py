"""Generation/decode throughput on the fused serving stack.

The serving path VERDICT r4 flagged as unmeasured: FusedMultiTransformer
decode over pre-allocated KV caches (reference:
paddle.incubate.nn.FusedMultiTransformer + masked_multihead_attention —
the kernels behind PaddleNLP fused generation; upstream AnalysisPredictor
is a *performance* artifact).

Three numbers, one JSON line:
  * prefill: full-prompt forward filling the stacked cache
  * decode (per-token): ONE compiled program per token (to_static; the
    stacked cache makes the per-layer loop a lax.scan, so program size is
    O(1) in depth)
  * decode (scan-K): K greedy tokens per dispatch — one compiled program
    runs the closed loop embed -> stack -> head -> argmax -> embed via
    lax.scan. On a relay-attached chip (~100 ms/dispatch here) this is
    the only honest serving number; on directly-attached TPUs the
    per-token path converges toward it.

A fourth mode, ``--serving``, drives the continuous-batching engine
(`paddle_tpu.serving`) over the SAME model: aggregate tok/s at batch
sizes 1/4/16 through the paged KV cache (``--kv-dtype native|bf16|int8``),
with per-request greedy parity pinned against the bs=1 per-token compiled
loop. Serving throughput = batch x per-token rate — the "millions of
users" number (ROADMAP item 1).

Usage: python benchmarks/bench_generation.py [--layers 22] [--prompt 512]
       [--tokens 64] [--scan-k 16]
       python benchmarks/bench_generation.py --serving [--kv-dtype int8]
       [--serving-batches 1,4,16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

# --serving JSON schema of record: what RESULTS.md / BENCH_r0*.json diffs key
# on, pinned by tests/test_bench_selfdefense.py. Change both together.
SERVING_RESULT_FIELDS = (
    "benchmark", "params", "layers", "hidden", "dtype", "kv_dtype",
    "page_size", "prompt", "tokens", "single_stream_tokens_per_sec",
    "serving", "paged_attention", "context_sweep", "resilience", "http",
    "fleet", "prefix_sharing", "speedup_vs_single_stream", "device")
SERVING_ROW_FIELDS = (
    "aggregate_tokens_per_sec", "ttft_ms", "tpot_ms", "queue_wait_ms",
    "scan_greedy_parity", "match_frac", "batch_utilization")
# the "serving under fire" counters (ISSUE 8): a healthy offline drain
# reports zeros, which is exactly the claim worth pinning — overload and
# recovery are VISIBLE series, so a nonzero here in a bench diff means the
# run itself degraded (shed requests, watchdog trips, replayed slots)
SERVING_RESILIENCE_FIELDS = (
    "rejected_queue_full", "rejected_deadline", "rejected_shed",
    "watchdog_trips", "replays")
# the paged-attention decode tier (ISSUE 13): which tier the measured
# steps actually ran (kernel = Pallas streaming over live pages, dense =
# the gather-the-whole-cache debug path) plus the per-token attention KV
# traffic of each — the structural claim of record is that the live
# number scales with the context, the dense one with max_len. Since
# ISSUE 16 the tier that actually ran reports the cost registry's
# MEASURED per-token bytes (XLA's bytes-accessed for the warmed bucket
# program, / bucket) instead of the hand formula, with
# attn_bytes_source = "measured"; the formula stays as the modeled
# number for the tier that did not run and as a one-sided cross-check
# (attention-only model must not exceed measured whole-program traffic
# by >10%).
PAGED_ATTENTION_FIELDS = (
    "mode", "kernel_steps", "dense_steps", "attn_bytes_per_token_live",
    "attn_bytes_per_token_dense", "attn_bytes_source", "suspect_reasons")
CONTEXT_SWEEP_FIELDS = (
    "context", "decode_tokens_per_sec", "attn_bytes_per_token_live",
    "attn_bytes_per_token_dense")
# the HTTP front-door leg (ISSUE 15, --serving --http): end-to-end
# request latency THROUGH the router + streaming front door vs the same
# workload through in-process Router.submit — the per-request front-door
# overhead of record — plus the router's resilience counters, which a
# healthy run reports all-zero (any nonzero in a bench diff means the
# measured run itself degraded: a replica failed over, a request was
# hedged or rejected)
HTTP_RESULT_FIELDS = (
    "replicas", "requests", "clients", "aggregate_tokens_per_sec",
    "e2e_p50_ms", "e2e_p99_ms", "inproc_p50_ms", "overhead_p50_ms",
    "router")
HTTP_ROUTER_FIELDS = ("retries", "failovers", "hedges", "rejected")
# the fleet-tier leg (ISSUE 20, --serving --fleet): the SAME workload
# through in-process Router.submit vs a 2-worker OUT-OF-PROCESS
# FleetSupervisor — the per-request cost of process isolation + RPC +
# crash supervision, the fleet tier's overhead of record. Workers are
# forced onto CPU (one accelerator cannot be shared by N processes), so
# on a TPU host the honest read is the supervisor counters and the fleet
# leg's own latencies, not the inproc delta. A healthy run reports
# respawns / worker_deaths / failovers / rejected all ZERO — any nonzero
# in a bench diff means the measured run itself degraded (a worker died
# and was respawned mid-measurement).
FLEET_RESULT_FIELDS = (
    "workers", "requests", "clients", "aggregate_tokens_per_sec",
    "e2e_p50_ms", "e2e_p99_ms", "inproc_p50_ms", "overhead_p50_ms",
    "supervisor")
FLEET_SUPERVISOR_FIELDS = (
    "respawns", "worker_deaths", "failovers", "rejected")
# the prefix-sharing leg (ISSUE 17, --serving --prompt-overlap): one row
# per seeded shared-prefix mix (0/50/90% of each prompt is a common
# page-aligned prefix), sharing ON vs the same workload with sharing OFF.
# The claims of record: prefill tokens COMPUTED collapse toward the
# unshared tail as overlap grows, TTFT follows, aggregate tok/s never
# regresses, and the transcripts stay bit-identical across the two modes
# (the COW numerics contract). Both modes run the CAUSAL prefill
# (seq_offset=0 vs seq_offset=start) so the parity comparison is
# apples-to-apples — the legacy bidirectional FMT prefill is semantically
# incompatible with chunked prefix reuse.
PREFIX_SHARING_FIELDS = (
    "page_size", "prompt", "tokens", "requests", "legs", "suspect_reasons")
PREFIX_SHARING_LEG_FIELDS = (
    "overlap_pct", "shared_prefix_tokens",
    "aggregate_tokens_per_sec", "baseline_tokens_per_sec",
    "ttft_ms_p50", "ttft_ms_p99",
    "prefill_tokens_requested", "prefill_tokens_computed",
    "pages_shared_ratio", "prefix_hit_rate", "transcripts_match")


def _prefix_suspect_reasons(legs: dict) -> list[str]:
    """Why the prefix_sharing block disqualifies this run ([] = healthy):
    the 90% leg sharing NOTHING means the measured run never exercised
    the feature the block claims to price (index disabled, prompts not
    page-aligned, or the chain hash broke), and a transcript mismatch
    means copy-on-write leaked one request's K/V into another's."""
    reasons = []
    hi = legs.get("overlap90")
    if hi is not None and hi["pages_shared_ratio"] == 0:
        reasons.append(
            "prefix_sharing: the 90% overlap leg shared ZERO pages — the "
            "run never exercised prefix reuse (check "
            "PADDLE_TPU_PREFIX_SHARING and page alignment)")
    for name, leg in legs.items():
        if not leg["transcripts_match"]:
            reasons.append(
                f"prefix_sharing: {name} transcripts differ between "
                "sharing on and off — COW isolation is broken")
    return reasons


def _storage_bytes(kv_dtype: str, compute_dtype: str) -> int:
    if kv_dtype == "int8":
        return 1
    if kv_dtype == "bf16":
        return 2
    return 4 if compute_dtype == "float32" else 2


def _paged_attn_bytes_per_token(layers, heads, head_dim, max_len, page_size,
                                storage_bytes, prompt, n_new):
    """Modeled per-token attention KV READ traffic for one slot.

    ``live``: the paged kernel streams ``ceil((t+1)/page_size)`` live
    pages per step (K+V, every layer) — averaged over the decode steps
    ``t = prompt .. prompt+n_new-1``, so it grows with the CONTEXT.
    ``dense``: the legacy gather reconstructs the full stacked cache
    every step, so it is ``max_len``-proportional regardless of context.
    Returns ``(live, dense)`` bytes/token."""
    page_row = layers * 2 * heads * page_size * head_dim * storage_bytes
    dense = layers * 2 * heads * max_len * head_dim * storage_bytes
    steps = [prompt + k for k in range(max(1, n_new))]
    live = sum(-(-(t + 1) // page_size) * page_row for t in steps) \
        / len(steps)
    return int(round(live)), int(dense)


def _measured_decode_bytes_per_token(bucket_records) -> int | None:
    """Per-token bytes of the largest warmed decode bucket program, from
    the cost registry (ISSUE 16): XLA's whole-program bytes-accessed for
    one decode step / bucket slots (one token per slot per step). None
    when the registry has no measured bucket (cost accounting off, or
    the backend returned no cost model)."""
    if not bucket_records:
        return None
    bucket = max(bucket_records)
    nbytes = (bucket_records[bucket] or {}).get("bytes_accessed")
    if not nbytes:
        return None
    return int(round(nbytes / bucket))


def _paged_suspect_reasons(block, on_tpu: bool, formula_live=None,
                           formula_dense=None):
    """All-dense-on-TPU disqualifies the number of record: with the
    kernel available (mode != off) every measured decode step running the
    dense tier means the run benchmarked the debug path — e.g. a test
    env's PADDLE_TPU_PAGED_ATTENTION=off leaking in (the
    _capture_suspect_reasons rule, for the serving tier).

    The formula cross-check (ISSUE 16) is one-sided: the hand formula
    models attention-only KV reads, a strict subset of the measured
    whole-program traffic — a modeled number above measured+10% means
    the formula or the measurement is wrong."""
    reasons = []
    if on_tpu and block["mode"] != "off" and block["kernel_steps"] == 0 \
            and block["dense_steps"] > 0:
        reasons.append(
            "paged_attention: every decode step ran the dense gather tier "
            "on TPU — the measured tok/s is the debug path, not the "
            "kernel (check PADDLE_TPU_PAGED_ATTENTION and kernel "
            "eligibility)")
    if block.get("attn_bytes_source") == "measured":
        ran_kernel = block["kernel_steps"] >= block["dense_steps"] \
            and block["kernel_steps"] > 0
        formula = formula_live if ran_kernel else formula_dense
        measured = block["attn_bytes_per_token_live"] if ran_kernel \
            else block["attn_bytes_per_token_dense"]
        if formula is not None and measured and formula > 1.10 * measured:
            reasons.append(
                f"paged_attention: modeled attention-only bytes/token "
                f"{formula} exceed the measured whole-program "
                f"{measured} by >10% — byte formula and cost registry "
                f"disagree")
    return reasons


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=2560)
    ap.add_argument("--inter", type=int, default=6912)
    ap.add_argument("--layers", type=int, default=22)
    ap.add_argument("--heads", type=int, default=20)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--prompt", type=int, default=512)
    ap.add_argument("--tokens", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--scan-k", type=int, default=16)
    ap.add_argument("--serving", action="store_true",
                    help="continuous-batching engine: aggregate tok/s at "
                         "--serving-batches with greedy parity vs the bs=1 "
                         "per-token loop")
    ap.add_argument("--serving-batches", default="1,4,16")
    ap.add_argument("--http", action="store_true",
                    help="with --serving: add the front-door leg — e2e "
                         "p50/p99 and tok/s through the K=2 router + "
                         "streaming HTTP tier vs in-process submit()")
    ap.add_argument("--fleet", action="store_true",
                    help="with --serving: add the fleet-tier leg — e2e "
                         "p50/p99 and tok/s through a 2-worker "
                         "out-of-process FleetSupervisor vs in-process "
                         "submit(), plus the supervisor's crash counters "
                         "(all-zero on a healthy run)")
    ap.add_argument("--prompt-overlap", action="store_true",
                    help="with --serving: add the prefix-sharing leg — a "
                         "seeded 0/50/90%% shared-prefix prompt mix, "
                         "sharing on vs off (tok/s, TTFT, prefill tokens "
                         "computed vs requested, pages shared)")
    ap.add_argument("--kv-dtype", default="native",
                    choices=("native", "bf16", "int8"))
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--context-sweep", default="",
                    help="comma list of context lengths (e.g. 512,2048,8192)"
                         ": per-context decode tok/s through the engine "
                         "plus the modeled live-vs-dense attention "
                         "bytes/token (the paged-attention win of record)")
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core.tensor import Tensor as _T, apply
    from paddle_tpu.core.tracing import no_grad
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    on_tpu = jax.devices()[0].platform != "cpu"
    if not on_tpu:  # CPU CI smoke: shrink to seconds
        args.hidden, args.inter, args.layers, args.heads = 128, 256, 2, 4
        args.vocab, args.prompt, args.tokens = 512, 16, 8
        args.max_len, args.scan_k = 64, 4
    E, H, L = args.hidden, args.heads, args.layers
    B, V, M = args.batch, args.vocab, args.max_len
    dtype = "bfloat16" if on_tpu else "float32"

    paddle.seed(0)
    with paddle.amp.auto_cast(False):
        embed = nn.Embedding(V, E)
        fmt = FusedMultiTransformer(E, H, args.inter, num_layers=L,
                                    activation="gelu")
        final_ln = nn.LayerNorm(E)
        head = nn.Linear(E, V, bias_attr=False)
    for layer in (embed, fmt, final_ln, head):
        layer.to(dtype=dtype)
        layer.eval()
    fmt.prepare_decode()  # stacked scan-decode weights, built eagerly
    n_params = sum(int(np.prod(p.shape)) for l in (embed, fmt, final_ln, head)
                   for p in l.parameters())

    def lm_step(tok, cache, t):
        """(B, 1) int32 token -> (next (B, 1) int32, new cache). Pure
        Tensor ops: shared by the compiled per-token step and the scan-K
        loop body."""
        x = embed(tok)
        x, cache = fmt(x, caches=cache, time_step=t)
        x = final_ln(x)
        logits = head(x)                       # (B, 1, V)
        nxt = paddle.argmax(logits, axis=-1)   # (B, 1) greedy
        return nxt.astype("int32"), cache

    def prefill_raw(ids, cache):
        x = embed(ids)
        x, cache = fmt(x, caches=cache, time_step=None)
        x = final_ln(x)
        logits = head(x[:, -1:])
        nxt = paddle.argmax(logits, axis=-1)
        return nxt.astype("int32"), cache

    def prefill_causal_raw(ids, cache, start=0):
        """3-arg causal prefill for the prefix-sharing leg (ISSUE 17):
        ``seq_offset`` makes the FMT prefill causal and chunk-resumable —
        positions [start, start+len) attend the resident cache prefix plus
        themselves, so a shared-prefix admission computes only its tail
        and the start=0 run is the exact full-prompt reference."""
        x = embed(ids)
        x, cache = fmt(x, caches=cache, time_step=None, seq_offset=start)
        x = final_ln(x)
        logits = head(x[:, -1:])
        nxt = paddle.argmax(logits, axis=-1)
        return nxt.astype("int32"), cache

    prefill = paddle.jit.to_static(prefill_raw)

    @paddle.jit.to_static
    def decode_one(tok, cache, t):
        nxt, cache = lm_step(tok, cache, t)
        return nxt, cache, t + 1

    K = args.scan_k

    @paddle.jit.to_static
    def decode_scan(tok, cache, t):
        """K greedy tokens in ONE program: lax.scan over the closed
        decode recurrence (the TPU serving loop — dispatch cost amortizes
        over K tokens)."""
        def fn(tok_a, cache_a, t_a):
            def body(carry, _):
                ta, ca, tt = carry
                with no_grad():
                    nxt, newc = lm_step(_T(ta), _T(ca), _T(tt))
                return (nxt._data, newc._data, tt + 1), nxt._data[:, 0]

            carry, toks = jax.lax.scan(body, (tok_a, cache_a, t_a), None,
                                       length=K)
            return carry[0], carry[1], carry[2], toks

        return apply("decode_scan_k", fn, tok, cache, t, amp=False)

    if args.serving:
        _run_serving(args, paddle, prefill_raw, prefill, lm_step, decode_one,
                     n_params, prefill_causal_raw=prefill_causal_raw,
                     L=L, H=H, E=E, V=V, M=M, dtype=dtype)
        return

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, V, (B, args.prompt),
                                        dtype=np.int32))
    zero_cache = paddle.zeros([L, 2, B, H, M, E // H], dtype=dtype)

    def sync(x):
        return np.asarray(x._data)

    # ---- prefill ----
    t0 = time.perf_counter()
    tok, cache = prefill(ids, zero_cache)
    sync(tok)
    prefill_compile = time.perf_counter() - t0
    tok, cache = prefill(ids, zero_cache)
    sync(tok)
    t0 = time.perf_counter()
    tok, cache = prefill(ids, zero_cache)
    sync(tok)
    prefill_s = time.perf_counter() - t0

    # ---- per-token compiled decode ----
    t = paddle.full([B], args.prompt, dtype="int32")
    tok1, cache1, t1 = decode_one(tok, cache, t)  # compile
    sync(tok1)
    n_tok = min(args.tokens, M - args.prompt - 2)
    t0 = time.perf_counter()
    tk, ck, tt = tok, cache, t
    for _ in range(n_tok):
        tk, ck, tt = decode_one(tk, ck, tt)
    sync(tk)
    per_token_s = (time.perf_counter() - t0) / n_tok

    # ---- scan-K decode ----
    tokS, cacheS, tS, toksS = decode_scan(tok, cache, t)  # compile
    sync(tokS)
    calls = max(1, n_tok // K)
    t0 = time.perf_counter()
    tk, ck, tt = tok, cache, t
    outs = []
    for _ in range(calls):
        tk, ck, tt, toks = decode_scan(tk, ck, tt)
        outs.append(toks)
    sync(tk)
    scan_s = (time.perf_counter() - t0) / (calls * K)

    # greedy parity: the scanned loop should emit the tokens the per-token
    # path emits. The two programs compile (and fuse) differently, so a
    # 1-ulp bf16 logit tie can legitimately flip an argmax — gate on a
    # match FRACTION, not exact equality, and report it.
    tk2, ck2, tt2 = tok, cache, t
    ref = []
    for _ in range(K):
        tk2, ck2, tt2 = decode_one(tk2, ck2, tt2)
        ref.append(int(np.asarray(tk2._data)[0, 0]))
    got = [int(x) for x in np.asarray(outs[0]._data)[:, 0]] if hasattr(
        outs[0], "_data") else [int(x) for x in np.asarray(outs[0])[:, 0]]
    match_frac = sum(a == b for a, b in zip(got, ref)) / K
    parity = match_frac >= 0.75

    print(json.dumps({
        "benchmark": "fused_generation",
        "params": n_params, "layers": L, "hidden": E, "batch": B,
        "prompt": args.prompt, "dtype": dtype,
        "prefill_ms": round(prefill_s * 1e3, 1),
        "prefill_tokens_per_sec": round(B * args.prompt / prefill_s, 1),
        "decode_per_token_ms": round(per_token_s * 1e3, 2),
        "decode_tokens_per_sec": round(B / per_token_s, 1),
        "decode_scan_per_token_ms": round(scan_s * 1e3, 2),
        "decode_scan_tokens_per_sec": round(B / scan_s, 1),
        "scan_k": K, "scan_greedy_parity": parity,
        "scan_greedy_match_frac": round(match_frac, 3),
        "prefill_compile_s": round(prefill_compile, 1),
        "device": str(jax.devices()[0]),
    }))
    if not parity:
        print(f"PARITY FAIL: scan {got} vs per-token {ref}", file=sys.stderr)
        sys.exit(1)


def _run_serving(args, paddle, prefill_raw, prefill, lm_step, decode_one,
                 n_params, *, prefill_causal_raw, L, H, E, V, M, dtype):
    """Continuous-batching throughput: aggregate tok/s per batch size with
    per-request greedy parity against the bs=1 per-token compiled loop."""
    import jax

    from paddle_tpu import observability as obs
    from paddle_tpu import serving

    obs.enable()   # batch_utilization is MEASURED from the engine's step/
    # token counters, not derived from config (which would pin it at 1.0)

    def serving_counters():
        snap = obs.snapshot()
        return (snap.get("serving.steps_total", 0) or 0,
                snap.get("serving.tokens_total", 0) or 0)

    def queue_wait_stats():
        # the SLO-bucketed histogram (ISSUE 12) scraped by the front door;
        # the per-bs row reports the mean over THIS drain's admissions
        h = obs.default_registry().get("serving.queue_wait_seconds")
        st = h.stats() if h is not None else {"sum": 0.0, "count": 0}
        return float(st["sum"]), int(st["count"])

    bss = sorted({int(b) for b in args.serving_batches.split(",") if b})
    max_bs = bss[-1]
    page_size = min(args.page_size, M)
    if args.tokens < 2 or M - args.prompt - 2 < 2:
        print(f"--serving needs >= 2 decode tokens (the single-stream rate "
              f"is measured over tokens after the first): got --tokens "
              f"{args.tokens} with prompt {args.prompt} / max_len {M}",
              file=sys.stderr)
        sys.exit(2)
    n_new = min(args.tokens, M - args.prompt - 2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, V, (args.prompt,), dtype=np.int32)
               for _ in range(max_bs)]

    def sync(x):
        return np.asarray(x._data)

    # ---- bs=1 per-token reference: the parity oracle ----
    def reference(prompt):
        ids = paddle.to_tensor(prompt[None, :])
        cache = paddle.zeros([L, 2, 1, H, M, E // H], dtype=dtype)
        tok, cache = prefill(ids, cache)
        toks = [int(sync(tok)[0, 0])]
        t = paddle.full([1], args.prompt, dtype="int32")
        for _ in range(n_new - 1):
            tok, cache, t = decode_one(tok, cache, t)
            toks.append(int(sync(tok)[0, 0]))
        return toks

    refs = [reference(p) for p in prompts]

    # single-stream steady-state rate (compiled; no per-token host sync —
    # the protocol of the non-serving decode timing above)
    ids = paddle.to_tensor(prompts[0][None, :])
    cache0 = paddle.zeros([L, 2, 1, H, M, E // H], dtype=dtype)
    tok, cache = prefill(ids, cache0)
    sync(tok)
    t = paddle.full([1], args.prompt, dtype="int32")
    t0 = time.perf_counter()
    tk, ck, tt = tok, cache, t
    for _ in range(n_new - 1):
        tk, ck, tt = decode_one(tk, ck, tt)
    sync(tk)
    single_rate = (n_new - 1) / (time.perf_counter() - t0)

    rows, parity_all = {}, True
    for bs in bss:
        buckets = tuple(b for b in (1, 4, 16) if b <= bs)
        if not buckets or buckets[-1] < bs:
            buckets += (bs,)
        cfg = serving.ServingConfig(
            num_layers=L, num_heads=H, head_dim=E // H, max_len=M,
            max_batch=bs, buckets=buckets, page_size=page_size,
            kv_dtype=args.kv_dtype, compute_dtype=dtype)
        eng = serving.Engine(prefill_raw, lm_step, cfg)
        eng.warmup(prompt_lens=[args.prompt])

        def drain():
            futs = [eng.submit(serving.GenerationRequest(
                prompts[i], max_new_tokens=n_new)) for i in range(bs)]
            eng.run()
            return [f.result() for f in futs]

        drain()                        # warm pass: everything compiled
        s0, tk0 = serving_counters()
        qw0 = queue_wait_stats()
        t0 = time.perf_counter()
        results = drain()
        elapsed = time.perf_counter() - t0
        s1, tk1 = serving_counters()
        qw1 = queue_wait_stats()

        fracs = [sum(a == b for a, b in zip(r.tokens, refs[i])) / n_new
                 for i, r in enumerate(results)]
        # same tolerance as the scan-parity gate: compiled programs fuse
        # differently, a 1-ulp bf16 logit tie may flip an argmax
        parity = min(fracs) >= 0.75
        parity_all &= parity
        bucket = next(b for b in buckets if b >= bs)
        # decode-token occupancy of the bs-slot bucket over the drain:
        # prefill emits bs first tokens outside decode steps; a mixed-
        # length workload (or mid-run eviction) pulls this below 1.0
        steps = s1 - s0
        util = ((tk1 - tk0) - bs) / (steps * bucket) if steps else 1.0
        rows[f"bs{bs}"] = {
            "aggregate_tokens_per_sec": round(bs * n_new / elapsed, 1),
            "ttft_ms": round(1e3 * float(np.mean(
                [r.ttft_s for r in results])), 2),
            "tpot_ms": round(1e3 * float(np.mean(
                [r.tpot_s for r in results])), 2),
            "queue_wait_ms": round(
                1e3 * (qw1[0] - qw0[0]) / max(1, qw1[1] - qw0[1]), 3),
            "scan_greedy_parity": parity,
            "match_frac": round(min(fracs), 3),
            "batch_utilization": round(util, 3),
        }
        assert set(rows[f"bs{bs}"]) == set(SERVING_ROW_FIELDS), \
            "serving row drifted from SERVING_ROW_FIELDS"

    top = rows[f"bs{max_bs}"]["aggregate_tokens_per_sec"]
    snap = obs.snapshot()
    on_tpu = jax.devices()[0].platform != "cpu"
    sbytes = _storage_bytes(args.kv_dtype, dtype)
    live_b, dense_b = _paged_attn_bytes_per_token(
        L, H, E // H, M, page_size, sbytes, args.prompt, n_new)
    steps_by_path = snap.get("serving.paged_attention_steps_total", {}) or {}
    from paddle_tpu.ops import paged_attention as _pa
    kernel_steps = int(steps_by_path.get("path=kernel", 0))
    dense_steps = int(steps_by_path.get("path=dense", 0))
    # ISSUE 16: the tier that ran reports the cost registry's MEASURED
    # per-token bytes for the last engine's largest warmed bucket program
    # (earlier engines' records retired when their programs died); the
    # other tier keeps the modeled formula, and the formula cross-checks
    # the measurement inside _paged_suspect_reasons
    from paddle_tpu.observability import cost as _cost_mod
    measured_b = _measured_decode_bytes_per_token(
        _cost_mod.decode_bucket_records())
    live_rep, dense_rep, source = live_b, dense_b, "model"
    if measured_b is not None:
        source = "measured"
        if kernel_steps >= dense_steps and kernel_steps > 0:
            live_rep = measured_b
        else:
            dense_rep = measured_b
    paged_block = {
        "mode": _pa.mode(),
        "kernel_steps": kernel_steps,
        "dense_steps": dense_steps,
        "attn_bytes_per_token_live": live_rep,
        "attn_bytes_per_token_dense": dense_rep,
        "attn_bytes_source": source,
    }
    paged_block["suspect_reasons"] = _paged_suspect_reasons(
        paged_block, on_tpu, formula_live=live_b, formula_dense=dense_b)
    assert set(paged_block) == set(PAGED_ATTENTION_FIELDS), \
        "paged_attention block drifted from PAGED_ATTENTION_FIELDS"
    sweep = _context_sweep(args, serving, paddle, prefill_raw, lm_step,
                           L=L, H=H, E=E, V=V, dtype=dtype)
    http_block = _run_http(args, serving, obs, prefill_raw, lm_step,
                           n_new=n_new, L=L, H=H, E=E, V=V, M=M,
                           dtype=dtype) if args.http else None
    fleet_block = _run_fleet(args, serving, obs, prefill_raw, lm_step,
                             n_new=n_new, L=L, H=H, E=E, V=V, M=M,
                             dtype=dtype) if args.fleet else None
    prefix_block = _run_prefix_sharing(
        args, serving, prefill_causal_raw, lm_step, L=L, H=H, E=E, V=V,
        dtype=dtype, on_tpu=on_tpu) if args.prompt_overlap else None
    rejected = snap.get("serving.rejected_total", {}) or {}
    trips = snap.get("serving.watchdog_trips_total", {}) or {}
    fire = {
        "rejected_queue_full": rejected.get("reason=queue_full", 0),
        "rejected_deadline": rejected.get("reason=deadline", 0),
        "rejected_shed": rejected.get("reason=shed", 0),
        "watchdog_trips": sum(trips.values()),
        "replays": snap.get("serving.replays_total", 0) or 0,
    }
    assert set(fire) == set(SERVING_RESILIENCE_FIELDS), \
        "serving resilience block drifted from SERVING_RESILIENCE_FIELDS"
    payload = {
        "benchmark": "serving_generation",
        "params": n_params, "layers": L, "hidden": E, "dtype": dtype,
        "kv_dtype": args.kv_dtype, "page_size": page_size,
        "prompt": args.prompt, "tokens": n_new,
        "single_stream_tokens_per_sec": round(single_rate, 1),
        "serving": rows,
        "paged_attention": paged_block,
        "context_sweep": sweep,
        "resilience": fire,
        "http": http_block,
        "fleet": fleet_block,
        "prefix_sharing": prefix_block,
        "speedup_vs_single_stream": round(top / single_rate, 2),
        "device": str(jax.devices()[0]),
    }
    assert set(payload) == set(SERVING_RESULT_FIELDS), \
        "serving payload drifted from SERVING_RESULT_FIELDS"
    print(json.dumps(payload))
    if not parity_all:
        print(f"SERVING PARITY FAIL: {rows}", file=sys.stderr)
        sys.exit(1)
    if paged_block["suspect_reasons"]:
        # mirror bench.py's anomaly contract: the number still prints, the
        # exit code says don't trust it as the number of record
        print(f"PAGED SUSPECT: {paged_block['suspect_reasons']}",
              file=sys.stderr)
        sys.exit(1)
    if prefix_block and prefix_block["suspect_reasons"]:
        print(f"PREFIX SHARING SUSPECT: {prefix_block['suspect_reasons']}",
              file=sys.stderr)
        sys.exit(1)


def _run_http(args, serving, obs, prefill_raw, lm_step, *, n_new, L, H, E,
              V, M, dtype):
    """The front-door leg (ISSUE 15): the SAME workload through (a)
    in-process ``Router.submit`` over K=2 replicas and (b) the streaming
    HTTP front door over that router, from ``clients`` concurrent client
    threads. Reports e2e p50/p99 and aggregate tok/s for the HTTP leg,
    the in-process p50, and their difference — the per-request front-door
    overhead of record — plus the router's resilience counters (all-zero
    is the healthy-run claim, pinned in test_bench_selfdefense)."""
    import http.client
    import json as _json
    import threading

    replicas, clients, per_client = 2, 4, 2
    n_req = clients * per_client
    page_size = min(args.page_size, M)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, V, (args.prompt,), dtype=np.int32)
               for _ in range(n_req)]

    engines = []
    for i in range(replicas):
        cfg = serving.ServingConfig(
            num_layers=L, num_heads=H, head_dim=E // H, max_len=M,
            max_batch=4, buckets=(1, 4), page_size=page_size,
            kv_dtype=args.kv_dtype, compute_dtype=dtype, name=f"r{i}")
        engines.append((f"r{i}", serving.Engine(prefill_raw, lm_step, cfg)
                        .warmup(prompt_lens=[args.prompt])))
    router = serving.Router(engines).start()
    fd = serving.FrontDoor(router)

    def run_clients(fn):
        """fn(prompt) -> token count; returns (per-request seconds,
        wall seconds). A failed request fails the BENCH, not just its
        worker thread — numbers from a degraded run must never print."""
        lat, errors, lock = [], [], threading.Lock()

        def worker(chunk):
            for p in chunk:
                try:
                    t0 = time.perf_counter()
                    ntok = fn(p)
                    dt = time.perf_counter() - t0
                    if ntok != n_new:
                        raise AssertionError(
                            f"short response: {ntok}/{n_new} tokens")
                except Exception as e:
                    with lock:
                        errors.append(e)
                    return
                with lock:
                    lat.append(dt)

        chunks = [prompts[c::clients] for c in range(clients)]
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(c,))
                   for c in chunks]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors or len(lat) != n_req:
            raise RuntimeError(
                f"http bench leg degraded: {len(lat)}/{n_req} requests "
                f"completed; first error: {errors[0] if errors else None}")
        return lat, time.perf_counter() - t0

    def inproc(p):
        fut = router.submit(serving.GenerationRequest(
            p, max_new_tokens=n_new))
        return len(fut.result(timeout=300).tokens)

    def via_http(p):
        conn = http.client.HTTPConnection(fd.host, fd.port, timeout=300)
        try:
            conn.request("POST", "/v1/generate", body=_json.dumps({
                "prompt": p.tolist(), "max_new_tokens": n_new,
                "stream": True}).encode())
            resp = conn.getresponse()
            raw = resp.read().decode("utf-8")
            toks = sum(1 for ln in raw.splitlines()
                       if ln.startswith('data: {"token"'))
            assert resp.status == 200 and "event: done" in raw
            return toks
        finally:
            conn.close()

    try:
        run_clients(inproc)                      # warm both paths
        inproc_lat, _ = run_clients(inproc)
        http_lat, http_wall = run_clients(via_http)
    finally:
        router.stop(drain=True, timeout=60)
        fd.close()

    snap = obs.snapshot()
    rejected = snap.get("serving.router.rejected_total", {}) or {}
    block = {
        "replicas": replicas, "requests": n_req, "clients": clients,
        "aggregate_tokens_per_sec": round(n_req * n_new / http_wall, 1),
        "e2e_p50_ms": round(1e3 * float(np.percentile(http_lat, 50)), 2),
        "e2e_p99_ms": round(1e3 * float(np.percentile(http_lat, 99)), 2),
        "inproc_p50_ms": round(
            1e3 * float(np.percentile(inproc_lat, 50)), 2),
        "overhead_p50_ms": round(
            1e3 * float(np.percentile(http_lat, 50)
                        - np.percentile(inproc_lat, 50)), 2),
        "router": {
            "retries": snap.get("serving.router.retries_total", 0) or 0,
            "failovers": snap.get(
                "serving.router.failovers_total", 0) or 0,
            "hedges": snap.get("serving.router.hedges_total", 0) or 0,
            "rejected": sum(rejected.values()),
        },
    }
    assert set(block) == set(HTTP_RESULT_FIELDS), \
        "http block drifted from HTTP_RESULT_FIELDS"
    assert set(block["router"]) == set(HTTP_ROUTER_FIELDS), \
        "http router block drifted from HTTP_ROUTER_FIELDS"
    return block


def make_fleet_engine(*, name, hidden, inter, layers, heads, vocab,
                      max_len, page_size, kv_dtype, dtype, max_batch=4):
    """Fleet-worker factory (``--serving --fleet``): imported by
    ``paddle_tpu.serving.fleet_worker`` inside each worker process as
    ``bench_generation:make_fleet_engine``. Rebuilds the bench model
    under ``paddle.seed(0)`` — the identical seed and layer order the
    parent used — so every worker (and the parent's in-process
    comparison engines) carries bit-identical weights and the fleet leg
    measures transport, not model drift."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, serving
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(0)
    with paddle.amp.auto_cast(False):
        embed = nn.Embedding(vocab, hidden)
        fmt = FusedMultiTransformer(hidden, heads, inter, num_layers=layers,
                                    activation="gelu")
        final_ln = nn.LayerNorm(hidden)
        head = nn.Linear(hidden, vocab, bias_attr=False)
    for layer in (embed, fmt, final_ln, head):
        layer.to(dtype=dtype)
        layer.eval()
    fmt.prepare_decode()

    def lm_step(tok, cache, t):
        x = embed(tok)
        x, cache = fmt(x, caches=cache, time_step=t)
        x = final_ln(x)
        logits = head(x)
        nxt = paddle.argmax(logits, axis=-1)
        return nxt.astype("int32"), cache

    def prefill_raw(ids, cache):
        x = embed(ids)
        x, cache = fmt(x, caches=cache, time_step=None)
        x = final_ln(x)
        logits = head(x[:, -1:])
        nxt = paddle.argmax(logits, axis=-1)
        return nxt.astype("int32"), cache

    cfg = serving.ServingConfig(
        num_layers=layers, num_heads=heads, head_dim=hidden // heads,
        max_len=max_len, max_batch=max_batch, buckets=(1, 4),
        page_size=page_size, kv_dtype=kv_dtype, compute_dtype=dtype,
        name=name)
    return serving.Engine(prefill_raw, lm_step, cfg)


def _run_fleet(args, serving, obs, prefill_raw, lm_step, *, n_new, L, H, E,
               V, M, dtype):
    """The fleet-tier leg (ISSUE 20): the SAME workload through (a)
    in-process ``Router.submit`` over K=2 replicas and (b) a 2-worker
    OUT-OF-PROCESS ``FleetSupervisor`` (each worker a separate Python
    process serving the engine over the MAC'd RPC framing), from
    ``clients`` concurrent client threads. Reports e2e p50/p99 and
    aggregate tok/s for the fleet leg, the in-process p50, and their
    difference — the process-isolation + RPC + supervision overhead of
    record — plus the supervisor's crash counters (all-zero is the
    healthy-run claim, pinned in test_bench_selfdefense). Workers run
    with JAX_PLATFORMS=cpu: one accelerator cannot be shared by N
    processes, so on a TPU host read the supervisor counters and the
    fleet leg's own numbers, not the inproc delta."""
    import threading

    workers, clients, per_client = 2, 4, 2
    n_req = clients * per_client
    page_size = min(args.page_size, M)
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, V, (args.prompt,), dtype=np.int32)
               for _ in range(n_req)]

    engines = []
    for i in range(workers):
        cfg = serving.ServingConfig(
            num_layers=L, num_heads=H, head_dim=E // H, max_len=M,
            max_batch=4, buckets=(1, 4), page_size=page_size,
            kv_dtype=args.kv_dtype, compute_dtype=dtype, name=f"ip{i}")
        engines.append((f"ip{i}", serving.Engine(prefill_raw, lm_step, cfg)
                        .warmup(prompt_lens=[args.prompt])))
    router = serving.Router(engines).start()

    bench_dir = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(bench_dir)
    worker_env = {
        "JAX_PLATFORMS": "cpu",
        # the child imports paddle_tpu at interpreter startup (python -m),
        # BEFORE the spec's pythonpath is applied — the repo root has to
        # ride in on PYTHONPATH, not on spec.pythonpath
        "PYTHONPATH": os.pathsep.join(
            [repo_root] + [p for p in (os.environ.get("PYTHONPATH"),) if p]),
    }
    specs = [serving.FleetWorkerSpec(
        name=f"w{i}",
        factory="bench_generation:make_fleet_engine",
        config={"name": f"w{i}", "hidden": E, "inter": args.inter,
                "layers": L, "heads": H, "vocab": V, "max_len": M,
                "page_size": page_size, "kv_dtype": args.kv_dtype,
                "dtype": dtype},
        pythonpath=[bench_dir],
        env=worker_env,
        warmup=[args.prompt]) for i in range(workers)]
    sup = serving.FleetSupervisor(specs)

    def run_clients(fn):
        """fn(prompt) -> token count; returns (per-request seconds,
        wall seconds). A failed request fails the BENCH, not just its
        worker thread — numbers from a degraded run must never print."""
        lat, errors, lock = [], [], threading.Lock()

        def worker(chunk):
            for p in chunk:
                try:
                    t0 = time.perf_counter()
                    ntok = fn(p)
                    dt = time.perf_counter() - t0
                    if ntok != n_new:
                        raise AssertionError(
                            f"short response: {ntok}/{n_new} tokens")
                except Exception as e:
                    with lock:
                        errors.append(e)
                    return
                with lock:
                    lat.append(dt)

        chunks = [prompts[c::clients] for c in range(clients)]
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(c,))
                   for c in chunks]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors or len(lat) != n_req:
            raise RuntimeError(
                f"fleet bench leg degraded: {len(lat)}/{n_req} requests "
                f"completed; first error: {errors[0] if errors else None}")
        return lat, time.perf_counter() - t0

    def inproc(p):
        fut = router.submit(serving.GenerationRequest(
            p, max_new_tokens=n_new))
        return len(fut.result(timeout=300).tokens)

    def via_fleet(p):
        fut = sup.submit(serving.GenerationRequest(
            p, max_new_tokens=n_new))
        return len(fut.result(timeout=300).tokens)

    try:
        run_clients(inproc)                      # warm the inproc path
        inproc_lat, _ = run_clients(inproc)
        sup.start()
        run_clients(via_fleet)                   # warm worker programs
        fleet_lat, fleet_wall = run_clients(via_fleet)
    finally:
        router.stop(drain=True, timeout=60)
        sup.stop(drain=True, timeout=60)

    snap = obs.snapshot()
    deaths = snap.get("fleet.worker_deaths_total", {}) or {}
    rejected = snap.get("serving.router.rejected_total", {}) or {}
    block = {
        "workers": workers, "requests": n_req, "clients": clients,
        "aggregate_tokens_per_sec": round(n_req * n_new / fleet_wall, 1),
        "e2e_p50_ms": round(1e3 * float(np.percentile(fleet_lat, 50)), 2),
        "e2e_p99_ms": round(1e3 * float(np.percentile(fleet_lat, 99)), 2),
        "inproc_p50_ms": round(
            1e3 * float(np.percentile(inproc_lat, 50)), 2),
        "overhead_p50_ms": round(
            1e3 * float(np.percentile(fleet_lat, 50)
                        - np.percentile(inproc_lat, 50)), 2),
        "supervisor": {
            "respawns": snap.get("fleet.respawns_total", 0) or 0,
            "worker_deaths": sum(deaths.values())
            if isinstance(deaths, dict) else deaths,
            "failovers": snap.get(
                "serving.router.failovers_total", 0) or 0,
            "rejected": sum(rejected.values()),
        },
    }
    assert set(block) == set(FLEET_RESULT_FIELDS), \
        "fleet block drifted from FLEET_RESULT_FIELDS"
    assert set(block["supervisor"]) == set(FLEET_SUPERVISOR_FIELDS), \
        "fleet supervisor block drifted from FLEET_SUPERVISOR_FIELDS"
    return block


def _run_prefix_sharing(args, serving, prefill_causal_raw, lm_step, *,
                        L, H, E, V, dtype, on_tpu):
    """The prefix-sharing leg (ISSUE 17, --prompt-overlap): for each
    seeded overlap mix (0/50/90% of every prompt is one common
    page-aligned prefix) drain the SAME workload through an engine with
    prefix sharing ON and one with it OFF, both on the causal prefill.
    Each leg reports aggregate tok/s for both modes, the sharing-mode
    TTFT p50/p99, prefill tokens computed vs requested over the measured
    drain, the fraction of mapped pages that were shared instead of
    prefilled, the prefix-index hit rate, and whether the two modes'
    transcripts matched bit-for-bit. A warm drain precedes measurement so
    compile time (including the tail-prefill program) never lands in a
    TTFT, and its published chains stay resident on the idle list — the
    measured 90% leg exercises cross-drain reuse too."""
    n_req, overlaps = 8, (0, 50, 90)
    ps = args.page_size if on_tpu else 4
    plen = args.prompt if on_tpu else 32
    n_new = min(args.tokens, 8)
    max_len = -(-(plen + n_new + 2) // ps) * ps
    pages_per_req = -(-(plen + n_new) // ps)
    rng = np.random.default_rng(3)
    legs = {}
    for pct in overlaps:
        shared_len = int(pct / 100.0 * plen) // ps * ps
        base = rng.integers(0, V, (shared_len,), dtype=np.int32)

        def make_prompts():
            return [np.concatenate([
                base,
                rng.integers(0, V, (plen - shared_len,), dtype=np.int32)])
                for _ in range(n_req)]

        # fresh tails per drain, same shared base: the warm drain seeds
        # the index (and compiles the tail program for this leg's start
        # offset), the measured drain then shares exactly the base chain
        # per request — self-resubmission hits would otherwise make every
        # overlap level look like a 100% cache hit. Both modes replay the
        # SAME two prompt sets so the transcript comparison is exact.
        warm_prompts, measured_prompts = make_prompts(), make_prompts()
        out = {}
        for mode in ("on", "off"):
            cfg = serving.ServingConfig(
                num_layers=L, num_heads=H, head_dim=E // H,
                max_len=max_len, max_batch=4, buckets=(1, 4),
                page_size=ps, kv_dtype=args.kv_dtype, compute_dtype=dtype,
                prefix_sharing=mode)
            eng = serving.Engine(prefill_causal_raw, lm_step, cfg)
            eng.warmup(prompt_lens=[plen])

            def drain(prompts):
                futs = [eng.submit(serving.GenerationRequest(
                    p, max_new_tokens=n_new)) for p in prompts]
                eng.run()
                return [f.result() for f in futs]

            drain(warm_prompts)          # compiles + seeds the index
            req0, comp0 = eng.prefill_token_stats()
            shared0 = eng.kv.prefix_stats()["prefix_pages_shared_total"]
            t0 = time.perf_counter()
            results = drain(measured_prompts)
            elapsed = time.perf_counter() - t0
            req1, comp1 = eng.prefill_token_stats()
            stats = eng.kv.prefix_stats()
            out[mode] = {
                "tok_s": round(n_req * n_new / elapsed, 1),
                "ttft": [r.ttft_s for r in results],
                "tokens": [r.tokens for r in results],
                "requested": req1 - req0, "computed": comp1 - comp0,
                "shared_pages": stats["prefix_pages_shared_total"] - shared0,
                "hit_rate": stats["prefix_hit_rate"],
            }
        on = out["on"]
        leg = {
            "overlap_pct": pct,
            "shared_prefix_tokens": shared_len,
            "aggregate_tokens_per_sec": on["tok_s"],
            "baseline_tokens_per_sec": out["off"]["tok_s"],
            "ttft_ms_p50": round(
                1e3 * float(np.percentile(on["ttft"], 50)), 2),
            "ttft_ms_p99": round(
                1e3 * float(np.percentile(on["ttft"], 99)), 2),
            "prefill_tokens_requested": int(on["requested"]),
            "prefill_tokens_computed": int(on["computed"]),
            "pages_shared_ratio": round(
                on["shared_pages"] / (n_req * pages_per_req), 3),
            "prefix_hit_rate": round(on["hit_rate"], 3),
            "transcripts_match": on["tokens"] == out["off"]["tokens"],
        }
        assert set(leg) == set(PREFIX_SHARING_LEG_FIELDS), \
            "prefix sharing leg drifted from PREFIX_SHARING_LEG_FIELDS"
        legs[f"overlap{pct}"] = leg
    block = {
        "page_size": ps, "prompt": plen, "tokens": n_new,
        "requests": n_req, "legs": legs,
        "suspect_reasons": _prefix_suspect_reasons(legs),
    }
    assert set(block) == set(PREFIX_SHARING_FIELDS), \
        "prefix sharing block drifted from PREFIX_SHARING_FIELDS"
    return block


def _context_sweep(args, serving, paddle, prefill_raw, lm_step, *, L, H, E,
                   V, dtype):
    """Decode tok/s vs context length (``--context-sweep 512,2048,8192``):
    one bs=1 engine drain per context, with the modeled live-vs-dense
    attention bytes/token beside the measured rate — the long-context
    claim of ROADMAP 3a made visible in the row of record. Each context
    gets its own engine sized to ``context + tokens`` so max_len (and
    with it the dense tier's traffic) GROWS with the sweep while the
    kernel's live traffic tracks the context."""
    if not args.context_sweep:
        return []
    import jax

    on_tpu = jax.devices()[0].platform != "cpu"
    contexts = sorted({int(c) for c in args.context_sweep.split(",") if c})
    if not on_tpu:  # CPU CI smoke: keep each drain in seconds
        contexts = sorted({min(c, 48) for c in contexts})
    ps = args.page_size if on_tpu else min(args.page_size, 16)
    n_new = 8
    sbytes = _storage_bytes(args.kv_dtype, dtype)
    rng = np.random.default_rng(1)
    rows = []
    for c in contexts:
        max_len = -(-(c + n_new + 2) // ps) * ps
        cfg = serving.ServingConfig(
            num_layers=L, num_heads=H, head_dim=E // H, max_len=max_len,
            max_batch=1, buckets=(1,), page_size=ps,
            kv_dtype=args.kv_dtype, compute_dtype=dtype)
        eng = serving.Engine(prefill_raw, lm_step, cfg)
        prompt = rng.integers(0, V, (c,), dtype=np.int32)

        def drain():
            fut = eng.submit(serving.GenerationRequest(
                prompt, max_new_tokens=n_new))
            eng.run()
            return fut.result()

        drain()                              # compile pass
        t0 = time.perf_counter()
        drain()
        elapsed = time.perf_counter() - t0
        live_b, dense_b = _paged_attn_bytes_per_token(
            L, H, E // H, max_len, ps, sbytes, c, n_new)
        row = {"context": c,
               "decode_tokens_per_sec": round(n_new / elapsed, 1),
               "attn_bytes_per_token_live": live_b,
               "attn_bytes_per_token_dense": dense_b}
        assert set(row) == set(CONTEXT_SWEEP_FIELDS), \
            "context sweep row drifted from CONTEXT_SWEEP_FIELDS"
        rows.append(row)
    return rows


if __name__ == "__main__":
    main()
