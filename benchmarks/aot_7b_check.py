"""AOT compile-check: Llama-2-7B train step under dp x mp x pp hybrid.

The v5e dev chip cannot hold 7B for training, so this proves the NORTH-STAR
config LOWERS AND COMPILES: full 7B shapes (h=4096, inter=11008, L=32,
vocab=32000), AdamW fp32 state, bf16 compute, on an 8-device virtual mesh
(dp=2, mp=2, pp=2) with the same structure the framework uses on hardware —
blocks stacked over pp and scanned within each stage (jax.checkpoint),
megatron TP sharding over mp, batch over dp. Everything is ShapeDtypeStruct
specs — no 7B of host RAM is touched; jax.jit(...).lower().compile() on the
CPU backend exercises the full SPMD partitioner.

Run: python benchmarks/aot_7b_check.py       (writes AOT_7B.json)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import paddle_tpu as paddle

if len(jax.devices()) < 8:
    paddle.device.force_platform("cpu", 8)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# 7B geometry
V, H, I, L, HEADS = 32000, 4096, 11008, 32, 32
DP, MP, PP = 2, 2, 2
STAGE_LAYERS = L // PP
B, S, MICRO = 8, 2048, 4
HEAD_DIM = H // HEADS


def main():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(DP, MP, PP),
                ("dp", "mp", "pp"))

    def spec(shape, dtype, *pspec):
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, P(*pspec)))

    # per-block leaves stacked (PP, STAGE_LAYERS, ...): pp shards dim 0;
    # megatron TP shards the projection feature dims over mp
    def block_specs(dtype):
        return {
            "wq": spec((PP, STAGE_LAYERS, H, H), dtype, "pp", None, None, "mp"),
            "wk": spec((PP, STAGE_LAYERS, H, H), dtype, "pp", None, None, "mp"),
            "wv": spec((PP, STAGE_LAYERS, H, H), dtype, "pp", None, None, "mp"),
            "wo": spec((PP, STAGE_LAYERS, H, H), dtype, "pp", None, "mp", None),
            "w_gate": spec((PP, STAGE_LAYERS, H, I), dtype, "pp", None, None, "mp"),
            "w_up": spec((PP, STAGE_LAYERS, H, I), dtype, "pp", None, None, "mp"),
            "w_down": spec((PP, STAGE_LAYERS, I, H), dtype, "pp", None, "mp", None),
            "ln1": spec((PP, STAGE_LAYERS, H), dtype, "pp", None, None),
            "ln2": spec((PP, STAGE_LAYERS, H), dtype, "pp", None, None),
        }

    params_specs = {
        "embed": spec((V, H), jnp.float32, "mp", None),
        "norm": spec((H,), jnp.float32, None),
        "head": spec((H, V), jnp.float32, None, "mp"),
        "blocks": block_specs(jnp.float32),
    }
    # AdamW fp32 state mirrors the param tree
    adam_specs = {
        "m": params_specs, "v": params_specs,
        "step": jax.ShapeDtypeStruct((), jnp.int32),
    }
    ids_spec = spec((B, S), jnp.int32, "dp", None)

    def rms(x, w):
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + 1e-5)
        return (y * w).astype(x.dtype)

    def block(p, j, x):
        h = rms(x, p["ln1"][j])
        q = (h @ p["wq"][j].astype(h.dtype)).reshape(*h.shape[:-1], HEADS, HEAD_DIM)
        k = (h @ p["wk"][j].astype(h.dtype)).reshape(*h.shape[:-1], HEADS, HEAD_DIM)
        v = (h @ p["wv"][j].astype(h.dtype)).reshape(*h.shape[:-1], HEADS, HEAD_DIM)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(HEAD_DIM)
        mask = jnp.tril(jnp.ones((h.shape[-2], h.shape[-2]), bool))
        logits = jnp.where(mask, logits.astype(jnp.float32), -1e30)
        a = jax.nn.softmax(logits, -1).astype(h.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", a, v).reshape(h.shape)
        x = x + o @ p["wo"][j].astype(h.dtype)
        h2 = rms(x, p["ln2"][j])
        ff = (jax.nn.silu(h2 @ p["w_gate"][j].astype(h.dtype))
              * (h2 @ p["w_up"][j].astype(h.dtype)))
        return x + ff @ p["w_down"][j].astype(h.dtype)

    def stage_fn(stage_params, x):
        # scan the stage's layers; checkpoint each layer body
        def body(h, j):
            return jax.checkpoint(
                lambda hh: block(stage_params, j, hh))(h), None
        out, _ = jax.lax.scan(body, x, jnp.arange(STAGE_LAYERS))
        return out

    from paddle_tpu.distributed.fleet.tpu_pipeline import pipelined_forward

    def loss_fn(params, ids):
        x = params["embed"].astype(jnp.bfloat16)[ids]  # (B, S, H) bf16
        micro = x.reshape(MICRO, B // MICRO, S, H)
        blocks_nostage = params["blocks"]  # leaves (PP, SL, ...)
        out = pipelined_forward(
            lambda sp, h: stage_fn(sp, h), blocks_nostage, micro, mesh,
            axis="pp", remat=True, batch_axis="dp")
        x = out.reshape(B, S, H)
        x = rms(x, params["norm"])
        logits = (x @ params["head"].astype(x.dtype)).astype(jnp.float32)
        tgt = ids[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1], -1)
        nll = -jnp.take_along_axis(lp, tgt[..., None], -1)
        return jnp.mean(nll)

    def train_step(params, adam, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        t = adam["step"] + 1
        b1, b2, lr, eps = 0.9, 0.95, 1e-4, 1e-8
        new_m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                             adam["m"], grads)
        new_v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g,
                             adam["v"], grads)
        tf = t.astype(jnp.float32)
        new_p = jax.tree.map(
            lambda p, m, v: p - lr * (m / (1 - b1 ** tf))
            / (jnp.sqrt(v / (1 - b2 ** tf)) + eps),
            params, new_m, new_v)
        return new_p, {"m": new_m, "v": new_v, "step": t}, loss

    n_params = (V * H + H + H * V
                + PP * STAGE_LAYERS * (4 * H * H + 3 * H * I + 2 * H))
    print(f"7B config: {n_params/1e9:.2f}B params, mesh dp={DP} mp={MP} "
          f"pp={PP}, {STAGE_LAYERS} scanned layers/stage")

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    lowered = jitted.lower(params_specs, adam_specs, ids_spec)
    print("lowered OK")
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    per_dev_args = ma.argument_size_in_bytes / 1e9
    print(f"compiled OK: per-device args {per_dev_args:.2f}GB, "
          f"temp {ma.temp_size_in_bytes/1e9:.2f}GB, "
          f"output {ma.output_size_in_bytes/1e9:.2f}GB")
    result = {
        "config": "llama2_7b dp2 x mp2 x pp2, scan-layers + remat, "
                  "bf16 compute / fp32 AdamW",
        "params_b": round(n_params / 1e9, 3),
        "lowered": True,
        "compiled": True,
        "per_device_argument_gb": round(per_dev_args, 3),
        "per_device_temp_gb": round(ma.temp_size_in_bytes / 1e9, 3),
        "per_device_output_gb": round(ma.output_size_in_bytes / 1e9, 3),
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "AOT_7B.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", out_path)


if __name__ == "__main__":
    main()
