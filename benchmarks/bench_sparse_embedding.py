"""Sparse (SelectedRows) vs dense embedding gradients at PaddleRec scale.

Vocab 1M x dim 64, batch of 512 lookups per step, SGD. The dense path
materializes a (1M, 64) fp32 gradient (256MB) every step; the sparse path
carries 512 rows (~132KB: fp32 values + int32 row ids). Measures per-step
wall time for both; the gradient byte counts in the JSON are the payload
sizes implied by those layouts.

Run: python benchmarks/bench_sparse_embedding.py   (CPU or chip)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    VOCAB, DIM, BATCH, STEPS = 1_000_000, 64, 512, 20
    rng = np.random.default_rng(0)
    ids_np = rng.integers(0, VOCAB, (STEPS, BATCH), dtype=np.int64)

    rows = {}
    for sparse in (False, True):
        paddle.seed(7)
        emb = nn.Embedding(VOCAB, DIM, sparse=sparse)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=emb.parameters())

        @paddle.jit.to_static
        def step(ids):
            loss = (emb(ids) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        # warm (compile)
        step(paddle.to_tensor(ids_np[0]))
        step(paddle.to_tensor(ids_np[1]))
        t0 = time.perf_counter()
        for i in range(STEPS):
            loss = step(paddle.to_tensor(ids_np[i]))
        np.asarray(loss._data)
        dt = (time.perf_counter() - t0) / STEPS
        rows[sparse] = dt * 1e3
        print(f"sparse={sparse}: {dt * 1e3:.2f} ms/step")

    print(json.dumps({
        "benchmark": "sparse_embedding_grads", "vocab": VOCAB, "dim": DIM,
        "batch": BATCH,
        "dense_ms_per_step": round(rows[False], 2),
        "sparse_ms_per_step": round(rows[True], 2),
        "speedup": round(rows[False] / rows[True], 2),
        "dense_grad_bytes": VOCAB * DIM * 4,
        "sparse_grad_bytes": BATCH * (DIM * 4 + 4),  # fp32 rows + int32 ids
        # (ids enter as int64 but the sparse path stores int32 rows)
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
