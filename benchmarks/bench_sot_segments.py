"""Partial-graph (segment executor) throughput vs full-graph vs eager.

The full_graph=False contract claims a graph break costs "compiled
segments around the break", not a fall to per-op eager. This measures it:
one train step with a tensor-dependent Python branch mid-step, run three
ways on the same model/data:

  full    — full_graph=True with the branch removed (the ceiling)
  segment — full_graph=False with the branch (2 compiled segments/call)
  eager   — plain eager with the branch (the old fallback behavior)

Run: python benchmarks/bench_sot_segments.py   (chip or CPU)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import warnings

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    D, H, LAYERS, BATCH, STEPS = 512, 2048, 4, 256, 30
    rng = np.random.default_rng(0)
    xs = rng.normal(0, 1, (BATCH, D)).astype(np.float32)
    ys = rng.normal(0, 1, (BATCH, D)).astype(np.float32)

    def build():
        paddle.seed(7)
        layers = []
        for _ in range(LAYERS):
            layers += [nn.Linear(D, H), nn.GELU(), nn.Linear(H, D)]
        model = nn.Sequential(*layers)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        return model, opt

    def step_fn(model, opt, with_break):
        def step(x, y):
            loss = ((model(x) - y) ** 2).mean()
            if with_break and float(loss) > 1e9:  # tensor-dependent branch
                loss = loss * 0.5
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        return step

    def time_mode(runner):
        for _ in range(3):  # warm (compile / segment-cache fill)
            runner()
        t0 = time.perf_counter()
        for _ in range(STEPS):
            loss = runner()
        float(loss)  # sync
        return STEPS / (time.perf_counter() - t0)

    x, y = paddle.to_tensor(xs), paddle.to_tensor(ys)
    results = {}

    m, o = build()
    full = paddle.jit.to_static(step_fn(m, o, with_break=False))
    results["full_graph_steps_per_sec"] = time_mode(lambda: full(x, y))

    m, o = build()
    seg = paddle.jit.to_static(step_fn(m, o, with_break=True),
                               full_graph=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        results["segmented_steps_per_sec"] = time_mode(lambda: seg(x, y))

    m, o = build()
    eager = step_fn(m, o, with_break=True)
    results["eager_steps_per_sec"] = time_mode(lambda: eager(x, y))

    results = {k: round(v, 2) for k, v in results.items()}
    results["segment_vs_full"] = round(
        results["segmented_steps_per_sec"]
        / results["full_graph_steps_per_sec"], 3)
    results["segment_vs_eager"] = round(
        results["segmented_steps_per_sec"]
        / results["eager_steps_per_sec"], 2)
    print(json.dumps({"benchmark": "sot_segments",
                      "params": sum(p.size for p in m.parameters()),
                      **results, "device": str(jax.devices()[0])}))


if __name__ == "__main__":
    main()
